#!/usr/bin/env python
"""Fleet report CLI: render a soak record's observatory blocks for humans.

    python -m corda_tpu.loadtest.remote --hosts hosts.conf > soak.json
    python tools/fleet_report.py --current soak.json
    python tools/fleet_report.py --current - --paths 3

Three sections, all read from the record the soak already saved (this
tool never talks to a live rig — post-mortems outlive their processes):

  * the fleet table: one row per node — reachability, health, wedged
    polls, and how many spans / log records / samples it contributed;
  * the device-plane kernel table (when nodes drained /kernels): per
    node+kernel ledger records, padding occupancy, achieved sigs/s,
    and roofline attainment% — tools/kernel_report.py drills deeper;
  * the disruption timeline: fire→heal per catalog kind with mttr_ms,
    detect_ms, the correlated warning+ node events, and the metric rate
    inflections around each window;
  * the top-N stitched cross-node critical paths: per-hop walls down
    the rpc → initiator flow → p2p → responder flow → verifier batch →
    notary commit chain, each hop labelled with the node it ran on.

Exit status: 0 = rendered, 2 = unreadable record — a report tool has
no pass/fail opinion (that's tools/soak_gate.py's job).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable from any cwd without installation
    sys.path.insert(0, _REPO)


def _fmt_ms(value) -> str:
    return f"{value:.1f}ms" if isinstance(value, (int, float)) else "-"


def render(record: dict, paths: int = 5) -> str:
    lines = []
    out = lines.append

    fleet = record.get("fleet") or {}
    nodes = fleet.get("nodes") or {}
    out("== fleet ==")
    if nodes:
        out(f"{'node':<10} {'ok':<4} {'health':<10} {'wedged':>6} "
            f"{'spans':>7} {'logs':>6} {'samples':>8}")
        for name in sorted(nodes):
            st = nodes[name] or {}
            out(f"{name:<10} {str(st.get('ok', '-')):<4} "
                f"{str(st.get('health', '-')):<10} "
                f"{st.get('wedged_polls', 0):>6} "
                f"{st.get('spans', 0):>7} {st.get('log_records', 0):>6} "
                f"{st.get('samples', 0):>8}")
        out(f"polls={fleet.get('polls', 0)} "
            f"wedged_polls={fleet.get('wedged_polls', 0)} "
            f"traces_stitched={fleet.get('traces_stitched', 0)} "
            f"cross_node={fleet.get('cross_node_traces', 0)}")
    else:
        out("(no fleet capture in record)")

    kernel_rows = []
    for name in sorted(nodes):
        st = nodes[name] or {}
        att = st.get("kernel_attainment") or {}
        if not att and not st.get("kernel_records"):
            continue
        if not att:
            kernel_rows.append((name, "-", st.get("kernel_records", 0),
                                None, None, None))
        for kernel in sorted(att):
            e = att[kernel] or {}
            kernel_rows.append((
                name, kernel, st.get("kernel_records", 0),
                e.get("occupancy_pct"), e.get("achieved_sigs_s"),
                e.get("attainment_pct"),
            ))
    if kernel_rows:
        out("")
        out("== device-plane kernels ==")
        out(f"{'node':<10} {'kernel':<34} {'records':>7} "
            f"{'occ%':>6} {'sigs/s':>9} {'attain%':>8}")
        def _n(v, fmt="{:.1f}"):
            return fmt.format(v) if isinstance(v, (int, float)) else "-"
        for name, kernel, recs, occ, sigs, att_pct in kernel_rows:
            out(f"{name:<10} {kernel:<34} {recs:>7} {_n(occ):>6} "
                f"{_n(sigs):>9} {_n(att_pct, '{:.2f}'):>8}")

    out("")
    out("== disruption timeline ==")
    timeline = record.get("timeline") or []
    mttr = record.get("mttr") or {}
    if not timeline:
        out("(no timeline in record)")
    for entry in timeline:
        kind = entry.get("kind", "?")
        if "mttr_ms" not in entry:
            out(f"  t={entry.get('t', entry.get('recovered_t', '-'))} "
                f"{kind}: {entry.get('what', '?')}")
            continue
        out(f"  {kind}: fired t={entry.get('fired_t')}s healed "
            f"t={entry.get('recovered_t')}s "
            f"mttr={_fmt_ms(entry.get('mttr_ms'))} "
            f"detect={_fmt_ms(entry.get('detect_ms'))}")
        for rec in entry.get("node_events") or []:
            out(f"      [{rec.get('node')}] t={rec.get('t')}s "
                f"{rec.get('level')}/{rec.get('component')}: "
                f"{rec.get('message')}")
        for inf in entry.get("metric_inflections") or []:
            out(f"      [{inf.get('node')}] {inf.get('metric')}: "
                f"{inf.get('before_rate')}/s -> "
                f"{inf.get('during_min_rate')}/s")
    if mttr:
        out("  mean per kind: " + "  ".join(
            f"{k}={_fmt_ms(v)}" for k, v in sorted(mttr.items())
        ))

    out("")
    out("== critical paths ==")
    cps = (fleet.get("critical_paths") or [])[: max(0, paths)]
    if not cps:
        out("(no stitched critical paths in record)")
    for cp in cps:
        nodes_s = ",".join(cp.get("nodes") or [])
        flag = "" if cp.get("complete") else "  [incomplete]"
        out(f"  trace {cp.get('trace_id')} wall={_fmt_ms(cp.get('wall_ms'))} "
            f"nodes=[{nodes_s}]{flag}")
        for hop in cp.get("hops") or []:
            out(f"      {hop.get('hop'):<16} {_fmt_ms(hop.get('duration_ms')):>10} "
                f"@+{hop.get('t_offset_ms', 0):.1f}ms  "
                f"{hop.get('name')} on {hop.get('node')}")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fleet_report")
    ap.add_argument(
        "--current", required=True,
        help="soak record to render: a JSON file, or '-' for stdin",
    )
    ap.add_argument(
        "--paths", type=int, default=5,
        help="how many stitched critical paths to show (default 5)",
    )
    args = ap.parse_args(argv)

    try:
        if args.current == "-":
            record = json.load(sys.stdin)
        else:
            with open(args.current) as fh:
                record = json.load(fh)
        if not isinstance(record, dict):
            raise ValueError("not a soak record")
    except (OSError, ValueError) as exc:
        print(f"fleet_report: cannot read record: {exc}", file=sys.stderr)
        return 2

    sys.stdout.write(render(record, paths=args.paths))
    return 0


if __name__ == "__main__":
    sys.exit(main())

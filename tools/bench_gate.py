#!/usr/bin/env python
"""Bench regression gate CLI.

Compares a bench.py record (file or stdin) against the previous round's
`BENCH_r*.json` artifact and exits nonzero on a >20% regression in any
stage timing, or on a broken SLO bound:

    python tools/bench_gate.py --current out.json
    python bench.py | python tools/bench_gate.py --current -
    python tools/bench_gate.py --current out.json --baseline BENCH_r05.json
    python tools/bench_gate.py --current out.json \
        --slo "p99_notarise_ms<=500" --slo "settlement_burst_sigs_s>=100"

Exit status: 0 = pass, 1 = regression / SLO violation, 2 = usage error.
The comparison engine lives in `corda_tpu.loadtest.gate` so the loadtest
harness and tests reuse it without shelling out.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable from any cwd without installation
    sys.path.insert(0, _REPO)

from corda_tpu.loadtest import gate  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="bench_gate")
    ap.add_argument(
        "--current", required=True,
        help="bench record to gate: a JSON file, or '-' for stdin",
    )
    ap.add_argument(
        "--baseline",
        help="previous record (default: newest BENCH_r*.json in --repo)",
    )
    ap.add_argument(
        "--repo", default=_REPO,
        help="directory holding the BENCH_r*.json round artifacts",
    )
    ap.add_argument(
        "--threshold", type=float, default=gate.DEFAULT_THRESHOLD,
        help="tolerated relative regression (default 0.2 = 20%%)",
    )
    ap.add_argument(
        "--slo", action="append", metavar="KEY<=V | KEY>=V",
        help="absolute bound to assert on the current record (repeatable)",
    )
    ap.add_argument(
        "--slo-defaults", action="store_true",
        help="also assert the built-in system-path bounds "
             "(gate.DEFAULT_SLOS: p99 notarise latency, verify throughput)",
    )
    ap.add_argument(
        "--opbudget", action="store_true",
        help="also run the kernel op-budget gate (corda_tpu/ops/"
             "opbudget.py): trace the verify kernels and fail when a "
             "multiply count grew >5%% over the pinned manifest",
    )
    ap.add_argument(
        "--lint", action="store_true",
        help="also run the concurrency lint gate (corda_tpu/analysis): "
             "static passes + kernel-jaxpr lint vs the pinned "
             "analysis_manifest.json (docs/static-analysis.md)",
    )
    args = ap.parse_args(argv)

    try:
        if args.current == "-":
            cur = json.load(sys.stdin)
            if isinstance(cur.get("parsed"), dict):
                cur = cur["parsed"]
        else:
            cur = gate.load_bench_record(args.current)
    except (OSError, ValueError) as exc:
        print(f"bench_gate: cannot read current record: {exc}",
              file=sys.stderr)
        return 2

    prev = None
    baseline_path = None
    if args.baseline:
        try:
            prev = gate.load_bench_record(args.baseline)
            baseline_path = args.baseline
        except (OSError, ValueError) as exc:
            print(f"bench_gate: cannot read baseline: {exc}", file=sys.stderr)
            return 2
    else:
        found = gate.latest_baseline(args.repo)
        if found is not None:
            baseline_path, prev = found

    try:
        slos = gate.parse_slo_args(args.slo)
    except ValueError as exc:
        print(f"bench_gate: {exc}", file=sys.stderr)
        return 2
    if args.slo_defaults:
        slos = {**gate.DEFAULT_SLOS, **slos}

    result = gate.run_gate(cur, prev, threshold=args.threshold,
                           slos=slos or None)
    result["baseline"] = baseline_path
    result["threshold"] = args.threshold

    if args.opbudget:
        from corda_tpu.ops import opbudget

        try:
            violations = opbudget.check_all()
        except OSError as exc:
            print(f"bench_gate: cannot run op-budget gate: {exc}",
                  file=sys.stderr)
            return 2
        # the mesh-wrapped kernel gates against the SAME single-device
        # pin (sharding must not add work); skipped when this process
        # has fewer than 2 devices to build a mesh from
        try:
            violations.extend(opbudget.check_mesh_budget(2))
        except ValueError as exc:
            print(f"bench_gate: mesh op-budget skipped: {exc}",
                  file=sys.stderr)
        result["opbudget_violations"] = violations
        for v in violations:
            if v["kind"] == "improved":
                print(
                    f"OP-BUDGET improved {v['kernel']}.{v['metric']}: "
                    f"{v['pinned']} -> {v['measured']} "
                    f"({v['change'] * 100:+.1f}%) — re-pin the manifest",
                    file=sys.stderr,
                )
            else:
                print(
                    f"OP-BUDGET VIOLATION {v['kernel']}"
                    f".{v.get('metric')}: pinned={v['pinned']} "
                    f"measured={v['measured']} ({v['kind']})",
                    file=sys.stderr,
                )
        if opbudget.fatal_violations(violations):
            result["ok"] = False

    if args.lint:
        from corda_tpu.analysis import check_findings
        from corda_tpu.analysis import kernel_lint, manifest as _lint_manifest

        try:
            lint_result = check_findings()
            lint_kviol = kernel_lint.check_all()
        except (OSError, ValueError) as exc:  # missing OR corrupt manifest
            print(f"bench_gate: cannot run lint gate: {exc}",
                  file=sys.stderr)
            return 2
        result["lint"] = {**lint_result, "kernel_violations": lint_kviol}
        for f in lint_result["new"]:
            print(f"LINT NEW FINDING {f['key']}: {f['path']}:{f['line']} "
                  f"{f['message']}", file=sys.stderr)
        for v in lint_kviol:
            print(f"KERNEL-LINT {v['kind'].upper()} {v['kernel']}"
                  f".{v.get('metric')}: pinned={v['pinned']} "
                  f"measured={v['measured']}", file=sys.stderr)
        if lint_result["new"] or _lint_manifest.fatal_kernel_violations(
            lint_kviol
        ):
            result["ok"] = False

    for m in result.get("fingerprint_mismatch", ()):
        print(
            f"ENV MISMATCH {m['key']}: baseline={m['prev']!r} "
            f"current={m['cur']!r}",
            file=sys.stderr,
        )
    for r in result.get("warnings", ()):
        print(
            f"CROSS-ENV WARNING (not gated) {r['key']}: {r['prev']} -> "
            f"{r['cur']} ({r['change'] * 100:+.1f}% worse, "
            f"{r['direction']}-is-better)",
            file=sys.stderr,
        )
    for r in result["regressions"]:
        print(
            f"REGRESSION {r['key']}: {r['prev']} -> {r['cur']} "
            f"({r['change'] * 100:+.1f}% worse, {r['direction']}-is-better)",
            file=sys.stderr,
        )
    for v in result["slo_violations"]:
        print(
            f"SLO VIOLATION {v['key']}: value={v['value']} "
            f"bound={v['bound']} ({v['kind']})",
            file=sys.stderr,
        )
    if result["ok"]:
        compared = "no baseline found" if prev is None else baseline_path
        print(f"bench_gate: PASS (baseline: {compared})", file=sys.stderr)
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

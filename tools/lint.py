#!/usr/bin/env python
"""Concurrency correctness suite CLI (docs/static-analysis.md).

    python tools/lint.py --baseline     # gate vs analysis_manifest.json
    python tools/lint.py --pin          # re-pin after fixing findings
    python tools/lint.py --list         # dump all findings
    python tools/lint.py path/to/x.py   # findings for one file, no gate
    python tools/lint.py --no-kernel    # static passes only (no jax)

Exit status: 0 = clean, 1 = new finding / kernel-lint violation,
2 = usage error. Thin wrapper over `python -m corda_tpu.analysis` so
the suite runs from any cwd without installation; `bench.py --gate`
wires it in via `tools/bench_gate.py --lint`.
"""
from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable from any cwd without installation
    sys.path.insert(0, _REPO)

from corda_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Kernel report CLI: render the device-plane kernel ledger for humans.

    python bench.py --json > bench.json
    python tools/kernel_report.py --current bench.json

    curl -s localhost:9999/kernels > kernels.json
    python tools/kernel_report.py --current kernels.json
    python tools/kernel_report.py --current - < kernels.json

Accepts either shape and renders the same sections:

  * a bench record — attainment lives at stage_timings.kernel_attainment
    (what bench.py computes from the in-process ledger after its run);
  * a saved `GET /kernels` page — attainment/cost/compile_events ride at
    the top level next to the raw per-dispatch records.

Sections: the per-kernel attainment table (dispatches, padded vs REAL
rows, padding occupancy, achieved sigs/s vs the per-backend peak,
flops/row from the XLA cost model, attainment%), the cached cost model
per shape bucket, compile events, and — when the record carries raw
ledger rows — the most recent dispatches with their provenance stamp.

Exit status: 0 = rendered, 2 = unreadable record — a report tool has
no pass/fail opinion (that's bench.py --gate / tools/bench_gate.py's
job, which already understands `_attainment_pct` as higher-is-better).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable from any cwd without installation
    sys.path.insert(0, _REPO)


def _num(value, fmt: str = "{:.1f}") -> str:
    return fmt.format(value) if isinstance(value, (int, float)) else "-"


def _views(record: dict) -> dict:
    """Normalise the two accepted shapes into one view dict."""
    stage = record.get("stage_timings") or {}
    if "kernel_attainment" in stage or "kernel_attainment" in record:
        # bench record: attainment computed by bench.py after its run
        return {
            "attainment": stage.get("kernel_attainment")
            or record.get("kernel_attainment") or {},
            "cost": record.get("kernel_cost") or {},
            "compile_events": record.get("kernel_compile_events") or [],
            "records": record.get("kernel_records") or [],
            "backend": record.get("backend"),
            "enabled": True,
        }
    # /kernels page: everything rides at the top level
    return {
        "attainment": record.get("attainment") or {},
        "cost": record.get("cost") or {},
        "compile_events": record.get("compile_events") or [],
        "records": record.get("records") or [],
        "backend": record.get("backend"),
        "enabled": record.get("enabled", True),
    }


def render(record: dict, tail: int = 8) -> str:
    lines = []
    out = lines.append
    v = _views(record)

    out("== kernel attainment ==")
    if not v["enabled"]:
        out("(kernel ledger disabled — CORDA_TPU_KERNEL_LEDGER=0)")
    att = v["attainment"]
    if att:
        out(f"{'kernel':<34} {'disp':>5} {'rows':>8} {'real':>8} "
            f"{'occ%':>6} {'mean ms':>8} {'sigs/s':>9} "
            f"{'flops/row':>10} {'attain%':>8}")
        for kernel in sorted(att):
            e = att[kernel] or {}
            disp = e.get("dispatches") or 0
            wall = e.get("wall_s")
            mean_ms = (1000.0 * wall / disp) \
                if isinstance(wall, (int, float)) and disp else None
            out(f"{kernel:<34} {disp:>5} {e.get('rows', 0):>8} "
                f"{e.get('real_rows', 0):>8} "
                f"{_num(e.get('occupancy_pct')):>6} "
                f"{_num(mean_ms, '{:.2f}'):>8} "
                f"{_num(e.get('achieved_sigs_s')):>9} "
                f"{_num(e.get('flops_per_row')):>10} "
                f"{_num(e.get('attainment_pct'), '{:.2f}'):>8}")
        first = next(iter(att.values())) or {}
        out(f"backend={v['backend'] or first.get('backend', '-')} "
            f"peak_sigs_s={_num(first.get('peak_sigs_s'), '{:.0f}')}")
    else:
        out("(no measured dispatches — attainment is MEASURED, "
            "never assumed)")

    cost = v["cost"]
    if cost:
        out("")
        out("== xla cost model (per shape bucket) ==")
        out(f"{'kernel':<34} {'bucket':>8} {'rows':>8} "
            f"{'flops':>14} {'bytes':>12} {'flops/row':>10}")
        for kernel in sorted(cost):
            for bucket in sorted(cost[kernel]):
                e = cost[kernel][bucket] or {}
                out(f"{kernel:<34} {bucket:>8} {e.get('rows', 0):>8} "
                    f"{_num(e.get('flops'), '{:.0f}'):>14} "
                    f"{_num(e.get('bytes_accessed'), '{:.0f}'):>12} "
                    f"{_num(e.get('flops_per_row')):>10}")

    events = v["compile_events"]
    if events:
        out("")
        out("== compile events ==")
        for e in events:
            dur = e.get("seconds")
            dur_s = f" {dur * 1000.0:.1f}ms" \
                if isinstance(dur, (int, float)) else ""
            out(f"  #{e.get('seq')} {e.get('name')}"
                f"[{e.get('bucket', '-')}]{dur_s}")

    recs = v["records"]
    if recs:
        out("")
        out(f"== last {min(tail, len(recs))} of {len(recs)} "
            f"ledger records ==")
        for r in recs[-max(0, tail):]:
            prov = r.get("provenance")
            prov_s = f" prov={json.dumps(prov, sort_keys=True)}" \
                if prov else ""
            out(f"  #{r.get('seq')} {r.get('kernel')} "
                f"scheme={r.get('scheme')} bucket={r.get('bucket')} "
                f"rows={r.get('rows')} real={r.get('real_rows')} "
                f"occ={_num(r.get('occupancy_pct'))}% "
                f"wall={_num((r.get('wall_s') or 0) * 1000.0, '{:.2f}')}ms "
                f"donated={r.get('donated')} mesh_n={r.get('mesh_n')} "
                f"stage={r.get('stage')}{prov_s}")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kernel_report")
    ap.add_argument(
        "--current", required=True,
        help="record to render: a bench JSON / saved /kernels page, "
             "or '-' for stdin",
    )
    ap.add_argument(
        "--tail", type=int, default=8,
        help="how many raw ledger records to show (default 8)",
    )
    args = ap.parse_args(argv)

    try:
        if args.current == "-":
            record = json.load(sys.stdin)
        else:
            with open(args.current) as fh:
                record = json.load(fh)
        if not isinstance(record, dict):
            raise ValueError("not a kernel record")
    except (OSError, ValueError) as exc:
        print(f"kernel_report: cannot read record: {exc}", file=sys.stderr)
        return 2

    sys.stdout.write(render(record, tail=args.tail))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Sweep the ed25519 Pallas kernel's tuning knobs on real TPU hardware.

Runs `bench.py` in a fresh subprocess per configuration (the knobs are
read at import time) and reports each JSON line plus the best config.

Usage (on a machine with the TPU tunnel up):
    python tools/tune_kernel.py [--blks 256,512,1024] [--chunks 65536,131072]
"""
import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_one(blk: int, chunk: int, timeout: float, ecdsa_blk: int = 0,
            fast_mul: bool = True) -> dict:
    env = dict(os.environ)
    env["CORDA_TPU_ED25519_BLK"] = str(blk)
    env["CORDA_TPU_PIPE_CHUNK"] = str(chunk)
    env["CORDA_TPU_FAST_MUL"] = "1" if fast_mul else "0"
    if ecdsa_blk:
        env["CORDA_TPU_ECDSA_BLK"] = str(ecdsa_blk)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            env=env, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return {"blk": blk, "chunk": chunk, "error": "timeout"}
    line = next(
        (ln for ln in out.stdout.splitlines() if ln.startswith("{")), None
    )
    if line is None:
        return {
            "blk": blk, "chunk": chunk,
            "error": (out.stderr or out.stdout)[-400:],
        }
    rec = json.loads(line)
    rec.update(blk=blk, chunk=chunk)
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--blks", default="256,512,1024")
    ap.add_argument("--chunks", default="65536,131072")
    ap.add_argument("--timeout", type=float, default=1800)
    ap.add_argument(
        "--ab-fast-mul", action="store_true",
        help="run each config with CORDA_TPU_FAST_MUL on AND off "
        "(the Mosaic live-row accumulation A/B, docs/perf-roofline.md)",
    )
    args = ap.parse_args()

    results = []
    fast_opts = (True, False) if args.ab_fast_mul else (True,)
    for blk in (int(b) for b in args.blks.split(",")):
        for chunk in (int(c) for c in args.chunks.split(",")):
            for fast in fast_opts:
                rec = run_one(blk, chunk, args.timeout, fast_mul=fast)
                rec["fast_mul"] = fast
                print(json.dumps(rec), flush=True)
                results.append(rec)
    ok = [r for r in results if "value" in r]
    if ok:
        best = max(ok, key=lambda r: r["value"])
        print(
            f"# best: BLK={best['blk']} CHUNK={best['chunk']} "
            f"fast_mul={best['fast_mul']} "
            f"-> {best['value']:,.0f} sigs/s (vs_baseline {best['vs_baseline']})"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())

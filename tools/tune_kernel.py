#!/usr/bin/env python
"""Sweep the ed25519 Pallas kernel's tuning knobs on real TPU hardware.

Runs `bench.py` in a fresh subprocess per configuration (the knobs are
read at import time) and reports each JSON line plus the best config.

Usage (on a machine with the TPU tunnel up):
    python tools/tune_kernel.py [--blks 256,512,1024] [--chunks 65536,131072]
"""
import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_one(blk: int, chunk: int, timeout: float, ecdsa_blk: int = 0,
            radix: int = 13) -> dict:
    env = dict(os.environ)
    env["CORDA_TPU_ED25519_BLK"] = str(blk)
    env["CORDA_TPU_PIPE_CHUNK"] = str(chunk)
    env["CORDA_TPU_ED25519_RADIX"] = str(radix)
    env["CORDA_TPU_FAST_MUL"] = "0"  # cannot lower on current Mosaic
    if ecdsa_blk:
        env["CORDA_TPU_ECDSA_BLK"] = str(ecdsa_blk)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            env=env, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return {"blk": blk, "chunk": chunk, "error": "timeout"}
    line = next(
        (ln for ln in out.stdout.splitlines() if ln.startswith("{")), None
    )
    if line is None:
        return {
            "blk": blk, "chunk": chunk,
            "error": (out.stderr or out.stdout)[-400:],
        }
    rec = json.loads(line)
    rec.update(blk=blk, chunk=chunk)
    return rec


def run_bls(blk: int, timeout: float) -> dict:
    """One pairing-batch-size config: the bls12_batch microbench in a
    fresh subprocess (BLK is read at import)."""
    env = dict(os.environ)
    env["CORDA_TPU_BLS12_BLK"] = str(blk)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    try:
        out = subprocess.run(
            [sys.executable, "-m", "corda_tpu.ops.bls12_batch",
             "--bench", "--blk", str(blk)],
            env=env, capture_output=True, text=True, timeout=timeout,
            cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return {"blk": blk, "error": "timeout"}
    line = next(
        (ln for ln in out.stdout.splitlines() if ln.startswith("{")), None
    )
    if line is None:
        return {"blk": blk, "error": (out.stderr or out.stdout)[-400:]}
    return json.loads(line)


def run_mesh(n: int, rows: int, timeout: float) -> dict:
    """One mesh scaling point: the parallel.mesh microbench in a fresh
    subprocess (the forced host device count binds at CPU backend init,
    so every N needs its own process; n=0 = the single-device
    comparator, exactly CORDA_TPU_MESH_DEVICES=0)."""
    import re

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["CORDA_TPU_MESH_DEVICES"] = str(n)
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", ""),
    ).strip()
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={max(n, 1)}"
    ).strip()
    env.setdefault("JAX_PLATFORMS", "cpu")
    try:
        out = subprocess.run(
            [sys.executable, "-m", "corda_tpu.parallel.mesh", "--bench",
             "--devices", str(n), "--rows", str(rows), "--repeats", "2"],
            env=env, capture_output=True, text=True, timeout=timeout,
            cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return {"n_devices": n, "error": "timeout"}
    line = next(
        (ln for ln in out.stdout.splitlines() if ln.startswith("{")), None
    )
    if line is None:
        return {"n_devices": n, "error": (out.stderr or out.stdout)[-400:]}
    return json.loads(line)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--blks", default="256,512,1024")
    ap.add_argument("--chunks", default="65536,131072")
    ap.add_argument("--timeout", type=float, default=1800)
    ap.add_argument(
        "--radixes", default="13,16",
        help="limb radixes to A/B (13 = default dense radix-2^13 field; "
        "16 = the round-2-measured radix-2^16 field). Fast-mul is always "
        "off: its scatter-add cannot lower on current Mosaic "
        "(docs/perf-roofline.md).",
    )
    ap.add_argument(
        "--bls-blks", default="",
        help="comma-separated BLS12-381 pairing batch sizes to sweep "
        "(CORDA_TPU_BLS12_BLK; e.g. 4,8,16,32). When given, the sweep "
        "runs the bls12_batch aggregate-verify microbench INSTEAD of "
        "the ed25519 bench matrix.",
    )
    ap.add_argument(
        "--mesh-ns", default="",
        help="comma-separated mesh widths to sweep (e.g. 1,2,4,8; 0 is "
        "always prepended as the single-device comparator). Runs the "
        "corda_tpu.parallel.mesh scaling microbench INSTEAD of the "
        "ed25519 bench matrix, one virtual-device subprocess per point "
        "(docs/perf-pipeline.md).",
    )
    ap.add_argument(
        "--mesh-rows", type=int, default=256,
        help="batch size per mesh scaling point (--mesh-ns)",
    )
    args = ap.parse_args()

    if args.mesh_ns:
        ns = [int(n) for n in args.mesh_ns.split(",")]
        if 0 not in ns:
            ns = [0] + ns  # the all-off comparator anchors the curve
        results = []
        for n in ns:
            rec = run_mesh(n, args.mesh_rows, args.timeout)
            print(json.dumps(rec), flush=True)
            results.append(rec)
        ok = [r for r in results if "sigs_s" in r]
        if ok:
            base = next(
                (r for r in ok if r["n_devices"] == 0), None
            )
            best = max(ok, key=lambda r: r["sigs_s"])
            vs = (
                f" ({best['sigs_s'] / base['sigs_s']:.2f}x the n=0 "
                "single-device comparator)"
                if base and base["sigs_s"] else ""
            )
            print(
                f"# best: n={best['n_devices']} -> "
                f"{best['sigs_s']:,.1f} sigs/s{vs}"
            )
        return 0

    if args.bls_blks:
        results = []
        for blk in (int(b) for b in args.bls_blks.split(",")):
            rec = run_bls(blk, args.timeout)
            print(json.dumps(rec), flush=True)
            results.append(rec)
        ok = [r for r in results if "value" in r]
        if ok:
            best = max(ok, key=lambda r: r["value"])
            print(
                f"# best: BLS12_BLK={best['blk']} -> "
                f"{best['value']:,.1f} aggregate-verify rows/s "
                f"({best['row_ms']} ms/row)"
            )
        return 0

    results = []
    for blk in (int(b) for b in args.blks.split(",")):
        for chunk in (int(c) for c in args.chunks.split(",")):
            for radix in (int(r) for r in args.radixes.split(",")):
                rec = run_one(blk, chunk, args.timeout, radix=radix)
                rec["radix"] = radix
                print(json.dumps(rec), flush=True)
                results.append(rec)
    ok = [r for r in results if "value" in r]
    if ok:
        best = max(ok, key=lambda r: r["value"])
        print(
            f"# best: BLK={best['blk']} CHUNK={best['chunk']} "
            f"radix={best['radix']} "
            f"-> {best['value']:,.0f} sigs/s (vs_baseline {best['vs_baseline']})"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())

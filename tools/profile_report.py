#!/usr/bin/env python
"""Render a saved sampling-profiler capture as a per-thread text report.

Input: the JSON body of `GET /profile?seconds=N` (ops endpoint) or
`node_profile()` (RPC), saved to a file — or `-` for stdin:

    curl "127.0.0.1:9100/profile?seconds=5" > cap.json
    python tools/profile_report.py cap.json
    python tools/profile_report.py cap.json --top 30
    python tools/profile_report.py cap.json --collapsed out.folded
        # out.folded feeds flamegraph.pl / speedscope directly

The report has three sections: the capture metadata (window, tick
count, total CPU burn vs wall — on a 1-core GIL-bound node the ratio
IS the ceiling), the per-thread table (CPU-share + runnable-vs-waiting
sample split: many runnable threads sharing one core's worth of CPU
seconds is the GIL-convoy signature docs/perf-system.md tracks), and
the top-N hottest sampled stacks.
"""
from __future__ import annotations

import argparse
import json
import sys


def _fmt_share(value) -> str:
    return f"{value * 100:5.1f}%" if isinstance(value, (int, float)) else "    -"


def render(capture: dict, top: int = 20) -> str:
    meta = capture.get("meta", {})
    threads = capture.get("threads", [])
    collapsed = capture.get("collapsed", {})
    out = []
    wall = meta.get("wall_s", 0)
    total_cpu = meta.get("total_cpu_s", 0)
    out.append(
        f"capture: {meta.get('ticks', '?')} ticks over {wall}s wall "
        f"(interval {meta.get('interval_s', '?')}s), "
        f"{meta.get('n_threads', len(threads))} threads, "
        f"quiesced={meta.get('quiesced')}"
    )
    if wall:
        out.append(
            f"process CPU: {total_cpu}s over {wall}s wall "
            f"({total_cpu / wall:.2f} cores) + sampler self-cost "
            f"{meta.get('profiler_cpu_s', 0)}s"
        )
    out.append("")
    out.append(
        f"{'thread':<32} {'samples':>7} {'run':>5} {'wait':>5} "
        f"{'cpu_s':>8} {'share':>6}  top frame"
    )
    for row in threads:
        top_frames = row.get("top_frames") or []
        leaf = top_frames[0][0] if top_frames else "-"
        name = row.get("name", "?")
        if row.get("sampler"):
            name += " [sampler]"
        cpu = row.get("cpu_s")
        out.append(
            f"{name:<32.32} {row.get('samples', 0):>7} "
            f"{row.get('running', 0):>5} {row.get('waiting', 0):>5} "
            f"{cpu if cpu is not None else '-':>8} "
            f"{_fmt_share(row.get('cpu_share'))}  {leaf}"
        )
    out.append("")
    total_samples = sum(collapsed.values()) or 1
    out.append(f"top {min(top, len(collapsed))} sampled stacks:")
    ranked = sorted(collapsed.items(), key=lambda kv: -kv[1])[:top]
    for stack, count in ranked:
        frames = stack.split(";")
        head = frames[0]
        tail = ";".join(frames[-3:]) if len(frames) > 3 else stack
        out.append(
            f"  {count:>6} ({count / total_samples * 100:4.1f}%) "
            f"[{head}] …{tail}"
        )
    return "\n".join(out) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="profile_report")
    ap.add_argument("capture", help="saved /profile JSON, or '-' for stdin")
    ap.add_argument("--top", type=int, default=20,
                    help="stacks to show (default 20)")
    ap.add_argument("--collapsed", metavar="PATH",
                    help="also write flamegraph.pl-format collapsed "
                         "stacks ('stack count' lines) to PATH")
    args = ap.parse_args(argv)
    try:
        if args.capture == "-":
            capture = json.load(sys.stdin)
        else:
            with open(args.capture) as fh:
                capture = json.load(fh)
    except (OSError, ValueError) as exc:
        print(f"profile_report: cannot read capture: {exc}",
              file=sys.stderr)
        return 2
    if not isinstance(capture, dict) or "collapsed" not in capture:
        print("profile_report: not a /profile capture "
              "(expected keys: meta, collapsed, threads)", file=sys.stderr)
        return 2
    sys.stdout.write(render(capture, top=args.top))
    if args.collapsed:
        with open(args.collapsed, "w") as fh:
            for stack, count in capture["collapsed"].items():
                fh.write(f"{stack} {count}\n")
        print(f"collapsed stacks -> {args.collapsed}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""crashmc — exhaustive crash-point recovery matrix (docs/robustness.md §7).

Enumerates every registered durability barrier
(`corda_tpu.utils.faultpoints.CRASH_POINTS`) and, for each point x each
seed, runs that store's workload with a seeded "crash" fault armed at
the point, simulates the power cut (testing/crashstore.py: vanished
unsynced writes, torn pages, reordered blocks; sqlite via a live
crash-image snapshot with a torn WAL tail), recovers cold, and asserts
the single recovery invariant checker (`node/recovery.verify_node_state`
composed per store): no lost durably-acked message, no half-consumed
state ref, every journaled 2PC round fully re-driven or fully released,
checkpoint store parseable with corrupt trailing records quarantined —
never a wedged startup.

    python tools/crashmc.py                  # the full matrix
    python tools/crashmc.py --list           # enumerate points/stores
    python tools/crashmc.py --points 'journal.*' --seeds 5
    python tools/crashmc.py --stores checkpoints,vault
    python tools/crashmc.py --break-recovery broker_journal   # must go RED

Exit 0 = every cell clean, coverage floor met (>=25 points across >=5
stores) AND at least one demonstrably-injected torn write per store;
exit 1 otherwise. `--break-recovery STORE` deliberately sabotages that
store's recovery path — the matrix MUST fail then (pinned by
tests/test_crashplane.py), proving the matrix can catch a real
regression, not just bless whatever recovery does.
"""
from __future__ import annotations

import argparse
import contextlib
import fnmatch
import hashlib
import os
import random
import shutil
import sys
import tempfile
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from corda_tpu.testing import crashstore, faults  # noqa: E402
from corda_tpu.utils import faultpoints  # noqa: E402

#: acceptance floor (ISSUE 20): the registry must stay at least this wide
MIN_POINTS = 25
MIN_STORES = 5


def _import_stores() -> None:
    """Crash points register at module import; pull in every durable
    store so CRASH_POINTS is the complete registry."""
    import corda_tpu.messaging.broker  # noqa: F401
    import corda_tpu.node.database  # noqa: F401
    import corda_tpu.node.notary  # noqa: F401
    import corda_tpu.node.notary_change  # noqa: F401
    import corda_tpu.node.services  # noqa: F401
    import corda_tpu.node.sharded_notary  # noqa: F401
    import corda_tpu.utils.atomicfile  # noqa: F401


def _crash_errors() -> tuple:
    from corda_tpu.node.notary_change import NotaryChangeCrashError
    from corda_tpu.node.sharded_notary import CoordinatorCrashError

    return (faultpoints.InjectedCrashError, CoordinatorCrashError,
            NotaryChangeCrashError)


@contextlib.contextmanager
def _env(**overrides):
    prev = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    try:
        yield
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


class _Party:
    name = "O=CrashMc,L=Testland,C=ZZ"


def _tx_id(tag: str):
    from corda_tpu.core.crypto.secure_hash import SecureHash

    return SecureHash(hashlib.sha256(tag.encode()).digest())


def _ref_on_shard(shard: int, n_shards: int, tag: str):
    from corda_tpu.core.contracts.structures import StateRef
    from corda_tpu.core.crypto.secure_hash import SecureHash
    from corda_tpu.node.sharded_notary import shard_of_key

    for nonce in range(100_000):
        h = hashlib.sha256(f"{tag}-{nonce}".encode()).digest()
        if shard_of_key(h + (0).to_bytes(4, "big"), n_shards) == shard:
            return StateRef(SecureHash(h), 0)
    raise AssertionError("no nonce found")


# ---------------------------------------------------------------------------
# per-store scenarios: each runs the workload with `point` armed to crash,
# power-cuts, recovers, and returns {"problems": [...], "crashed": bool,
# "torn": bool}
# ---------------------------------------------------------------------------

def _scn_atomic_file(point: str, seed: int, wd: str) -> dict:
    import json

    from corda_tpu.utils import atomicfile

    target = os.path.join(wd, "state.json")
    atomicfile.write_json_atomic(target, {"v": 0})  # durable baseline
    disk = crashstore.CrashDisk(rng=random.Random(seed))
    with crashstore.interpose(disk):
        with faults.inject(seed=seed) as fi:
            rule = fi.rule(point, "crash", times=1)
            try:
                for v in range(1, 4):
                    atomicfile.write_json_atomic(
                        target, {"v": v, "pad": "x" * 2048}
                    )
            except _crash_errors():
                pass
        # a deliberately UNSYNCED multi-page decoy, written AFTER the
        # injected crash (hook disarmed): power_cut tears it, which is
        # this store's injected-torn-write evidence
        decoy = bytes(
            random.Random(seed + 1).getrandbits(8) for _ in range(4096)
        )
        atomicfile.write_atomic(
            os.path.join(wd, "decoy.bin"), decoy, fsync=False
        )
        stats = disk.power_cut()
    problems: List[str] = []
    if not rule.fired:
        problems.append(f"{point}: crash seam never fired in workload")
    if not os.path.exists(target):
        problems.append("durably-written atomic target vanished")
    else:
        try:
            with open(target) as fh:
                obj = json.load(fh)
            if obj.get("v") not in (0, 1, 2, 3):
                problems.append(f"impossible version {obj!r}")
        except Exception as exc:
            problems.append(
                f"atomic target visibly torn after power cut: {exc}"
            )
    return {
        "problems": problems, "crashed": bool(rule.fired),
        "torn": any(s["torn"] for s in stats.values()),
    }


def _scn_broker(point: str, seed: int, wd: str) -> dict:
    from corda_tpu.messaging.broker import Message, _Journal
    from corda_tpu.node import recovery

    jdir = os.path.join(wd, "journal")
    os.makedirs(jdir, exist_ok=True)
    jp = os.path.join(jdir, "q.journal")
    sent, acked, durable = set(), set(), set()
    disk = crashstore.CrashDisk(rng=random.Random(seed))
    with _env(CORDA_TPU_JOURNAL_FSYNC="1"):
        with crashstore.interpose(disk):
            j = _Journal(jp)
            with faults.inject(seed=seed) as fi:
                rule = fi.rule(point, "crash", times=1)
                try:
                    msgs = []
                    for i in range(30):
                        m = Message(
                            payload=(b"pay-%04d" % i) * 24,
                            headers={"n": str(i)},
                            message_id=str(uuid.uuid4()),
                        )
                        j.append_enqueue(m)
                        # only counted AFTER the fsync'd append returned
                        msgs.append(m)
                        sent.add(m.message_id)
                        durable.add(m.message_id)
                    for m in msgs[:10]:
                        j.append_ack(m.message_id)
                        acked.add(m.message_id)
                    j.compact(msgs[10:])
                except _crash_errors():
                    pass
            try:
                j.close()
            # lint: allow(swallow) — close after an injected crash may
            except Exception:  # fail; power_cut is the real ending
                pass
            stats = disk.power_cut()
    problems: List[str] = []
    if not rule.fired:
        problems.append(f"{point}: crash seam never fired in workload")
    problems += recovery.verify_broker_journal(
        jdir, sent=sent, acked=acked, durable_sent=durable
    )
    torn = any(
        s["torn"] or s["dropped_pages"] for s in stats.values()
    )
    return {"problems": problems, "crashed": bool(rule.fired),
            "torn": torn}


def _scn_checkpoints(point: str, seed: int, wd: str) -> dict:
    from corda_tpu.core.serialization.codec import serialize
    from corda_tpu.node import recovery
    from corda_tpu.node.database import CheckpointStorage, NodeDatabase

    dbp = os.path.join(wd, "node.db")
    db = NodeDatabase(dbp)
    store = CheckpointStorage(db)
    if "group_commit" in point:
        store.enable_group_commit()
    disk = crashstore.CrashDisk(rng=random.Random(seed))
    disk.sqlite_paths.append(dbp)
    written: Dict[str, int] = {}
    with faults.inject(seed=seed) as fi:
        rule = fi.rule(point, "crash", times=1)
        try:
            for i in range(12):
                fid = f"flow-{i}"
                if i % 3 == 2:
                    store.put_incremental(
                        fid,
                        serialize({"flow_name": f"F{i}", "args": i}),
                        [(0, serialize({"io": i}))],
                        serialize({"sessions": i}),
                    )
                else:
                    store.put(
                        fid, serialize({"flow_name": f"F{i}", "step": i})
                    )
                written[fid] = i
            for i in (0, 3):
                store.remove(f"flow-{i}")
        except _crash_errors():
            pass
    # the crash image: live snapshot + torn WAL tail, like the plug
    snap = disk.snapshot_sqlite(os.path.join(wd, "crashimg"))
    torn = bool(disk.tear_sqlite_wal(snap.values()))
    db.close()
    problems: List[str] = []
    if not rule.fired:
        problems.append(f"{point}: crash seam never fired in workload")
    db2 = NodeDatabase(snap[dbp])
    store2 = CheckpointStorage(db2)
    problems += recovery.verify_checkpoints(store2)
    for fid, _blob in store2.all_checkpoints():
        if fid not in written:
            problems.append(f"ghost checkpoint {fid} after recovery")
    db2.close()
    return {"problems": problems, "crashed": bool(rule.fired),
            "torn": torn}


#: the vault/notary-change scenarios need real transactions: a minimal
#: registered contract + state (mirrors the tier-1 federation tests)
_CONTRACT_READY = False


def _ensure_contract() -> None:
    global _CONTRACT_READY
    if _CONTRACT_READY:
        return
    from dataclasses import dataclass as _dc

    from corda_tpu.core.contracts import (
        Contract,
        ContractState,
        TypeOnlyCommandData,
        contract,
    )
    from corda_tpu.core.serialization.codec import corda_serializable

    @corda_serializable
    @_dc(frozen=True)
    class CrashMcState(ContractState):
        parties: tuple = ()
        tag: int = 0
        contract_name = "CrashMcContract"

        @property
        def participants(self) -> List:
            return list(self.parties)

    @corda_serializable
    @_dc(frozen=True)
    class CrashMcCommand(TypeOnlyCommandData):
        pass

    @contract(name="CrashMcContract")
    class CrashMcContract(Contract):
        def verify(self, tx) -> None:
            pass

    globals()["CrashMcState"] = CrashMcState
    globals()["CrashMcCommand"] = CrashMcCommand
    _CONTRACT_READY = True


def _issue(node, notary, tag: int):
    from corda_tpu.core.transactions import TransactionBuilder

    builder = TransactionBuilder(notary=notary.info)
    builder.add_output_state(
        CrashMcState(parties=(node.info,), tag=tag)  # noqa: F821
    )
    builder.add_command(CrashMcCommand(), node.info.owning_key)  # noqa: F821
    stx = node.services.sign_initial_transaction(builder)
    node.services.record_transactions([stx])
    return stx.tx.out_ref(0)


def _scn_vault(point: str, seed: int, wd: str) -> dict:
    from corda_tpu.core.transactions import TransactionBuilder
    from corda_tpu.node import recovery
    from corda_tpu.node.database import NodeDatabase
    from corda_tpu.testing.mocknetwork import MockNetwork

    _ensure_contract()
    dbp = os.path.join(wd, "alice.db")
    net = MockNetwork()
    disk = crashstore.CrashDisk(rng=random.Random(seed))
    disk.sqlite_paths.append(dbp)
    try:
        notary = net.create_notary_node()
        alice = net.create_node("O=Alice,L=London,C=GB", db_path=dbp)
        refs = []
        with faults.inject(seed=seed) as fi:
            rule = fi.rule(point, "crash", times=1)
            try:
                for i in range(6):
                    refs.append(_issue(alice, notary, i))
                if point.startswith("vault.mark_notary_consumed"):
                    alice.services.vault_service.mark_notary_consumed(
                        [r.ref for r in refs[:2]]
                    )
                else:
                    # a consuming ingest: inputs consume + outputs land
                    # in ONE notify batch — the torn-ingest window
                    builder = TransactionBuilder(notary=notary.info)
                    builder.add_input_state(refs[0])
                    builder.add_output_state(
                        CrashMcState(  # noqa: F821
                            parties=(alice.info,), tag=99
                        )
                    )
                    builder.add_command(
                        CrashMcCommand(),  # noqa: F821
                        alice.info.owning_key,
                    )
                    stx = alice.services.sign_initial_transaction(builder)
                    alice.services.record_transactions([stx])
            except _crash_errors():
                pass
        snap = disk.snapshot_sqlite(os.path.join(wd, "crashimg"))
        torn = bool(disk.tear_sqlite_wal(snap.values()))
    finally:
        net.stop_nodes()
    problems: List[str] = []
    if not rule.fired:
        problems.append(f"{point}: crash seam never fired in workload")
    db2 = NodeDatabase(snap[dbp])
    # cold-start recovery re-runs the vault's idempotent DDL first (a
    # torn WAL may have taken the schema with it), like a real boot
    from corda_tpu.node.services import VaultService

    VaultService(db2, lambda *a: True)
    problems += recovery.verify_vault(db2)
    db2.close()
    return {"problems": problems, "crashed": bool(rule.fired),
            "torn": torn}


def _scn_sharded(point: str, seed: int, wd: str) -> dict:
    from corda_tpu.node import recovery
    from corda_tpu.node.database import NodeDatabase
    from corda_tpu.node.notary import UniquenessException
    from corda_tpu.node.sharded_notary import ShardedUniquenessProvider

    dbp = os.path.join(wd, "shard.db")
    db = NodeDatabase(dbp)
    p = ShardedUniquenessProvider.over_database(db, 4)
    disk = crashstore.CrashDisk(rng=random.Random(seed))
    disk.sqlite_paths.append(dbp)
    committed: Dict[bytes, str] = {}

    def key_of(ref):
        return ref.txhash.bytes + ref.index.to_bytes(4, "big")

    with faults.inject(seed=seed) as fi:
        rule = fi.rule(point, "crash", times=1)
        try:
            for i in range(3):
                ref = _ref_on_shard(i % 4, 4, tag=f"s{seed}-{i}")
                tx = _tx_id(f"single-{seed}-{i}")
                p.commit([ref], tx, _Party())
                committed[key_of(ref)] = tx.bytes.hex()
            a = _ref_on_shard(0, 4, tag=f"xa{seed}")
            b = _ref_on_shard(2, 4, tag=f"xb{seed}")
            tx = _tx_id(f"cross-{seed}")
            p.commit([a, b], tx, _Party())
            committed[key_of(a)] = tx.bytes.hex()
            committed[key_of(b)] = tx.bytes.hex()
        except _crash_errors():
            pass
    snap = disk.snapshot_sqlite(os.path.join(wd, "crashimg"))
    torn = bool(disk.tear_sqlite_wal(snap.values()))
    db.close()
    problems: List[str] = []
    if not rule.fired:
        problems.append(f"{point}: crash seam never fired in workload")
    db2 = NodeDatabase(snap[dbp])
    p2 = ShardedUniquenessProvider.over_database(db2, 4)  # auto-recovers
    problems += recovery.verify_sharded_journal(p2)
    problems += recovery.verify_consumption(p2.delegates, committed)
    # liveness probe: a fresh commit must land (no wedged lock)
    try:
        p2.commit(
            [_ref_on_shard(1, 4, tag=f"probe{seed}")],
            _tx_id(f"probe-{seed}"), _Party(),
        )
    except UniquenessException:
        pass  # a conflict verdict is a healthy answer too
    except Exception as exc:
        problems.append(
            f"post-recovery commit wedged: {type(exc).__name__}: {exc}"
        )
    db2.close()
    return {"problems": problems, "crashed": bool(rule.fired),
            "torn": torn}


def _scn_notary_change(point: str, seed: int, wd: str) -> dict:
    from corda_tpu.core.flows import NotaryChangeFlow
    from corda_tpu.node import recovery
    from corda_tpu.node.database import NodeDatabase
    from corda_tpu.node.notary_change import (
        JOURNAL_TABLE,
        NotaryChangeRecoveryFlow,
        change_journal,
    )
    from corda_tpu.node.sharded_notary import PrepareJournal
    from corda_tpu.testing.mocknetwork import MockNetwork

    _ensure_contract()
    dbp = os.path.join(wd, "alice.db")
    net = MockNetwork()
    disk = crashstore.CrashDisk(rng=random.Random(seed))
    disk.sqlite_paths.append(dbp)
    problems: List[str] = []
    try:
        notary_a = net.create_notary_node("O=Notary A,L=Zurich,C=CH")
        notary_b = net.create_notary_node("O=Notary B,L=Geneva,C=CH")
        alice = net.create_node("O=Alice,L=London,C=GB", db_path=dbp)
        original = _issue(alice, notary_a, seed)
        with faults.inject(seed=seed) as fi:
            rule = fi.rule(point, "crash", times=1)
            h = alice.start_flow(NotaryChangeFlow(original, notary_b.info))
            net.run_network()
            try:
                h.result.result(timeout=5)
            # lint: allow(swallow) — the injected crash is SUPPOSED to
            except Exception:  # fail the flow; rule.fired asserts below
                pass
        # crash image first (journal entry still parked): the torn-WAL
        # parse check is this store's injected-torn-write evidence
        snap = disk.snapshot_sqlite(os.path.join(wd, "crashimg"))
        torn = bool(disk.tear_sqlite_wal(snap.values()))
        db2 = NodeDatabase(snap[dbp])
        try:
            PrepareJournal(db2, table=JOURNAL_TABLE).items()
        except Exception as exc:
            problems.append(
                f"change journal unparseable after torn WAL: "
                f"{type(exc).__name__}: {exc}"
            )
        db2.close()
        # live recovery: re-drive (or no-op) then the journal MUST drain
        rh = alice.start_flow(NotaryChangeRecoveryFlow())
        net.run_network()
        rh.result.result(timeout=5)
        problems += recovery.verify_notary_change(
            change_journal(alice.services)
        )
    finally:
        net.stop_nodes()
    if not rule.fired:
        problems.append(f"{point}: crash seam never fired in workload")
    return {"problems": problems, "crashed": bool(rule.fired),
            "torn": torn}


def _scn_uniqueness(point: str, seed: int, wd: str) -> dict:
    from corda_tpu.core.contracts.structures import StateRef
    from corda_tpu.node import recovery
    from corda_tpu.node.database import NodeDatabase
    from corda_tpu.node.notary import (
        NotaryService,
        PersistentUniquenessProvider,
        UniquenessException,
    )

    dbp = os.path.join(wd, "notary.db")
    disk = crashstore.CrashDisk(rng=random.Random(seed))
    disk.sqlite_paths.append(dbp)

    class _Svc:
        pass

    committed: Dict[bytes, str] = {}
    with _env(CORDA_TPU_NOTARY_COALESCE="0"):
        db = NodeDatabase(dbp)
        svc = _Svc()
        svc.db = db
        svc.clock = time.time
        ns = NotaryService(svc, _Party())
        with faults.inject(seed=seed) as fi:
            rule = fi.rule(point, "crash", times=1)
            for i in range(5):
                ref = StateRef(_tx_id(f"state-{seed}-{i}"), 0)
                tx = _tx_id(f"spend-{seed}-{i}")
                try:
                    ns.commit_input_states([ref], tx)
                except _crash_errors():
                    continue  # the commit died BEFORE the log write
                committed[
                    ref.txhash.bytes + ref.index.to_bytes(4, "big")
                ] = tx.bytes.hex()
        snap = disk.snapshot_sqlite(os.path.join(wd, "crashimg"))
        torn = bool(disk.tear_sqlite_wal(snap.values()))
        db.close()
    problems: List[str] = []
    if not rule.fired:
        problems.append(f"{point}: crash seam never fired in workload")
    db2 = NodeDatabase(snap[dbp])
    p2 = PersistentUniquenessProvider(db2)
    problems += recovery.verify_consumption([p2], committed)
    # double-spend probe: a committed key must still CONFLICT for a
    # different tx, and re-accept its own tx (idempotent replay)
    if committed:
        ref0 = StateRef(_tx_id(f"state-{seed}-0"), 0)
        key0 = ref0.txhash.bytes + (0).to_bytes(4, "big")
        if key0 in committed:
            try:
                p2.commit([ref0], _tx_id("thief"), _Party())
                problems.append(
                    "recovered commit log accepted a double-spend"
                )
            except UniquenessException:
                pass
    db2.close()
    return {"problems": problems, "crashed": bool(rule.fired),
            "torn": torn}


SCENARIOS = {
    "atomic_file": _scn_atomic_file,
    "broker_journal": _scn_broker,
    "checkpoints": _scn_checkpoints,
    "vault": _scn_vault,
    "sharded_2pc": _scn_sharded,
    "notary_change_journal": _scn_notary_change,
    "uniqueness_log": _scn_uniqueness,
}


# ---------------------------------------------------------------------------
# sabotage (--break-recovery): prove the matrix catches a broken recovery
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def _sabotage(store: Optional[str]):
    if store is None:
        yield
        return
    if store == "broker_journal":
        from corda_tpu.messaging import broker

        orig = broker._Journal.replay
        broker._Journal.replay = staticmethod(lambda path: [])
        try:
            yield
        finally:
            broker._Journal.replay = orig
    elif store == "checkpoints":
        from corda_tpu.node import database

        orig = database.CheckpointStorage.all_checkpoints

        def _wedge(self):
            raise RuntimeError(
                "sabotaged recovery (crashmc --break-recovery)"
            )

        database.CheckpointStorage.all_checkpoints = _wedge
        try:
            yield
        finally:
            database.CheckpointStorage.all_checkpoints = orig
    else:
        raise SystemExit(
            f"--break-recovery supports broker_journal|checkpoints, "
            f"not {store!r}"
        )


# ---------------------------------------------------------------------------
# the matrix
# ---------------------------------------------------------------------------

@dataclass
class MatrixReport:
    cells: Dict[Tuple[str, int], List[str]] = field(default_factory=dict)
    torn_stores: Dict[str, int] = field(default_factory=dict)
    coverage_problems: List[str] = field(default_factory=list)

    @property
    def failed_cells(self) -> Dict[Tuple[str, int], List[str]]:
        return {k: v for k, v in self.cells.items() if v}

    @property
    def ok(self) -> bool:
        return not self.failed_cells and not self.coverage_problems


def run_cell(point: str, store: str, seed: int) -> dict:
    """One matrix cell in a throwaway workdir; never lets a scenario
    exception wedge the matrix — a raise IS a red cell."""
    wd = tempfile.mkdtemp(prefix=f"crashmc-{store}-")
    try:
        return SCENARIOS[store](point, seed, wd)
    except Exception as exc:
        return {
            "problems": [
                f"scenario raised {type(exc).__name__}: {exc} "
                f"(recovery must never wedge)"
            ],
            "crashed": False, "torn": False,
        }
    finally:
        shutil.rmtree(wd, ignore_errors=True)


def run_matrix(
    points: Optional[List[str]] = None,
    seeds: int = 3,
    seed_base: int = 0,
    break_recovery: Optional[str] = None,
    require_coverage: bool = True,
    echo=None,
) -> MatrixReport:
    _import_stores()
    registry = dict(faultpoints.CRASH_POINTS)
    selected = {
        p: s for p, s in sorted(registry.items())
        if points is None or any(fnmatch.fnmatch(p, pat) for pat in points)
    }
    report = MatrixReport()
    if require_coverage:
        if len(registry) < MIN_POINTS:
            report.coverage_problems.append(
                f"only {len(registry)} crash points registered "
                f"(floor {MIN_POINTS})"
            )
        if len(set(registry.values())) < MIN_STORES:
            report.coverage_problems.append(
                f"only {len(set(registry.values()))} stores covered "
                f"(floor {MIN_STORES})"
            )
    with _sabotage(break_recovery):
        for point, store in selected.items():
            for i in range(seeds):
                seed = seed_base + i
                res = run_cell(point, store, seed)
                report.cells[(point, seed)] = res["problems"]
                if res["torn"]:
                    report.torn_stores[store] = (
                        report.torn_stores.get(store, 0) + 1
                    )
                if echo:
                    verdict = "CLEAN" if not res["problems"] else "RED"
                    echo(f"  {point:42} seed={seed} {verdict}")
                    for prob in res["problems"]:
                        echo(f"      !! {prob}")
        # every store must show at least one demonstrably-injected torn
        # write somewhere in the matrix; retry the probabilistic stores
        # with fresh seeds before declaring the evidence missing
        if require_coverage:
            stores_run = set(selected.values())
            for store in sorted(stores_run):
                extra = 0
                while (report.torn_stores.get(store, 0) == 0
                       and extra < 12):
                    point = next(
                        p for p, s in selected.items() if s == store
                    )
                    res = run_cell(
                        point, store, seed_base + seeds + 1000 + extra
                    )
                    if res["torn"]:
                        report.torn_stores[store] = 1
                    extra += 1
                if report.torn_stores.get(store, 0) == 0:
                    report.coverage_problems.append(
                        f"store {store}: no injected torn write "
                        f"demonstrated anywhere in the matrix"
                    )
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="crashmc", description=__doc__.splitlines()[0]
    )
    ap.add_argument("--list", action="store_true",
                    help="enumerate registered crash points and exit")
    ap.add_argument("--points", default=None,
                    help="comma-separated glob(s) of points to run")
    ap.add_argument("--stores", default=None,
                    help="comma-separated stores to run")
    ap.add_argument("--seeds", type=int, default=3,
                    help="seeds per point (default 3)")
    ap.add_argument("--seed-base", type=int, default=0)
    ap.add_argument("--break-recovery", default=None, metavar="STORE",
                    help="sabotage STORE's recovery; the matrix must "
                    "then FAIL (self-test of the matrix's teeth)")
    args = ap.parse_args(argv)

    _import_stores()
    registry = dict(faultpoints.CRASH_POINTS)
    if args.list:
        for p, s in sorted(registry.items()):
            print(f"{s:22} {p}")
        print(f"{len(registry)} points across "
              f"{len(set(registry.values()))} stores")
        return 0

    patterns = args.points.split(",") if args.points else None
    if args.stores:
        wanted = set(args.stores.split(","))
        unknown = wanted - set(SCENARIOS)
        if unknown:
            ap.error(f"unknown stores: {sorted(unknown)}")
        store_pts = [p for p, s in registry.items() if s in wanted]
        patterns = (patterns or []) + store_pts

    print(f"crashmc: {len(registry)} registered points, "
          f"{len(set(registry.values()))} stores, "
          f"{args.seeds} seeds per point")
    report = run_matrix(
        points=patterns, seeds=args.seeds, seed_base=args.seed_base,
        break_recovery=args.break_recovery, echo=print,
    )
    print()
    for store, n in sorted(report.torn_stores.items()):
        print(f"torn-write evidence: {store} ({n} runs)")
    if report.ok:
        print(f"MATRIX GREEN: {len(report.cells)} cells clean")
        return 0
    for (point, seed), probs in sorted(report.failed_cells.items()):
        for prob in probs:
            print(f"RED {point} seed={seed}: {prob}")
    for prob in report.coverage_problems:
        print(f"RED coverage: {prob}")
    print(f"MATRIX RED: {len(report.failed_cells)} of "
          f"{len(report.cells)} cells failed, "
          f"{len(report.coverage_problems)} coverage problems")
    return 1


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Opportunistic TPU capture daemon for a flaky accelerator tunnel.

The tunnel (axon) comes and goes: it answered a probe at the start of
this session, then hung within minutes. This daemon loops forever:
probe; when the tunnel is alive, run the highest-priority *incomplete*
step from the runbook (docs/hardware-runbook.md), each as a subprocess
with its own timeout; record every result to tpu_capture/log.jsonl and
completed step names to tpu_capture/state.json so a mid-sequence tunnel
death resumes instead of restarting.

Run:  mkdir -p tpu_capture && \
      nohup python tools/hw_capture.py > tpu_capture/daemon.out 2>&1 &
Stop: touch tpu_capture/STOP
"""
import hashlib
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPDIR = os.path.join(REPO, "tpu_capture")
STATE = os.path.join(CAPDIR, "state.json")
LOG = os.path.join(CAPDIR, "log.jsonl")
STOP = os.path.join(CAPDIR, "STOP")

sys.path.insert(0, REPO)
from bench import PROBE_SNIPPET  # noqa: E402  (shared liveness criteria)

# Quiesce handshake, the WRITER's module as single source of truth
# (path resolution incl. the CORDA_TPU_QUIESCE_FILE override, marker
# schema, expiry semantics — a drifted re-implementation here would
# silently void the handshake): bench.py posts the marker around its
# measurement window; while it is unexpired the daemon neither probes
# nor launches steps — a probe subprocess landing inside a bench window
# halves that reading on the 1-core box (the round-5 host regression).
# corda_tpu.utils.quiesce is stdlib-only: importing it cannot pull jax
# into the daemon parent (probes are subprocesses precisely to keep the
# parent's JAX state clean).
from corda_tpu.utils.quiesce import file_quiesced as quiesced  # noqa: E402

# ---------------------------------------------------------------------------
# Tiered liveness probes.  Three variants, cheapest first, each run in its
# own subprocess so a hang cannot poison the daemon.  Every variant arms
# faulthandler.dump_traceback_later a few seconds BEFORE the parent's
# timeout: on a hang the subprocess dumps the stack of every thread to
# stderr and self-exits, so the round artifact shows WHERE init hangs
# (libtpu init vs first transfer vs first compile) instead of a bare
# "probe hang".  Tiering means a revival is detected at the cheapest
# level: enum alone passing (but dput/jit hanging) is itself a diagnosis.
# ---------------------------------------------------------------------------


def _armed(body: str, timeout: int) -> str:
    return (
        "import faulthandler, sys\n"
        f"faulthandler.dump_traceback_later({max(timeout - 4, 3)}, exit=True, "
        "file=sys.stderr)\n" + body
    )


PROBE_VARIANTS = [
    # Bare client init + device enumeration: no data transfer, no compile.
    ("enum", 40, (
        "import jax\n"
        "d = jax.devices()\n"
        "print('PROBE-OK platform=' + d[0].platform + ' n=' + str(len(d)))\n"
    )),
    # One-element host->device transfer and readback: exercises the data
    # plane but not the compiler.
    ("dput", 40, (
        "import jax, jax.numpy as jnp\n"
        "x = jax.device_put(jnp.ones((1,), dtype=jnp.uint32))\n"
        "assert int(x[0]) == 1\n"
        "print('PROBE-OK platform=' + x.devices().pop().platform)\n"
    )),
    # Tiny jit: first real compile + dispatch. Derived from bench.py's
    # OWN probe so the daemon's liveness bar can never drift from the
    # bar bench applies when the capture step actually runs.
    ("jit", 75, PROBE_SNIPPET + (
        "print('PROBE-OK platform=' + d[0].platform)\n"
    )),
]

ECDSA_SMOKE = """
import time
t0 = time.time()
import jax
assert jax.default_backend() == "tpu", jax.default_backend()
from corda_tpu.core.crypto import crypto
from corda_tpu.core.crypto.schemes import ECDSA_SECP256K1_SHA256
from corda_tpu.ops import ecdsa_batch, ecdsa_pallas
kps = [crypto.generate_keypair(ECDSA_SECP256K1_SHA256) for _ in range(8)]
items = [(kp.public.encoded, crypto.do_sign(kp.private, b"x"), b"x")
         for kp in kps for _ in range(64)]
out = ecdsa_batch.verify_batch("secp256k1",
    [i[0] for i in items], [i[1] for i in items], [i[2] for i in items])
assert all(out), "ECDSA verify_batch returned failures"
assert not ecdsa_batch._pallas_failed_once, (
    "dispatch fell back to the portable XLA kernel -- the Pallas kernel "
    "did NOT run; see the 'Pallas ECDSA kernel failed' log above")
from corda_tpu.ops import ed25519_pallas
print(f"ECDSA-SMOKE-OK wall={time.time()-t0:.1f}s "
      f"fast_mul_survived={ed25519_pallas._FAST_MUL_ENABLED}")
"""

MESH_SMOKE = """
import time
t0 = time.time()
import jax
assert jax.default_backend() == "tpu", jax.default_backend()
import numpy as np
from corda_tpu.core.crypto import ed25519_math
from corda_tpu.parallel import mesh
rng = np.random.default_rng(3)
seeds = [rng.bytes(32) for _ in range(8)]
pubs, sigs, msgs = [], [], []
for k in range(512):
    s = seeds[k % 8]
    m = rng.bytes(32)
    pubs.append(ed25519_math.public_from_seed(s))
    sigs.append(ed25519_math.sign(s, m))
    msgs.append(m)
out = mesh.shard_verify_ed25519(mesh.data_mesh(), pubs, sigs, msgs)
assert bool(np.asarray(out).all())
print(f"MESH-SMOKE-OK wall={time.time()-t0:.1f}s")
"""


def bench_env(**kw):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    for k, v in kw.items():
        env[k] = str(v)
    return env


def bench_step(blk, chunk, fast, radix=16):
    name = f"headline-blk{blk}-chunk{chunk}-fast{int(fast)}"
    if radix != 16:
        name += f"-r{radix}"
    return {
        "name": name,
        "argv": [sys.executable, os.path.join(REPO, "bench.py")],
        "env": bench_env(
            CORDA_TPU_ED25519_BLK=blk,
            CORDA_TPU_PIPE_CHUNK=chunk,
            CORDA_TPU_FAST_MUL=int(fast),
            CORDA_TPU_ED25519_RADIX=radix,
            CORDA_TPU_BENCH_HEADLINE_ONLY=1,
        ),
        "timeout": 1500,
        "require_tpu_line": True,
    }


def steps():
    """The fast-mul (.at[].add) variants were REMOVED from the matrix:
    the jax.export TPU cross-lowering gate proved Mosaic has no
    scatter-add lowering, so those configs cannot compile on current
    JAX. Dense radix-13 (the new default) and dense radix-16 both pass
    the gate; the A/B here decides which ships."""
    out = [
        # The gate number first: the defaults (radix-13 dense).
        bench_step(512, 65536, False, radix=13),
        # radix A/B: the round-2-measured radix-16 dense config.
        bench_step(512, 65536, False, radix=16),
        # First-ever ECDSA Pallas execution on silicon (long compile ok).
        {
            "name": "ecdsa-smoke",
            "argv": [sys.executable, "-c", ECDSA_SMOKE],
            "env": bench_env(CORDA_TPU_LOG="info"),
            "timeout": 2400,
        },
        # BLK/chunk sweep at the default radix.
        bench_step(256, 65536, False, radix=13),
        bench_step(1024, 65536, False, radix=13),
        bench_step(512, 131072, False, radix=13),
        # Pallas-under-shard_map lowering on a 1-device mesh.
        {
            "name": "mesh-smoke",
            "argv": [sys.executable, "-c", MESH_SMOKE],
            "env": bench_env(),
            "timeout": 1500,
        },
        # Full bench: headline + ECDSA/mixed secondaries + notarise p50
        # + real-process system rate. The complete driver-style record.
        {
            "name": "full-bench",
            "argv": [sys.executable, os.path.join(REPO, "bench.py")],
            "env": bench_env(),
            "timeout": 3600,
            "require_tpu_line": True,
        },
    ]
    return out


def log(rec):
    rec["ts"] = time.time()
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)


def load_state():
    try:
        with open(STATE) as f:
            return json.load(f)
    except Exception:
        return {"done": [], "fail_counts": {}}


def save_state(st):
    # atomic + fsync'd: a crash mid-write must not destroy the resume state
    from corda_tpu.utils import atomicfile

    atomicfile.write_json_atomic(STATE, st, indent=1)


_last_stack_hash: dict[str, str] = {}
_last_failed: set[str] = set()  # tiers that failed on the previous loop
_healthy = False  # last full probe ladder passed


def _hang_stack(stderr: str) -> tuple[str, str]:
    """Extract the faulthandler dump (if any) and a stable signature.

    The signature hashes only the code locations (file:line), not thread
    ids or addresses, so "same hang as before" dedups across runs.
    """
    idx = stderr.find("Timeout (")
    dump = stderr[idx:] if idx >= 0 else stderr
    lines = [ln.strip() for ln in dump.splitlines()
             if ln.strip().startswith('File "')]
    sig = hashlib.sha256("\n".join(lines).encode()).hexdigest()[:10]
    return dump[-3000:], sig


def probe_variant(name, timeout, body):
    """Run one probe tier; return a log record with hang diagnostics."""
    rec = {"step": "probe-" + name}
    t0 = time.time()
    try:
        out = subprocess.run(
            [sys.executable, "-c", _armed(body, timeout)],
            capture_output=True, text=True, timeout=timeout, env=bench_env(),
            # probes run every ~50s all round on a 1-core box: without
            # a low priority they visibly skew any concurrently running
            # benchmark (incl. the driver's end-of-round bench.py)
            preexec_fn=lambda: os.nice(15),
        )
    except subprocess.TimeoutExpired as exc:
        # faulthandler should have fired first; this is the backstop
        stderr = (exc.stderr.decode("utf8", "replace")
                  if isinstance(exc.stderr, bytes) else (exc.stderr or ""))
        rec.update(alive=False, why="hard hang (faulthandler did not fire)",
                   wall_s=round(time.time() - t0, 1),
                   stderr_tail=stderr[-500:])
        return rec
    rec["wall_s"] = round(time.time() - t0, 1)
    if "PROBE-OK platform=tpu" in out.stdout:
        rec["alive"] = True
        return rec
    rec["alive"] = False
    if "Timeout (" in out.stderr:
        dump, sig = _hang_stack(out.stderr)
        rec["why"] = "probe hang"
        rec["stack_hash"] = sig
        if _last_stack_hash.get(name) != sig:
            _last_stack_hash[name] = sig
            rec["hang_stack"] = dump  # full dump only when it CHANGES
        else:
            rec["hang_stack"] = "unchanged"
    elif "PROBE-OK" in out.stdout:
        rec["why"] = "wrong platform: " + out.stdout.strip()[-100:]
    else:
        rec["why"] = (out.stderr or out.stdout).strip()[-300:]
    return rec


def probe():
    """Tiered probe; returns (alive, why).

    While HEALTHY only the jit tier (the actual liveness bar) runs —
    paying three JAX-client inits per loop would shrink the capture
    window on a tunnel whose uptime is O(minutes). After any failure the
    full ladder (enum -> device_put -> jit, cheapest first) runs each
    loop, so the round artifact localises the hang at the cheapest tier
    that distinguishes it and a revival is detected tier by tier.
    """
    global _healthy
    tiers = PROBE_VARIANTS if not _healthy else PROBE_VARIANTS[-1:]
    for name, timeout, body in tiers:
        rec = probe_variant(name, timeout, body)
        if not rec["alive"]:
            log(rec)
            _last_failed.add(name)
            _healthy = False
            return False, rec.get("why", "?")
        # a success is only worth a log line when the SAME tier failed
        # on the previous loop (revival evidence, not per-loop noise)
        if name in _last_failed:
            _last_failed.discard(name)
            log(rec)
    _healthy = True
    return True, None


def run_step(step):
    t0 = time.time()
    try:
        out = subprocess.run(
            step["argv"], capture_output=True, text=True,
            timeout=step["timeout"], env=step["env"],
        )
    except subprocess.TimeoutExpired as exc:
        return {
            "step": step["name"], "ok": False, "error": "timeout",
            "wall_s": round(time.time() - t0, 1),
            "partial": ((exc.stdout or b"").decode("utf8", "replace")[-500:]
                        if isinstance(exc.stdout, bytes) else (exc.stdout or "")[-500:]),
        }
    rec = {
        "step": step["name"],
        "ok": out.returncode == 0,
        "rc": out.returncode,
        "wall_s": round(time.time() - t0, 1),
    }
    line = next(
        (ln for ln in out.stdout.splitlines() if ln.startswith("{")), None)
    if line:
        try:
            rec["result"] = json.loads(line)
        except Exception:
            rec["stdout_tail"] = out.stdout[-500:]
    else:
        rec["stdout_tail"] = out.stdout[-500:]
    if out.returncode != 0 or not line:
        rec["stderr_tail"] = out.stderr[-1500:]
    if step.get("require_tpu_line"):
        # a CPU-fallback line, a lost/unparseable JSON line, a TPU number
        # silently served by the XLA fallback, OR a run whose kernel
        # degraded away from the REQUESTED fast_mul/radix config (the
        # in-process retry ladder flips those flags on Mosaic failure)
        # is NOT a capture of this step's variant: leave it incomplete
        res = rec.get("result", {})
        env = step.get("env", {})
        want_fast = env.get("CORDA_TPU_FAST_MUL", "0") != "0"
        want_r13 = env.get("CORDA_TPU_ED25519_RADIX", "13") == "13"
        rec["ok"] = bool(
            rec["ok"]
            and res.get("backend") == "tpu"
            and not res.get("pallas_fallback", False)
            and res.get("fast_mul") == want_fast
            and res.get("radix13") == want_r13
        )
    return rec


def main():
    os.makedirs(CAPDIR, exist_ok=True)
    st = load_state()
    log({"step": "daemon-start", "done": st["done"]})
    deadline = time.time() + 11.5 * 3600
    was_quiesced = False
    while time.time() < deadline:
        if os.path.exists(STOP):
            log({"step": "daemon-stop", "reason": "STOP file"})
            return 0
        if quiesced():
            if not was_quiesced:  # one line per transition, not per nap
                log({"step": "quiesce-pause"})
                was_quiesced = True
            time.sleep(5)
            continue
        if was_quiesced:
            log({"step": "quiesce-resume"})
            was_quiesced = False
        todo = [s for s in steps()
                if s["name"] not in st["done"]
                and st["fail_counts"].get(s["name"], 0) < 4]
        if not todo:
            abandoned = [n for n, c in st["fail_counts"].items()
                         if c >= 4 and n not in st["done"]]
            log({"step": "daemon-done", "done": st["done"],
                 "abandoned": abandoned})
            return 0
        alive, why = probe()  # failures logged per-tier inside probe()
        if not alive:
            # short sleep: a hung probe already costs ~40s, and the tunnel's
            # uptime windows have been O(minutes) — a 30s extra nap was
            # enough to miss one (round-3 logged 440 hangs, 0 captures)
            time.sleep(10)
            continue
        step = todo[0]
        log({"step": "probe", "alive": True, "next": step["name"]})
        rec = run_step(step)
        log(rec)
        if rec["ok"]:
            st["done"].append(step["name"])
        else:
            st["fail_counts"][step["name"]] = (
                st["fail_counts"].get(step["name"], 0) + 1)
        save_state(st)
    log({"step": "daemon-timeout", "done": st["done"]})
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Opportunistic TPU capture daemon for a flaky accelerator tunnel.

The tunnel (axon) comes and goes: it answered a probe at the start of
this session, then hung within minutes. This daemon loops forever:
probe; when the tunnel is alive, run the highest-priority *incomplete*
step from the runbook (docs/hardware-runbook.md), each as a subprocess
with its own timeout; record every result to tpu_capture/log.jsonl and
completed step names to tpu_capture/state.json so a mid-sequence tunnel
death resumes instead of restarting.

Run:  mkdir -p tpu_capture && \
      nohup python tools/hw_capture.py > tpu_capture/daemon.out 2>&1 &
Stop: touch tpu_capture/STOP
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPDIR = os.path.join(REPO, "tpu_capture")
STATE = os.path.join(CAPDIR, "state.json")
LOG = os.path.join(CAPDIR, "log.jsonl")
STOP = os.path.join(CAPDIR, "STOP")

sys.path.insert(0, REPO)
from bench import PROBE_SNIPPET as PROBE  # noqa: E402  (shared liveness criteria)

ECDSA_SMOKE = """
import time
t0 = time.time()
import jax
assert jax.default_backend() == "tpu", jax.default_backend()
from corda_tpu.core.crypto import crypto
from corda_tpu.core.crypto.schemes import ECDSA_SECP256K1_SHA256
from corda_tpu.ops import ecdsa_batch, ecdsa_pallas
kps = [crypto.generate_keypair(ECDSA_SECP256K1_SHA256) for _ in range(8)]
items = [(kp.public.encoded, crypto.do_sign(kp.private, b"x"), b"x")
         for kp in kps for _ in range(64)]
out = ecdsa_batch.verify_batch("secp256k1",
    [i[0] for i in items], [i[1] for i in items], [i[2] for i in items])
assert all(out), "ECDSA verify_batch returned failures"
assert not ecdsa_batch._pallas_failed_once, (
    "dispatch fell back to the portable XLA kernel -- the Pallas kernel "
    "did NOT run; see the 'Pallas ECDSA kernel failed' log above")
from corda_tpu.ops import ed25519_pallas
print(f"ECDSA-SMOKE-OK wall={time.time()-t0:.1f}s "
      f"fast_mul_survived={ed25519_pallas._FAST_MUL_ENABLED}")
"""

MESH_SMOKE = """
import time
t0 = time.time()
import jax
assert jax.default_backend() == "tpu", jax.default_backend()
import numpy as np
from corda_tpu.core.crypto import ed25519_math
from corda_tpu.parallel import mesh
rng = np.random.default_rng(3)
seeds = [rng.bytes(32) for _ in range(8)]
pubs, sigs, msgs = [], [], []
for k in range(512):
    s = seeds[k % 8]
    m = rng.bytes(32)
    pubs.append(ed25519_math.public_from_seed(s))
    sigs.append(ed25519_math.sign(s, m))
    msgs.append(m)
out = mesh.shard_verify_ed25519(mesh.data_mesh(), pubs, sigs, msgs)
assert bool(np.asarray(out).all())
print(f"MESH-SMOKE-OK wall={time.time()-t0:.1f}s")
"""


def bench_env(**kw):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    for k, v in kw.items():
        env[k] = str(v)
    return env


def bench_step(blk, chunk, fast, radix=16):
    name = f"headline-blk{blk}-chunk{chunk}-fast{int(fast)}"
    if radix != 16:
        name += f"-r{radix}"
    return {
        "name": name,
        "argv": [sys.executable, os.path.join(REPO, "bench.py")],
        "env": bench_env(
            CORDA_TPU_ED25519_BLK=blk,
            CORDA_TPU_PIPE_CHUNK=chunk,
            CORDA_TPU_FAST_MUL=int(fast),
            CORDA_TPU_ED25519_RADIX=radix,
            CORDA_TPU_BENCH_HEADLINE_ONLY=1,
        ),
        "timeout": 1500,
        "require_tpu_line": True,
    }


def steps():
    """The fast-mul (.at[].add) variants were REMOVED from the matrix:
    the jax.export TPU cross-lowering gate proved Mosaic has no
    scatter-add lowering, so those configs cannot compile on current
    JAX. Dense radix-13 (the new default) and dense radix-16 both pass
    the gate; the A/B here decides which ships."""
    out = [
        # The gate number first: the defaults (radix-13 dense).
        bench_step(512, 65536, False, radix=13),
        # radix A/B: the round-2-measured radix-16 dense config.
        bench_step(512, 65536, False, radix=16),
        # First-ever ECDSA Pallas execution on silicon (long compile ok).
        {
            "name": "ecdsa-smoke",
            "argv": [sys.executable, "-c", ECDSA_SMOKE],
            "env": bench_env(CORDA_TPU_LOG="info"),
            "timeout": 2400,
        },
        # BLK/chunk sweep at the default radix.
        bench_step(256, 65536, False, radix=13),
        bench_step(1024, 65536, False, radix=13),
        bench_step(512, 131072, False, radix=13),
        # Pallas-under-shard_map lowering on a 1-device mesh.
        {
            "name": "mesh-smoke",
            "argv": [sys.executable, "-c", MESH_SMOKE],
            "env": bench_env(),
            "timeout": 1500,
        },
        # Full bench: headline + ECDSA/mixed secondaries + notarise p50
        # + real-process system rate. The complete driver-style record.
        {
            "name": "full-bench",
            "argv": [sys.executable, os.path.join(REPO, "bench.py")],
            "env": bench_env(),
            "timeout": 3600,
            "require_tpu_line": True,
        },
    ]
    return out


def log(rec):
    rec["ts"] = time.time()
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)


def load_state():
    try:
        with open(STATE) as f:
            return json.load(f)
    except Exception:
        return {"done": [], "fail_counts": {}}


def save_state(st):
    # atomic: a crash mid-write must not destroy the resume state
    tmp = STATE + ".tmp"
    with open(tmp, "w") as f:
        json.dump(st, f, indent=1)
    os.replace(tmp, STATE)


def probe(timeout=45):
    try:
        out = subprocess.run(
            [sys.executable, "-c", PROBE], capture_output=True, text=True,
            timeout=timeout, env=bench_env(),
        )
    except subprocess.TimeoutExpired:
        return False, "probe hang"
    if "PLATFORM=tpu" in out.stdout:
        return True, None
    return False, (out.stderr or out.stdout)[-200:]


def run_step(step):
    t0 = time.time()
    try:
        out = subprocess.run(
            step["argv"], capture_output=True, text=True,
            timeout=step["timeout"], env=step["env"],
        )
    except subprocess.TimeoutExpired as exc:
        return {
            "step": step["name"], "ok": False, "error": "timeout",
            "wall_s": round(time.time() - t0, 1),
            "partial": ((exc.stdout or b"").decode("utf8", "replace")[-500:]
                        if isinstance(exc.stdout, bytes) else (exc.stdout or "")[-500:]),
        }
    rec = {
        "step": step["name"],
        "ok": out.returncode == 0,
        "rc": out.returncode,
        "wall_s": round(time.time() - t0, 1),
    }
    line = next(
        (ln for ln in out.stdout.splitlines() if ln.startswith("{")), None)
    if line:
        try:
            rec["result"] = json.loads(line)
        except Exception:
            rec["stdout_tail"] = out.stdout[-500:]
    else:
        rec["stdout_tail"] = out.stdout[-500:]
    if out.returncode != 0 or not line:
        rec["stderr_tail"] = out.stderr[-1500:]
    if step.get("require_tpu_line"):
        # a CPU-fallback line, a lost/unparseable JSON line, a TPU number
        # silently served by the XLA fallback, OR a run whose kernel
        # degraded away from the REQUESTED fast_mul/radix config (the
        # in-process retry ladder flips those flags on Mosaic failure)
        # is NOT a capture of this step's variant: leave it incomplete
        res = rec.get("result", {})
        env = step.get("env", {})
        want_fast = env.get("CORDA_TPU_FAST_MUL", "0") != "0"
        want_r13 = env.get("CORDA_TPU_ED25519_RADIX", "13") == "13"
        rec["ok"] = bool(
            rec["ok"]
            and res.get("backend") == "tpu"
            and not res.get("pallas_fallback", False)
            and res.get("fast_mul") == want_fast
            and res.get("radix13") == want_r13
        )
    return rec


def main():
    os.makedirs(CAPDIR, exist_ok=True)
    st = load_state()
    log({"step": "daemon-start", "done": st["done"]})
    deadline = time.time() + 11.5 * 3600
    while time.time() < deadline:
        if os.path.exists(STOP):
            log({"step": "daemon-stop", "reason": "STOP file"})
            return 0
        todo = [s for s in steps()
                if s["name"] not in st["done"]
                and st["fail_counts"].get(s["name"], 0) < 4]
        if not todo:
            abandoned = [n for n, c in st["fail_counts"].items()
                         if c >= 4 and n not in st["done"]]
            log({"step": "daemon-done", "done": st["done"],
                 "abandoned": abandoned})
            return 0
        alive, why = probe()
        if not alive:
            log({"step": "probe", "alive": False, "why": why})
            # short sleep: a hung probe already costs 45s, and the tunnel's
            # uptime windows have been O(minutes) — a 30s extra nap was
            # enough to miss one (round-3 logged 440 hangs, 0 captures)
            time.sleep(10)
            continue
        step = todo[0]
        log({"step": "probe", "alive": True, "next": step["name"]})
        rec = run_step(step)
        log(rec)
        if rec["ok"]:
            st["done"].append(step["name"])
        else:
            st["fail_counts"][step["name"]] = (
                st["fail_counts"].get(step["name"], 0) + 1)
        save_state(st)
    log({"step": "daemon-timeout", "done": st["done"]})
    return 0


if __name__ == "__main__":
    sys.exit(main())

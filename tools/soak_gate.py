#!/usr/bin/env python
"""Soak gate CLI: turn a remote-soak result record into CI exit status.

    python -m corda_tpu.loadtest.remote --hosts hosts.conf > soak.json
    python tools/soak_gate.py --current soak.json
    python tools/soak_gate.py --current - --slo "pairs>=100"

Fails (exit 1) on:
  * any `slo_violations` the soak itself recorded (the run's own SLO
    spec — disruption recovery, typed-shed hygiene, reconciliation);
  * `consistent` false, or loss/dup evidence (`hard_driver_errors`,
    `reconciliation.torn_spends`);
  * any extra `--slo` bound asserted here (gate.check_slos semantics:
    a bound on a metric the record lacks is a violation, not a skip);
  * any `--mttr MS` repair-time ceiling: every `mttr_ms{kind=…}` key in
    the record's `mttr` block must sit under the bound, and a record
    that fired disruptions but carries NO mttr block breaches too (an
    observatory that silently stopped reporting must not read as green);
  * any `--domain-goodput PCT` floor: the multi-domain soak's
    `domain_goodput_pct` (foreign-traffic rate while one domain was
    dark, as a % of the undisrupted baseline) must be >= PCT — and a
    record MISSING the key breaches, same missing-block hygiene as
    --mttr (a soak that never measured goodput must not read as green);
  * any `--require KIND` (repeatable): the record's event timeline must
    show that disruption kind FIRED and RECOVERED at least once — a
    soak whose catalog silently skipped the kind (or whose run ended
    before the rotation reached it) must not read as coverage. E.g.
    `--require restart_storm` pins the crash-consistency rotation.

Exit status: 0 = pass, 1 = breach, 2 = usage error — the same contract
as tools/bench_gate.py, sharing its comparison engine
(corda_tpu.loadtest.gate).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable from any cwd without installation
    sys.path.insert(0, _REPO)

from corda_tpu.loadtest import gate  # noqa: E402

#: invariants asserted on EVERY soak record, beyond what the run chose
#: to check — a gate that trusts the record's own verdict alone can be
#: defeated by a run that never evaluated SLOs at all
BASELINE_SLOS = {
    "pairs": {"min": 1.0},
    "hard_error_rate": {"max": 0.25},
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="soak_gate")
    ap.add_argument(
        "--current", required=True,
        help="soak record to gate: a JSON file, or '-' for stdin",
    )
    ap.add_argument(
        "--slo", action="append", metavar="KEY<=V | KEY>=V",
        help="extra absolute bound to assert (repeatable; dotted keys "
             "reach nested blocks, e.g. overload.recovered>=1)",
    )
    ap.add_argument(
        "--mttr", type=float, metavar="MS",
        help="ceiling (ms) asserted on EVERY mttr_ms{kind=…} the record "
             "reports; missing mttr block on a disrupted run = breach",
    )
    ap.add_argument(
        "--require", action="append", metavar="KIND",
        help="disruption kind that must appear in the record's events "
             "as fired AND recovered (repeatable); absence = breach",
    )
    ap.add_argument(
        "--domain-goodput", type=float, metavar="PCT",
        help="floor (%%) asserted on the record's domain_goodput_pct "
             "(multi-domain soak: foreign traffic while one domain was "
             "dark vs baseline); a missing/None value = breach",
    )
    args = ap.parse_args(argv)

    try:
        if args.current == "-":
            record = json.load(sys.stdin)
        else:
            with open(args.current) as fh:
                record = json.load(fh)
        if not isinstance(record, dict):
            raise ValueError("not a soak record")
    except (OSError, ValueError) as exc:
        print(f"soak_gate: cannot read record: {exc}", file=sys.stderr)
        return 2

    try:
        slos = {**BASELINE_SLOS, **gate.parse_slo_args(args.slo)}
    except ValueError as exc:
        print(f"soak_gate: {exc}", file=sys.stderr)
        return 2

    violations = list(record.get("slo_violations") or [])
    violations.extend(gate.check_slos(record, slos))
    if args.mttr is not None:
        mttr = record.get("mttr") or {}
        kinds = {
            k: v for k, v in mttr.items() if k.startswith("mttr_ms{")
        }
        if not kinds and record.get("disruptions_recovered"):
            violations.append({
                "key": "mttr", "value": None, "bound": args.mttr,
                "kind": "missing",
            })
        for key, value in sorted(kinds.items()):
            if not isinstance(value, (int, float)) or value > args.mttr:
                violations.append({
                    "key": f"mttr.{key}", "value": value,
                    "bound": args.mttr, "kind": "max",
                })
    if args.domain_goodput is not None:
        goodput = record.get("domain_goodput_pct")
        if not isinstance(goodput, (int, float)):
            violations.append({
                "key": "domain_goodput_pct", "value": goodput,
                "bound": args.domain_goodput, "kind": "missing",
            })
        elif goodput < args.domain_goodput:
            violations.append({
                "key": "domain_goodput_pct", "value": goodput,
                "bound": args.domain_goodput, "kind": "min",
            })
    for kind in args.require or []:
        statuses = {
            str(ev[2]) for ev in (record.get("events") or [])
            if isinstance(ev, (list, tuple)) and len(ev) >= 3
            and ev[1] == kind
        }
        fired_ev = any(s == "fired" for s in statuses)
        recovered_ev = any(s.startswith("recovered") for s in statuses)
        if not (fired_ev and recovered_ev):
            violations.append({
                "key": f"require.{kind}",
                "value": sorted(statuses) or None,
                "bound": "fired+recovered", "kind": "missing",
            })
    if record.get("consistent") is not True:
        violations.append({
            "key": "consistent", "value": record.get("consistent"),
            "bound": True, "kind": "loss-or-dup",
        })

    for v in violations:
        print(
            f"SOAK VIOLATION {v.get('key')}: value={v.get('value')} "
            f"bound={v.get('bound')} ({v.get('kind')})",
            file=sys.stderr,
        )
    ok = not violations
    if ok:
        print(
            f"soak_gate: PASS ({record.get('pairs')} pairs, "
            f"{record.get('disruptions_recovered')} disruptions "
            f"recovered)",
            file=sys.stderr,
        )
    print(json.dumps({"ok": ok, "violations": violations}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Headline benchmark: batched ed25519 signature verification throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline target (BASELINE.json): >= 1,000,000 sig-verifies/sec on a v5e-4,
i.e. 250k/sec/chip; vs_baseline is measured-chip-rate / 250_000.

Runs on whatever backend JAX selects (the driver provides one real TPU chip;
falls back to CPU in dev environments).
"""
import json
import time

import numpy as np

BATCH = 16384
PER_CHIP_BASELINE = 250_000.0  # 1M/s on 4 chips


def main() -> None:
    import jax

    from corda_tpu.core.crypto import ed25519_math
    from corda_tpu.ops import ed25519_batch

    rng = np.random.default_rng(7)
    n_keys = 256  # realistic notary batch: many txs from few parties
    seeds = [rng.bytes(32) for _ in range(n_keys)]
    pubs_pool = [ed25519_math.public_from_seed(s) for s in seeds]
    pubs, sigs, msgs = [], [], []
    for i in range(BATCH):
        k = i % n_keys
        msg = rng.bytes(64)
        pubs.append(pubs_pool[k])
        sigs.append(ed25519_math.sign(seeds[k], msg))
        msgs.append(msg)

    kwargs, n = ed25519_batch.prepare_batch(pubs, sigs, msgs, pad_to=BATCH)

    # warm-up: compile + one execution
    mask = ed25519_batch.verify_kernel(**kwargs)
    mask.block_until_ready()
    assert bool(np.asarray(mask).all()), "benchmark batch failed to verify"

    reps = 3
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        ed25519_batch.verify_kernel(**kwargs).block_until_ready()
        best = min(best, time.perf_counter() - t0)

    rate = BATCH / best
    print(
        json.dumps(
            {
                "metric": "ed25519-sig-verifies/sec/chip",
                "value": round(rate, 1),
                "unit": "sigs/s",
                "vs_baseline": round(rate / PER_CHIP_BASELINE, 4),
                "batch": BATCH,
                "backend": jax.devices()[0].platform,
            }
        )
    )


if __name__ == "__main__":
    main()

"""Headline benchmark: batched ed25519 signature verification throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
Baseline target (BASELINE.json): >= 1,000,000 sig-verifies/sec on a v5e-4,
i.e. 250k/sec/chip; vs_baseline is measured-chip-rate / 250_000.

Measures END-TO-END throughput — byte parsing + SHA-512 prehash on the
host AND the device kernel — through the production `verify_batch`
pipeline (chunked host/device overlap), not the kernel alone (round-1
bench measured only the kernel; VERDICT round 1 called that out).

Runs on whatever backend JAX selects (the driver provides one real TPU
chip; the Pallas ladder kernel is used there, the portable XLA kernel
elsewhere).
"""
import json
import time

import numpy as np

BATCH = 131072  # two pipeline chunks
PER_CHIP_BASELINE = 250_000.0  # 1M/s on 4 chips


def main() -> None:
    import jax

    import corda_tpu  # noqa: F401  (enables the persistent compile cache)
    from corda_tpu.core.crypto import ed25519_math
    from corda_tpu.ops import ed25519_batch

    on_tpu = jax.default_backend() == "tpu"
    batch = BATCH if on_tpu else 4096  # CPU fallback kernel is ~100x slower

    rng = np.random.default_rng(7)
    n_keys = 256  # realistic notary batch: many txs from few parties
    seeds = [rng.bytes(32) for _ in range(n_keys)]
    pubs_pool = [ed25519_math.public_from_seed(s) for s in seeds]
    sig_pool = []
    msg_pool = []
    for k in range(n_keys):
        msg = rng.bytes(64)
        sig_pool.append(ed25519_math.sign(seeds[k], msg))
        msg_pool.append(msg)
    pubs = [pubs_pool[i % n_keys] for i in range(batch)]
    sigs = [sig_pool[i % n_keys] for i in range(batch)]
    msgs = [msg_pool[i % n_keys] for i in range(batch)]

    # warm-up: compile + one full pipeline execution
    mask = ed25519_batch.verify_batch(pubs, sigs, msgs)
    assert bool(np.asarray(mask).all()), "benchmark batch failed to verify"

    reps = 3
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        ed25519_batch.verify_batch(pubs, sigs, msgs)
        best = min(best, time.perf_counter() - t0)

    rate = batch / best
    print(
        json.dumps(
            {
                "metric": "ed25519-sig-verifies/sec/chip",
                "value": round(rate, 1),
                "unit": "sigs/s",
                "vs_baseline": round(rate / PER_CHIP_BASELINE, 4),
                "batch": batch,
                "backend": jax.devices()[0].platform,
                "end_to_end": True,
            }
        )
    )


if __name__ == "__main__":
    main()

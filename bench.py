"""Headline benchmark: batched ed25519 signature verification throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
Baseline target (BASELINE.json): >= 1,000,000 sig-verifies/sec on a v5e-4,
i.e. 250k/sec/chip; vs_baseline is measured-chip-rate / 250_000.

Measures END-TO-END throughput — byte parsing + SHA-512 prehash on the
host AND the device kernel — through the production `verify_batch`
pipeline (chunked host/device overlap), not the kernel alone (round-1
bench measured only the kernel; VERDICT round 1 called that out).

Runs on whatever backend JAX selects (the driver provides one real TPU
chip; the Pallas ladder kernel is used there, the portable XLA kernel
elsewhere).
"""
import json
import os
import subprocess
import sys
import time

import numpy as np

BATCH = 131072  # two pipeline chunks
PER_CHIP_BASELINE = 250_000.0  # 1M/s on 4 chips
# notary shard count the system stage runs (and the fingerprint default
# when the stage failed) — ONE knob so the stage, the failed-stage
# fingerprint, and the policy string cannot drift apart
SYSTEM_SHARDS = 4


# One real dispatch proves the backend works end-to-end; shared with
# tools/hw_capture.py so bench and the capture daemon agree on liveness.
PROBE_SNIPPET = (
    "import jax, jax.numpy as jnp\n"
    "d = jax.devices()\n"
    "v = int(jax.jit(lambda x: x.sum())(jnp.arange(8, dtype=jnp.uint32))"
    ".block_until_ready())\n"
    "assert v == 28, v\n"
    "print('PLATFORM=' + d[0].platform)\n"
)


def _probe_backend(timeout_s: int = 120) -> tuple[bool, str | None]:
    """Decide TPU vs CPU by running ONE REAL dispatch in a subprocess.

    `jax.default_backend()` is not enough: the accelerator tunnel can
    register its backend and then die (or hang) at the *first op* — that is
    exactly how BENCH_r02 went rc=1.  A subprocess gives us a hard timeout
    against the hang mode and keeps a failed TPU initialisation from
    poisoning this process's JAX state.  Retries once, then falls back to
    CPU with an honest note.
    """
    code = PROBE_SNIPPET
    note = "no probe attempt ran"
    for attempt in (1, 2):
        try:
            out = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                text=True,
                timeout=timeout_s,
            )
        except subprocess.TimeoutExpired:
            note = f"backend probe hung >{timeout_s}s (attempt {attempt})"
            continue
        for line in out.stdout.splitlines():
            if line.startswith("PLATFORM="):
                platform = line.split("=", 1)[1]
                return platform == "tpu", None
        note = (
            f"backend probe rc={out.returncode} (attempt {attempt}): "
            + out.stderr.strip()[-300:].replace("\n", " | ")
        )
    return False, note + "; CPU fallback"


def _best_tpu_capture() -> tuple[dict, dict] | None:
    """The newest/best in-repo TPU headline datapoint, with provenance.

    Priority: a successful capture from this round's opportunistic daemon
    (tools/hw_capture.py writes tpu_capture/log.jsonl the moment the
    accelerator tunnel answers a probe), then the last driver-recorded
    TPU bench artifact. Returns (result_json, provenance) or None.
    """
    here = os.path.dirname(os.path.abspath(__file__))
    best = None
    try:
        with open(os.path.join(here, "tpu_capture", "log.jsonl")) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                res = rec.get("result") or {}
                if (
                    rec.get("ok")
                    and res.get("backend") == "tpu"
                    and res.get("metric") == "ed25519-sig-verifies/sec/chip"
                    and not res.get("pallas_fallback", False)
                    and (best is None or res["value"] > best[0]["value"])
                ):
                    best = (
                        res,
                        {
                            "source": "tpu_capture/log.jsonl"
                            + f" step={rec.get('step')}",
                            "captured_ts": rec.get("ts"),
                        },
                    )
    except OSError:
        pass
    if best is not None:
        return best
    for name in ("BENCH_r03.json", "BENCH_r02.json", "BENCH_r01.json"):
        try:
            with open(os.path.join(here, name)) as f:
                res = json.load(f).get("parsed") or {}
        except (OSError, ValueError):
            continue
        if res.get("backend") == "tpu" and "value" in res:
            return res, {"source": name}
    return None


def main() -> None:
    # Quiesced, attested measurement window (docs/observability.md):
    # pause the opportunistic capture daemon's probes (each one a fresh
    # `import jax` subprocess that eats seconds of CPU on the 1-core
    # driver box — the round-5 host regression's attributed cause) for
    # the whole bench via the cross-process QUIESCE handshake, and stamp
    # the record with the environment fingerprint the regression gate
    # compares before trusting a cross-round diff.
    from corda_tpu.utils import quiesce as _quiesce

    with _quiesce.quiesce(expected_s=4 * 3600):
        _measured_main(_quiesce)


def _measured_main(_quiesce) -> None:
    force_cpu = os.environ.get("CORDA_TPU_BENCH_FORCE_CPU") == "1"
    if force_cpu:
        on_tpu, tunnel_note = False, "forced CPU (mid-bench tunnel death retry)"
    else:
        on_tpu, tunnel_note = _probe_backend()

    import jax

    if not on_tpu:
        # must happen before any other jax use; env vars alone don't stick
        # (the accelerator sitecustomize latches JAX_PLATFORMS)
        jax.config.update("jax_platforms", "cpu")

    import corda_tpu  # noqa: F401  (enables the persistent compile cache)
    from corda_tpu.core.crypto import ed25519_math
    from corda_tpu.ops import ed25519_batch

    # On CPU the production dispatch routes to the host OpenSSL path
    # (backend-aware dispatch, VERDICT r3 #2) — measure THAT, at a batch
    # it handles in a few hundred ms, not the 131072-row device pipeline.
    batch = BATCH if on_tpu else 4096

    t_start = time.perf_counter()
    rng = np.random.default_rng(7)
    n_keys = 256  # realistic notary batch: many txs from few parties
    seeds = [rng.bytes(32) for _ in range(n_keys)]
    pubs_pool = [ed25519_math.public_from_seed(s) for s in seeds]
    sig_pool = []
    msg_pool = []
    for k in range(n_keys):
        msg = rng.bytes(64)
        sig_pool.append(ed25519_math.sign(seeds[k], msg))
        msg_pool.append(msg)
    pubs = [pubs_pool[i % n_keys] for i in range(batch)]
    sigs = [sig_pool[i % n_keys] for i in range(batch)]
    msgs = [msg_pool[i % n_keys] for i in range(batch)]

    if on_tpu:
        # warm-up: compile + one full pipeline execution
        mask = ed25519_batch.verify_batch(pubs, sigs, msgs)
        assert bool(np.asarray(mask).all()), "benchmark batch failed to verify"

        reps = 3
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            ed25519_batch.verify_batch(pubs, sigs, msgs)
            best = min(best, time.perf_counter() - t0)
        rate = batch / best
    else:
        # the production scheme dispatch: on the CPU backend this is the
        # host OpenSSL path in a thread pool, NOT the portable XLA kernel
        from corda_tpu.core.crypto import batch as crypto_batch
        from corda_tpu.core.crypto.keys import SchemePublicKey
        from corda_tpu.core.crypto.schemes import EDDSA_ED25519_SHA512

        from corda_tpu.core.crypto import host_batch

        code = EDDSA_ED25519_SHA512.scheme_code_name
        items = [
            (SchemePublicKey(code, pubs[i]), sigs[i], msgs[i])
            for i in range(batch)
        ]
        # label what the staged dispatch will ACTUALLY do for this run (an
        # overridden DISPATCH or configured mesh routes to the device
        # kernels even on a CPU backend — the record must say so)
        if crypto_batch._use_device_kernels() and (
            batch >= crypto_batch.MIN_DEVICE_BATCH
        ):
            cpu_path = "device-kernel"
        elif host_batch.available():
            cpu_path = "native-msm-batch"
        else:
            cpu_path = "host-openssl-pool"
        assert all(crypto_batch.verify_batch(items)), "bench batch failed"
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            crypto_batch.verify_batch(items)
            best = min(best, time.perf_counter() - t0)
        rate = batch / best

    # KERNEL BENCH FIRST (ROADMAP item 1): the headline record is fully
    # assembled — and, when live-TPU, PERSISTED — before any secondary
    # stage runs. A revived tunnel that dies mid-secondaries used to
    # discard the already-measured live kernel number when the CPU
    # re-exec replayed an old artifact; now the inline capture below is
    # exactly what _best_tpu_capture() picks up in that re-exec.
    if on_tpu:
        record = {
            "metric": "ed25519-sig-verifies/sec/chip",
            "value": round(rate, 1),
            "unit": "sigs/s",
            "vs_baseline": round(rate / PER_CHIP_BASELINE, 4),
            "batch": batch,
            "backend": jax.devices()[0].platform,
            # a TPU number served by the XLA fallback (or with the
            # fast-mul variants silently dropped) must be visibly
            # tagged — hw_capture refuses to mark such runs captured
            "pallas_fallback": ed25519_batch._pallas_failed_once,
            "fast_mul": _kernel_flag("_FAST_MUL_ENABLED"),
            "radix13": _kernel_flag("_RADIX13_ENABLED"),
            "end_to_end": True,
            "provenance": {"live": True},
        }
    else:
        # The tunnel is dark (or this box has no accelerator): the
        # headline stays a REAL TPU datapoint — the newest in-repo
        # capture, provenance-stamped — and the live host-path dispatch
        # rate rides along as its own honestly-labelled key (r3 VERDICT
        # #1b: a 90 sigs/s CPU line is not the framework's TPU number).
        cap = _best_tpu_capture()
        if cap is not None:
            res, prov = cap
            record = {
                "metric": "ed25519-sig-verifies/sec/chip",
                "value": res["value"],
                "unit": "sigs/s",
                "vs_baseline": round(res["value"] / PER_CHIP_BASELINE, 4),
                "batch": res.get("batch"),
                "backend": "tpu",
                # a replayed number carries the ORIGINAL measurement's
                # semantics (r4 VERDICT weak #2): nothing is upgraded in
                # replay. r01's bench measured the kernel alone, so a
                # record without an explicit end_to_end stays False here.
                "end_to_end": bool(res.get("end_to_end", False)),
                # the source record rides verbatim so the replay can
                # never misdescribe what was measured
                "provenance": {"live": False, **prov, "source_record": res},
                "cpu_dispatch_sigs_s": round(rate, 1),
                "cpu_dispatch_batch": batch,
                "cpu_dispatch_path": cpu_path,
            }
        else:  # no TPU datapoint anywhere in the repo: report CPU honestly
            record = {
                "metric": "ed25519-sig-verifies/sec/chip",
                "value": round(rate, 1),
                "unit": "sigs/s",
                "vs_baseline": round(rate / PER_CHIP_BASELINE, 4),
                "batch": batch,
                "backend": "cpu",
                "end_to_end": True,
                "cpu_dispatch_path": cpu_path,
            }
    if tunnel_note:
        record["note"] = tunnel_note
    if on_tpu and record.get("provenance", {}).get("live"):
        _persist_inline_capture(record)

    # Secondary BASELINE.md configs: ECDSA and the mixed-scheme batch
    # through the production scheme-bucketing dispatch (VERDICT round 1
    # asked for both; they ride the same single JSON line as extra keys).
    # Deliberately AFTER the headline record exists: the kernel number is
    # the first thing attested, never hostage to a secondary stage.
    extras = {}
    if os.environ.get("CORDA_TPU_BENCH_HEADLINE_ONLY") == "1":
        # tools/hw_capture.py sweeps configs on a flaky tunnel: each
        # config must cost one kernel compile, not the whole secondary set
        extras["secondary_skipped"] = "headline-only mode"
    elif time.perf_counter() - t_start > 900:
        # compiles/tunnel already ate the budget: ship the headline alone
        extras["secondary_skipped"] = "headline exceeded 900s"
    else:
        try:
            extras.update(_secondary_rates(on_tpu, rng))
        except Exception as exc:  # secondaries must never sink the headline
            extras["secondary_error"] = f"{type(exc).__name__}: {exc}"

    # attestation: what kind of window produced these numbers (the gate
    # refuses to hard-compare records whose fingerprints differ)
    record["quiesced"] = _quiesce.is_quiesced()
    record.update(extras)
    # fingerprint AFTER the stage keys merge: the system stage enables
    # sharding by parameter (not env), and the topology it actually ran
    # is part of what makes two records comparable. When the stage
    # FAILED (no system_* keys) stamp the CONFIGURED topology — a
    # missing/zero stamp would mismatch the baseline's and demote every
    # unrelated regression to a warning, disarming the gate in exactly
    # the rounds where a flaky system stage co-occurs with a real one.
    record["env_fingerprint"] = _quiesce.env_fingerprint(
        shards=record.get("system_shards", SYSTEM_SHARDS),
        node_workers=record.get("system_node_workers", 0),
    )
    print(json.dumps(record))

    if "--gate" in sys.argv:
        # regression gate: hand this run's record to tools/bench_gate.py,
        # which compares it against the newest BENCH_r*.json round
        # artifact; a >20% stage-timing regression fails the bench run
        here = os.path.dirname(os.path.abspath(__file__))
        gate_cmd = [
            sys.executable, os.path.join(here, "tools", "bench_gate.py"),
            "--current", "-", "--repo", here, "--opbudget", "--lint",
        ]
        # the fleet-observatory A/B asserts an ABSOLUTE ceiling too (the
        # relative gate would pass a 0%->huge jump on a fresh baseline):
        # observation overhead above the noise floor fails the round
        if isinstance(
            record.get("stage_timings", {}).get("fleet_observe_overhead_pct"),
            (int, float),
        ):
            gate_cmd += [
                "--slo", "stage_timings.fleet_observe_overhead_pct<=25",
            ]
        if isinstance(
            record.get("stage_timings", {}).get(
                "kernel_observe_overhead_pct"
            ),
            (int, float),
        ):
            gate_cmd += [
                "--slo", "stage_timings.kernel_observe_overhead_pct<=25",
            ]
        proc = subprocess.run(
            gate_cmd,
            input=json.dumps(record), text=True,
            stdout=subprocess.DEVNULL,  # gate detail goes to stderr; the
        )                               # record stays this run's only stdout
        if proc.returncode != 0:
            raise SystemExit(proc.returncode)


def _persist_inline_capture(record: dict) -> None:
    """Append a LIVE TPU headline to tpu_capture/log.jsonl the moment it
    is measured — the same record shape the opportunistic capture daemon
    writes, so a mid-secondaries tunnel death (which re-execs the bench
    CPU-pinned) replays THIS round's kernel number via
    _best_tpu_capture() instead of an older artifact."""
    here = os.path.dirname(os.path.abspath(__file__))
    # tpu_capture join: stamp the kernel flight ledger so every record
    # the live run produced (and produces) carries provenance.live —
    # a /kernels drain or kernel_report of this run is attributable to
    # the same capture event the headline cites
    from corda_tpu.utils import profiling as _profiling

    _profiling.annotate_provenance({"live": True, "step": "bench-inline"})
    try:
        os.makedirs(os.path.join(here, "tpu_capture"), exist_ok=True)
        with open(os.path.join(here, "tpu_capture", "log.jsonl"), "a") as f:
            f.write(json.dumps({
                "ok": True,
                "step": "bench-inline",
                "ts": time.time(),
                "result": dict(record),
            }) + "\n")
    except OSError as exc:
        print(f"bench: inline capture persist failed: {exc}",
              file=sys.stderr)


def _kernel_flag(name: str) -> bool:
    from corda_tpu.ops import ed25519_pallas

    return getattr(ed25519_pallas, name)


def _codec_encode_us(n: int = 2000) -> float:
    """Microbench the codec encode seam on the hot wire shape: one
    serialize() of a realistic SignedTransaction (tx bytes + sigs)
    through the production codec (native if built, else the pure-Python
    fast path). Returns mean us per encode."""
    from corda_tpu.core.contracts import Amount
    from corda_tpu.core.contracts.amount import Issued
    from corda_tpu.core.crypto import crypto
    from corda_tpu.core.identity import Party
    from corda_tpu.core.serialization.codec import serialize
    from corda_tpu.core.transactions.builder import TransactionBuilder
    from corda_tpu.finance.cash import CashCommand, CashState

    kp = crypto.entropy_to_keypair(12)
    me = Party("O=CodecBench,L=London,C=GB", kp.public)
    token = Issued(me.ref(1), "USD")
    b = TransactionBuilder(notary=me)
    b.add_output_state(CashState(amount=Amount(100, token), owner=me))
    b.add_command(CashCommand.Issue(), kp.public)
    wtx = b.to_wire_transaction()
    from corda_tpu.core.crypto.signing import DigitalSignatureWithKey
    from corda_tpu.core.transactions.signed import SignedTransaction

    stx = SignedTransaction.of(wtx, [
        DigitalSignatureWithKey(
            bytes=crypto.do_sign(kp.private, wtx.id.bytes), by=kp.public
        )
    ])
    serialize(stx)  # warm the per-type encoder caches
    t0 = time.perf_counter()
    for _ in range(n):
        serialize(stx)
    return (time.perf_counter() - t0) / n * 1e6


def _bls_aggregate_stage(n: int = 64) -> dict:
    """Committee aggregate-vs-naive verification A/B: n per-vote
    verifies vs aggregation + ONE 2-pairing check, measured by the
    shared loadtest helper (docs/bls-aggregation.md)."""
    from corda_tpu.loadtest.latency import measure_bls_aggregate_ab

    return measure_bls_aggregate_ab(n=n)


def _mesh_scaling_stage(on_tpu: bool, ns=(0, 1, 2, 4, 8),
                        rows: int = 256) -> dict:
    """The mesh scaling curve: `mesh_sigs_s{n=N}` for each point, one
    SUBPROCESS per N (docs/perf-pipeline.md mesh stage).

    A subprocess per point is structural, not caution: the forced host
    device count (--xla_force_host_platform_device_count) binds when the
    CPU backend first initializes, so one process cannot measure n=2 and
    n=8 — the same reason tools/tune_kernel.py sweeps configs out of
    process. n=0 is the all-off comparator (CORDA_TPU_MESH_DEVICES=0):
    the same rows through today's single-device ops path, beside the
    sharded points so the curve reads against the kill switch. Points
    ride stage_timings, so the regression gate direction-classifies them
    (higher-is-better, the `{n=...}` label stripped by gate.direction)."""
    import re as _re

    here = os.path.dirname(os.path.abspath(__file__))
    out = {}
    for n in ns:
        env = dict(os.environ)
        env["CORDA_TPU_MESH_DEVICES"] = str(n)
        if not on_tpu:
            flags = _re.sub(
                r"--xla_force_host_platform_device_count=\d+", "",
                env.get("XLA_FLAGS", ""),
            ).strip()
            env["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={max(n, 1)}"
            ).strip()
            env["JAX_PLATFORMS"] = "cpu"
        key = f"mesh_sigs_s{{n={n}}}"
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "corda_tpu.parallel.mesh",
                 "--bench", "--devices", str(n), "--rows", str(rows),
                 "--repeats", "2"],
                capture_output=True, text=True, timeout=600,
                env=env, cwd=here,
            )
            rec = json.loads(proc.stdout.splitlines()[-1])
            out[key] = rec["sigs_s"]
        except Exception as exc:  # one dead point must not sink the curve
            out[f"mesh_stage_error{{n={n}}}"] = (
                f"{type(exc).__name__}: {exc}"
            )
    return out


def _secondary_rates(on_tpu: bool, rng) -> dict:
    """ECDSA-P256 and mixed-scheme throughput via the production
    `core.crypto.batch.verify_batch` dispatch (scheme bucketing)."""
    import time

    from corda_tpu.core.crypto import crypto
    from corda_tpu.core.crypto import batch as crypto_batch
    from corda_tpu.core.crypto.schemes import (
        ECDSA_SECP256R1_SHA256,
        EDDSA_ED25519_SHA512,
    )

    def build(scheme, n_keys, count):
        kps = [crypto.generate_keypair(scheme) for _ in range(n_keys)]
        items = []
        for i in range(count):
            kp = kps[i % n_keys]
            msg = rng.bytes(48)
            items.append((kp.public, crypto.do_sign(kp.private, msg), msg))
        return items

    # sizes sit on kernel bucket boundaries so each path compiles once
    ecdsa_n = 4096 if on_tpu else 1024
    ed_n = 4096 if on_tpu else 1024
    ecdsa_items = build(ECDSA_SECP256R1_SHA256, 32, ecdsa_n)
    ed_items = build(EDDSA_ED25519_SHA512, 32, ed_n)

    def rate_of(items):
        assert all(crypto_batch.verify_batch(items))  # warm-up + correctness
        best = float("inf")
        for _ in range(2):  # best-of-2: robust to one probe collision
            t0 = time.perf_counter()
            crypto_batch.verify_batch(items)
            best = min(best, time.perf_counter() - t0)
        return len(items) / best

    ecdsa_rate = rate_of(ecdsa_items)

    # BASELINE.md multi-sig config: CompositeKey threshold trees whose
    # constituents flatten into the device batch, tree evaluated over the
    # returned bitmask (3 ed25519 leaves per item, threshold 2).
    from corda_tpu.core.crypto.composite import (
        CompositeKey,
        CompositeSignaturesWithKeys,
    )

    comp_n = 2048 if on_tpu else 256
    leaf_kps = [
        crypto.generate_keypair(EDDSA_ED25519_SHA512) for _ in range(24)
    ]
    comp_items = []
    for i in range(comp_n):
        kps = [leaf_kps[(i + j) % len(leaf_kps)] for j in range(3)]
        builder = CompositeKey.Builder()
        for kp in kps:
            builder.add_key(kp.public)
        ckey = builder.build(2)
        content = rng.bytes(40)
        pairs = tuple(
            (kp.public, crypto.do_sign(kp.private, content)) for kp in kps
        )
        comp_items.append(
            (ckey, CompositeSignaturesWithKeys(pairs).serialize(), content)
        )
    composite_rate = rate_of(comp_items)

    mixed = []
    for i in range(max(len(ecdsa_items), len(ed_items))):
        if i < len(ed_items):
            mixed.append(ed_items[i])
        if i < len(ecdsa_items):
            mixed.append(ecdsa_items[i])
    mixed_rate = rate_of(mixed)

    # p50 notarise latency (BASELINE.md notary-demo config): full
    # NotaryFlow rounds over a burst of independent spends
    from corda_tpu.loadtest.latency import measure_notarise_latency

    lat = measure_notarise_latency(n_tx=256 if on_tpu else 64)

    # Bulk-settlement burst: transactions carrying 1024 signatures each
    # drive the notary's cross-transaction SignatureBatcher to
    # device-worthy flushes through the production NotaryFlow path
    # (r3 VERDICT #7: largest_batch >= 1024 in a full-flow run)
    from corda_tpu.loadtest.latency import measure_notarise_burst

    burst = measure_notarise_burst(n_signers=1024, n_tx=4)

    # BASELINE.md notary-demo config: p50 @ 10k-tx uniqueness batch
    # (the reference harness size, NotaryTest.kt:25-53 — r3 VERDICT #6),
    # against the single-node commit log AND a 3-member Raft cluster.
    from corda_tpu.loadtest.latency import measure_uniqueness_batch

    uniq = measure_uniqueness_batch(n_tx=10_000)

    # Per-stage seam timings (VERDICT open item 2): each pipeline stage
    # reports its own number so a system-path regression can be
    # attributed to a stage instead of guessed at. Codec from the encode
    # microbench; uniqueness from the commit coalescer's telemetry;
    # batcher flush wall time from the settlement burst's batcher.
    try:
        codec_us = round(_codec_encode_us(), 2)
    except Exception:
        codec_us = None
    # Failover time-to-recovery (docs/robustness.md): kill the sole
    # verifier worker after ack, measure how long in-flight signature
    # futures take to complete via redispatch/fallback — the gate then
    # guards recovery latency like any other stage.
    from corda_tpu.loadtest.latency import measure_failover_recovery

    try:
        failover = measure_failover_recovery()
    except Exception as exc:
        failover = {"error": f"{type(exc).__name__}: {exc}"}

    # Cold restart-to-serving over a loaded durable state (docs/
    # robustness.md §7): 10k-enqueued/5k-acked journal replay + 200
    # checkpoint restores, one lower-is-better number the gate guards
    # so a recovery-path regression trips CI before a real crash does.
    from corda_tpu.loadtest.latency import measure_recovery_replay

    try:
        recovery = measure_recovery_replay()
    except Exception as exc:
        recovery = {"error": f"{type(exc).__name__}: {exc}"}

    # Overload protection (docs/robustness.md): saturate the admission
    # cap with a 5x flow-start burst, verify the excess sheds (typed
    # rejection + /readyz 503), then measure time-to-recover after the
    # load drops plus the goodput the node sustained through the event —
    # both guarded by the regression gate (_ms is auto-classified
    # lower-is-better, _per_sec higher-is-better).
    from corda_tpu.loadtest.latency import measure_overload_shed_recovery

    try:
        overload = measure_overload_shed_recovery()
    except Exception as exc:
        overload = {"error": f"{type(exc).__name__}: {exc}"}

    # BLS committee aggregation A/B (docs/bls-aggregation.md): the
    # n=64 aggregate-vs-naive stage rides the regression gate through
    # its _ms keys (lower-is-better auto-classification)
    try:
        bls = _bls_aggregate_stage(n=64)
    except Exception as exc:
        bls = {"bls_stage_error": f"{type(exc).__name__}: {exc}"}

    # Overlapped-pipeline A/B (docs/perf-pipeline.md, ROADMAP item 3):
    # the same staged phase functions run back-to-back vs through the
    # verifier pipeline engine, proving the host SHA-512 prehash hides
    # behind the dispatch engine. `pipeline_overlap_ratio` /
    # `pipeline_prehash_hidden_pct` gate higher-is-better; the
    # `pipeline_*_wall_ms` family lower-is-better. Overlap needs >= 2
    # cores (`pipeline_cpus` rides the record; cpus is also part of the
    # env fingerprint the gate compares before trusting a diff).
    from corda_tpu.loadtest.latency import measure_pipeline_overlap

    try:
        pipe_ab = measure_pipeline_overlap()
    except Exception as exc:
        pipe_ab = {"pipeline_stage_error": f"{type(exc).__name__}: {exc}"}

    # GIL-escaped message plane (ISSUE 12, docs/perf-system.md round
    # 16): the native batch codec vs the pure-Python fast path (the
    # ≥3x acceptance A/B — byte parity asserted inside), and the
    # end-to-end wire-layer drain rate through BrokerServer +
    # RemoteBroker (`pump_drain_msgs_s`, higher-is-better gated). Like
    # the pipeline overlap, the PARALLELISM win needs ≥2 cores — on a
    # 1-core box native≈python for the drain, and cpus rides the env
    # fingerprint the gate compares.
    from corda_tpu.loadtest.latency import measure_codec_batch
    from corda_tpu.loadtest.latency import measure_pump_drain

    try:
        codec_batch = measure_codec_batch()
    except Exception as exc:
        codec_batch = {
            "codec_batch_error": f"{type(exc).__name__}: {exc}"
        }
    try:
        pump_drain = measure_pump_drain()
    except Exception as exc:
        pump_drain = {"pump_drain_error": f"{type(exc).__name__}: {exc}"}

    # Bank-side flow hot path (ISSUE 15, docs/perf-system.md round 20):
    # (1) coin selection must stay FLAT as the vault grows (the decoded
    # cache + availability buckets vs the old per-query full-vault
    # deserialize — `coin_select_us_per_pick` gates lower-is-better);
    # (2) checkpoint group commit at FULL durability — concurrent flows'
    # step commits coalescing into one fsync per drain window
    # (`checkpoint_*_flows_s` gate higher-is-better); (3) laned vs
    # on-pump flow execution over an in-process broker rig (the
    # multi-lane executor A/B; like the r15/r16 stages, the wall-clock
    # win needs >= 2 cores — cpus rides the env fingerprint).
    from corda_tpu.loadtest.latency import (
        measure_checkpoint_group_commit,
        measure_coin_selection,
        measure_flow_lane_ab,
    )

    try:
        coin_select = measure_coin_selection()
    except Exception as exc:
        coin_select = {"coin_select_error": f"{type(exc).__name__}: {exc}"}
    try:
        cp_group = measure_checkpoint_group_commit()
    except Exception as exc:
        cp_group = {"checkpoint_gc_error": f"{type(exc).__name__}: {exc}"}
    try:
        lane_ab = measure_flow_lane_ab()
    except Exception as exc:
        lane_ab = {"flow_lane_error": f"{type(exc).__name__}: {exc}"}

    # Fleet-observatory A/B (docs/observability.md): the same notarise
    # workload bare vs under a live OpsServer + FleetCollector poll loop
    # — observation must stay within run-to-run noise of the hot path.
    from corda_tpu.loadtest.observatory import measure_fleet_observe_overhead

    try:
        fleet_ab = measure_fleet_observe_overhead()
    except Exception as exc:
        fleet_ab = {"fleet_observe_error": f"{type(exc).__name__}: {exc}"}

    # Device-plane kernel-ledger A/B (docs/observability.md "Device
    # plane"): ledger killed vs ledger + a collector draining /kernels
    # — per-dispatch recording must stay within run-to-run noise too.
    from corda_tpu.loadtest.observatory import (
        measure_kernel_observe_overhead,
    )

    try:
        kernel_ab = measure_kernel_observe_overhead()
    except Exception as exc:
        kernel_ab = {"kernel_observe_error": f"{type(exc).__name__}: {exc}"}

    # Mesh-sharded dispatch scaling curve (docs/perf-pipeline.md): the
    # `mesh_sigs_s{n=...}` points, one virtual-device subprocess per N,
    # with the CORDA_TPU_MESH_DEVICES=0 comparator at n=0.
    try:
        mesh_curve = _mesh_scaling_stage(on_tpu)
    except Exception as exc:
        mesh_curve = {"mesh_stage_error": f"{type(exc).__name__}: {exc}"}

    # device-dispatch telemetry accumulated across the whole secondary
    # run (the same recorder the ops endpoint's Jax.* gauges read)
    from corda_tpu.utils import profiling
    from corda_tpu.utils import quiesce as _q

    stage_timings = {
        # every measurement stage above ran inside the bench's quiesce
        # window (probe daemons paused); a record claiming otherwise
        # is a record taken outside bench.py's main()
        "quiesced": _q.is_quiesced(),
        "codec_encode_us_per_tx": codec_us,
        "uniq_commit_batch_mean": uniq["raft_commit_batch_mean"],
        "uniq_commit_batches": uniq["raft_commit_batches"],
        "uniq_commit_batch_max": uniq["raft_commit_batch_max"],
        "batcher_flush_wall_s": burst.get("batcher_flush_wall_s"),
        "batcher_handoffs": burst.get("batcher_handoffs"),
        # per-hop critical path from the tracing spine (p50/p99 per span
        # name over the notarise-latency run): the per-REQUEST view next
        # to the aggregate stage numbers, so a regression names its hop
        "critical_path": lat.get("span_summary"),
        "jax_dispatch": profiling.dispatch_snapshot(),
        "failover_recovery_ms": failover.get("failover_recovery_ms"),
        "failover_recovered_via": failover.get("recovered_via"),
        "recovery_replay_ms": recovery.get("recovery_replay_ms"),
        "recovery_pending_msgs": recovery.get("recovery_pending_msgs"),
        "overload_shed_recovery_ms": overload.get(
            "overload_shed_recovery_ms"
        ),
        "overload_goodput_per_sec": overload.get("overload_goodput_per_sec"),
        "bls_naive_wall_ms": bls.get("bls_naive_wall_ms"),
        "bls_aggregate_verify_ms": bls.get("bls_aggregate_verify_ms"),
        "pipeline_sync_wall_ms": pipe_ab.get("pipeline_sync_wall_ms"),
        "pipeline_pipelined_wall_ms": pipe_ab.get(
            "pipeline_pipelined_wall_ms"
        ),
        "pipeline_prehash_wall_ms": pipe_ab.get("pipeline_prehash_wall_ms"),
        "pipeline_overlap_ratio": pipe_ab.get("pipeline_overlap_ratio"),
        "pipeline_prehash_hidden_pct": pipe_ab.get(
            "pipeline_prehash_hidden_pct"
        ),
        "codec_batch_native_us_per_obj": codec_batch.get(
            "codec_batch_native_us_per_obj"
        ),
        "codec_batch_python_us_per_obj": codec_batch.get(
            "codec_batch_python_us_per_obj"
        ),
        "codec_batch_speedup_x": codec_batch.get("codec_batch_speedup_x"),
        "pump_drain_msgs_s": pump_drain.get("pump_drain_msgs_s"),
        "coin_select_us_per_pick": coin_select.get("coin_select_us_per_pick"),
        "checkpoint_group_commit_flows_s": cp_group.get(
            "checkpoint_group_commit_flows_s"
        ),
        "checkpoint_per_step_flows_s": cp_group.get(
            "checkpoint_per_step_flows_s"
        ),
        "checkpoint_group_commit_speedup_x": cp_group.get(
            "checkpoint_group_commit_speedup_x"
        ),
        "flow_lane_pairs_s": lane_ab.get("flow_lane_pairs_s"),
        "flow_lane_sync_pairs_s": lane_ab.get("flow_lane_sync_pairs_s"),
        "fleet_observe_off_per_sec": fleet_ab.get(
            "fleet_observe_off_per_sec"
        ),
        "fleet_observe_on_per_sec": fleet_ab.get("fleet_observe_on_per_sec"),
        "fleet_observe_overhead_pct": fleet_ab.get(
            "fleet_observe_overhead_pct"
        ),
        "kernel_observe_off_per_sec": kernel_ab.get(
            "kernel_observe_off_per_sec"
        ),
        "kernel_observe_on_per_sec": kernel_ab.get(
            "kernel_observe_on_per_sec"
        ),
        "kernel_observe_overhead_pct": kernel_ab.get(
            "kernel_observe_overhead_pct"
        ),
        # the flight ledger's derived roofline view for THIS run: what
        # the engaged kernels actually achieved vs the per-backend peak
        # (docs/perf-roofline.md "attainment is MEASURED")
        "kernel_attainment": profiling.attainment(),
    }
    stage_timings.update(mesh_curve)
    out = {
        "uniq_batch_n_tx": uniq["n_tx"],
        "uniq_raft_p50_ms": uniq["raft_p50_ms"],
        "uniq_raft_commits_s": uniq["raft_commits_s"],
        "uniq_single_p50_ms": uniq["single_p50_ms"],
        "uniq_single_commits_s": uniq["single_commits_s"],
        "uniq_commit_batch_mean": uniq["raft_commit_batch_mean"],
        "codec_encode_us_per_tx": codec_us,
        "stage_timings": stage_timings,
        "ecdsa_p256_sigs_s": round(ecdsa_rate, 1),
        "composite_items_s": round(composite_rate, 1),
        "composite_batch": comp_n,
        "mixed_scheme_sigs_s": round(mixed_rate, 1),
        "mixed_batch": len(mixed),
        "p50_notarise_ms": lat["p50_ms"],
        "p95_notarise_ms": lat["p95_ms"],
        "p99_notarise_ms": lat["p99_ms"],
        "notarise_burst": lat["n_tx"],
        "settlement_burst_sigs_s": burst["sigs_per_sec"],
        "batcher_flushes": burst["batcher_flushes"],
        "batcher_largest_batch": burst["batcher_largest_batch"],
        "overload_burst": overload.get("burst"),
        "overload_shed": overload.get("shed"),
        "overload_admitted": overload.get("admitted"),
    }
    out.update(bls)
    out.update(pipe_ab)
    out.update(codec_batch)
    out.update(pump_drain)
    out.update(coin_select)
    out.update(cp_group)
    out.update(lane_ab)
    out.update(fleet_ab)
    out.update(kernel_ab)

    # Full-system throughput: issue+pay pairs through REAL node processes
    # (cordform network, TCP brokers, bridges, validating notary) — the
    # kernel->system gap metric (round-2 VERDICT #4). Saturation config
    # measured round 3; see docs/perf-system.md for the breakdown.
    # SHARDING ENABLED from round 13 (docs/sharding.md): the notary runs
    # the 4-shard partitioned uniqueness provider — `system_policy`
    # records the config change so rounds compare like with like.
    # BEST OF TWO runs: the measurement window is seconds long on a
    # 1-core box that also hosts the capture daemon's periodic probes —
    # a probe landing inside one window halves that reading (observed:
    # 34 vs a consistent ~78-86 standalone), and the max of two
    # independent windows is robust to a single collision.
    try:
        from corda_tpu.loadtest.real import run as loadtest_run

        runs, failures = [], []
        for _ in range(2):
            try:
                runs.append(loadtest_run(
                    pairs=120, parallelism=8, shards=SYSTEM_SHARDS,
                ))
            except Exception as exc:  # one failed launch must not sink
                failures.append(f"{type(exc).__name__}: {exc}")
        if runs:
            best = max(
                runs, key=lambda r: (r["errors"] == 0, r["pairs_per_sec"])
            )
            # TWO names, ONE reading, on purpose: the trajectory key the
            # driver has captured since round 2 (the stage now runs
            # sharded), and the r13 stage name that pairs with
            # `system_unsharded_pairs_s` below for the same-window A/B
            out["system_notarised_pairs_s"] = best["pairs_per_sec"]
            out["system_sharded_pairs_s"] = best["pairs_per_sec"]
            out["system_shards"] = best.get("shards", SYSTEM_SHARDS)
            # the fingerprint stamps the topology the stage ACTUALLY ran
            # (env_fingerprint reads this key, not the env var)
            out["system_node_workers"] = best.get("node_workers", 0)
            # errors SUM across runs: a flaky window must stay visible
            # even when the clean window supplies the rate
            out["system_pairs_errors"] = sum(r["errors"] for r in runs)
            # methodology changed in r5 (was ONE window at pairs=80) and
            # again in r13 (notary shards=4)
            out["system_policy"] = (
                f"best-of-2 x 120 pairs, notary shards={SYSTEM_SHARDS}"
            )
            out["system_runs_pairs_s"] = [
                round(r["pairs_per_sec"], 2) for r in runs
            ]
        if failures:
            out["system_run_failures"] = failures
        if not runs:
            out["system_error"] = failures[0]
        # 1-shard comparator for the A/B (same box, same window): the
        # unsharded notary config the rounds before r13 measured
        try:
            unsharded = loadtest_run(pairs=120, parallelism=8)
            out["system_unsharded_pairs_s"] = unsharded["pairs_per_sec"]
        except Exception as exc:
            out["system_unsharded_error"] = f"{type(exc).__name__}: {exc}"
        # Flow-hot-path comparator (ISSUE 15, docs/perf-system.md round
        # 20): the SAME sharded topology with every bank-side lever
        # killed — on-pump dispatch, full-scan coin selection, per-step
        # checkpoint commits. The node processes inherit the env, so
        # this IS the driver-capturable A/B on system_notarised_pairs_s.
        _kill = {
            "CORDA_TPU_FLOW_LANES": "0",
            "CORDA_TPU_VAULT_CACHE": "0",
            "CORDA_TPU_CP_GROUP_COMMIT": "0",
        }
        _saved = {k: os.environ.get(k) for k in _kill}
        try:
            os.environ.update(_kill)
            baseline = loadtest_run(
                pairs=120, parallelism=8, shards=SYSTEM_SHARDS
            )
            out["system_flowpath_baseline_pairs_s"] = (
                baseline["pairs_per_sec"]
            )
        except Exception as exc:
            out["system_flowpath_baseline_error"] = (
                f"{type(exc).__name__}: {exc}"
            )
        finally:
            for k, v in _saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
    except Exception as exc:
        out["system_error"] = f"{type(exc).__name__}: {exc}"

    # Partitioned-commit A/B (docs/sharding.md §scale): 1 shard vs 4
    # shards under 4 OS worker processes on the two-phase provider
    # itself — what the partition structurally buys, isolated from the
    # bank-side flow machinery that dominates the full-system number on
    # a small box. Keys auto-gate (higher-is-better _commits_s).
    try:
        from corda_tpu.loadtest.shard_ab import measure_sharded_commit_ab

        out.update(measure_sharded_commit_ab())
    except Exception as exc:
        out["sharded_ab_error"] = f"{type(exc).__name__}: {exc}"
    return out


if __name__ == "__main__":
    try:
        main()
    except Exception:
        # Last resort: the tunnel passed the probe but died mid-bench.
        # Re-exec once, pinned to CPU, so the driver always gets a JSON
        # line (rc=0) instead of a crash.  The guard env var prevents a
        # retry loop if even the CPU run fails.
        if os.environ.get("CORDA_TPU_BENCH_FORCE_CPU") == "1":
            raise
        import traceback

        traceback.print_exc(file=sys.stderr)
        print("bench: retrying on CPU after mid-run failure", file=sys.stderr)
        env = dict(os.environ, CORDA_TPU_BENCH_FORCE_CPU="1")
        raise SystemExit(
            subprocess.run(
                [sys.executable, __file__, *sys.argv[1:]], env=env
            ).returncode
        )

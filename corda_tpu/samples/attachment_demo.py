"""Attachment demo (reference `samples/attachment-demo/`): one node sends a
transaction referencing an attachment; the recipient fetches the attachment
content from the sender and verifies its hash."""
from __future__ import annotations

from dataclasses import dataclass
from typing import List

from ..core.contracts import Contract, ContractState, TypeOnlyCommandData, contract
from ..core.flows import FinalityFlow, FlowLogic
from ..core.serialization.codec import corda_serializable
from ..core.transactions import TransactionBuilder
from ..testing import MockNetwork


@contract(name="AttachmentContract")
class AttachmentContract(Contract):
    def verify(self, tx) -> None:
        # The attachment must be present in the resolved transaction.
        if not tx.attachments:
            from ..core.contracts import TransactionVerificationError

            raise TransactionVerificationError(tx.id, "attachment missing")


@corda_serializable
@dataclass(frozen=True)
class AttachmentState(ContractState):
    owner: object = None
    contract_name = "AttachmentContract"

    @property
    def participants(self) -> List:
        return [self.owner]


@corda_serializable
@dataclass(frozen=True)
class AttachCmd(TypeOnlyCommandData):
    pass


def main(verbose: bool = True) -> dict:
    log = print if verbose else (lambda *a, **k: None)
    net = MockNetwork()
    notary = net.create_notary_node(validating=True)
    sender = net.create_node("O=Sender,L=London,C=GB")
    recipient = net.create_node("O=Recipient,L=Paris,C=FR")

    data = b"A transcript of Swift v. Tyson, 41 U.S. 1 (1842)" * 100
    att_id = sender.services.attachments.import_attachment(data)
    log(f"uploaded attachment {att_id}")

    b = TransactionBuilder(notary=notary.info)
    b.add_output_state(AttachmentState(owner=recipient.info))
    b.add_command(AttachCmd(), sender.info.owning_key)
    b.add_attachment(att_id)
    stx = sender.services.sign_initial_transaction(b)
    h = sender.start_flow(FinalityFlow(stx), stx)
    net.run_network()
    h.result.result(timeout=10)

    received = recipient.services.attachments.open_attachment(att_id)
    ok = received is not None and received.data == data
    log(f"recipient fetched + verified attachment: {ok}")
    net.stop_nodes()
    assert ok
    return {"attachment_id": str(att_id), "received": ok}


if __name__ == "__main__":
    main()

"""Network visualiser (reference `samples/network-visualiser/`): the
Simulation event stream rendered three ways — aligned terminal text,
JSONL, or an ANIMATED browser map (`--web PORT`) where message pulses
travel node-to-node on an SVG layout while flows light their nodes
(the graphical tier the reference implements in JavaFX; the page is
webserver/static/visualiser.html).  The *simulation engine* lives in
`corda_tpu.testing.simulation`.

Run: python -m corda_tpu.samples.visualiser [--json] [--latency SECONDS]
     python -m corda_tpu.samples.visualiser --web 8350
"""
from __future__ import annotations

import json
import sys
from typing import Optional, TextIO

from ..utils.miniweb import MiniWebServer


class ConsoleVisualiser:
    """Renders SimulationEvents as aligned text lines or JSONL."""

    def __init__(self, stream: Optional[TextIO] = None, as_json: bool = False):
        self._stream = stream or sys.stdout
        self._json = as_json
        self.counts = {"message": 0, "flow": 0, "progress": 0, "clock": 0}

    def attach(self, simulation) -> None:
        simulation.events.subscribe(self.on_event)

    @staticmethod
    def _short(name: str) -> str:
        # "O=Bank of Breakfast Tea,L=London,C=GB" -> "Bank of Breakfast Tea"
        for part in name.split(","):
            if part.startswith("O="):
                return part[2:]
        return name

    def on_event(self, ev) -> None:
        self.counts[ev.kind] = self.counts.get(ev.kind, 0) + 1
        if self._json:
            self._stream.write(
                json.dumps({"kind": ev.kind, **ev.detail}) + "\n"
            )
            return
        d = ev.detail
        if ev.kind == "message":
            line = (
                f"  {self._short(d['from']):>24} ── {d['topic']:<18} ──▶ "
                f"{self._short(d['to'])}  ({d['bytes']}B)"
            )
        elif ev.kind == "flow":
            line = f"[flow {d['event']:<8}] {self._short(d['node'])}: {d['flow']}"
        elif ev.kind == "progress":
            line = f"[progress     ] {self._short(d['node'])}: {d['step']}"
        else:  # clock
            line = f"===== clock -> {d['now']:.0f} ====="
        self._stream.write(line + "\n")


class EventRecorder:
    """Buffers the whole event stream for replay (the web map animates
    the virtual-time run at a human-visible pace client-side)."""

    def __init__(self):
        self.events = []

    def attach(self, simulation) -> None:
        simulation.events.subscribe(
            lambda ev: self.events.append({"kind": ev.kind, **ev.detail})
        )


class WebVisualiser(MiniWebServer):
    """Serves the animated map page + the recorded event stream; POST
    /run re-executes the simulation for a fresh stream.  Built on the
    shared MiniWebServer scaffold (utils/miniweb.py)."""

    pages = {"/": "visualiser.html", "/index.html": "visualiser.html"}

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import threading

        self._lock = threading.Lock()
        self._events: list = []
        self._summary = ""
        super().__init__(host=host, port=port)

    def handle(self, method, path, query, body):
        if method == "GET" and path == "/events":
            # snapshot under the lock, respond AFTER releasing it — a
            # stalled client reading the response must not serialize
            # every other request behind the lock
            with self._lock:
                events = list(self._events)
                summary = self._summary
            return 200, {"events": events, "summary": summary}
        if method == "POST" and path == "/run":
            self.run_simulation()
            with self._lock:
                n = len(self._events)
            return 200, {"events": n}
        return 404, {"error": f"no route {path}"}

    def run_simulation(self) -> dict:
        from ..testing.simulation import IRSSimulation

        sim = IRSSimulation()
        rec = EventRecorder()
        rec.attach(sim)
        try:
            outcome = sim.run()
        finally:
            sim.stop()
        with self._lock:
            self._events = rec.events
            self._summary = (
                f"IRS simulation: {len(rec.events)} events — "
                + ", ".join(f"{k}={v}" for k, v in sorted(outcome.items())
                            if isinstance(v, (int, float, str)))
            )
        return outcome


def main(argv=None) -> dict:
    from ..testing.simulation import IRSSimulation

    argv = list(sys.argv[1:] if argv is None else argv)
    if "--web" in argv:
        import argparse

        ap = argparse.ArgumentParser(prog="corda_tpu.samples.visualiser")
        ap.add_argument("--web", type=int, metavar="PORT", required=True)
        ap.add_argument("--json", action="store_true")
        ap.add_argument("--latency", type=float, default=None)
        web_args = ap.parse_args(argv)
        from ..utils import eventlog

        server = WebVisualiser(port=web_args.web)
        ready = (
            f"visualiser ready at http://127.0.0.1:{server.port}/ "
            "(running the IRS simulation...)"
        )
        print(ready, flush=True)  # launcher protocol line
        eventlog.emit("info", "visualiser", ready)
        server.run_simulation()
        recorded = f"simulation recorded: {len(server._events)} events"
        print(recorded, flush=True)
        eventlog.emit("info", "visualiser", recorded)
        import time as _time

        try:
            while True:
                _time.sleep(3600)
        except KeyboardInterrupt:
            server.stop()
        return {}
    as_json = "--json" in argv
    latency = None
    if "--latency" in argv:
        secs = float(argv[argv.index("--latency") + 1])
        latency = lambda s, r: secs  # noqa: E731
    sim = IRSSimulation(latency_seconds=latency)
    vis = ConsoleVisualiser(as_json=as_json)
    vis.attach(sim)
    try:
        outcome = sim.run()
    finally:
        sim.stop()
    summary = {**outcome, "events": dict(vis.counts)}
    print(json.dumps(summary))
    return summary


if __name__ == "__main__":
    main()

"""Headless network visualiser (reference `samples/network-visualiser/` —
the JavaFX map UI is replaced by a terminal/JSONL event renderer over the
Simulation event stream; the *simulation engine* lives in
`corda_tpu.testing.simulation`).

Run: python -m corda_tpu.samples.visualiser [--json] [--latency SECONDS]
"""
from __future__ import annotations

import json
import sys
from typing import Optional, TextIO


class ConsoleVisualiser:
    """Renders SimulationEvents as aligned text lines or JSONL."""

    def __init__(self, stream: Optional[TextIO] = None, as_json: bool = False):
        self._stream = stream or sys.stdout
        self._json = as_json
        self.counts = {"message": 0, "flow": 0, "progress": 0, "clock": 0}

    def attach(self, simulation) -> None:
        simulation.events.subscribe(self.on_event)

    @staticmethod
    def _short(name: str) -> str:
        # "O=Bank of Breakfast Tea,L=London,C=GB" -> "Bank of Breakfast Tea"
        for part in name.split(","):
            if part.startswith("O="):
                return part[2:]
        return name

    def on_event(self, ev) -> None:
        self.counts[ev.kind] = self.counts.get(ev.kind, 0) + 1
        if self._json:
            self._stream.write(
                json.dumps({"kind": ev.kind, **ev.detail}) + "\n"
            )
            return
        d = ev.detail
        if ev.kind == "message":
            line = (
                f"  {self._short(d['from']):>24} ── {d['topic']:<18} ──▶ "
                f"{self._short(d['to'])}  ({d['bytes']}B)"
            )
        elif ev.kind == "flow":
            line = f"[flow {d['event']:<8}] {self._short(d['node'])}: {d['flow']}"
        elif ev.kind == "progress":
            line = f"[progress     ] {self._short(d['node'])}: {d['step']}"
        else:  # clock
            line = f"===== clock -> {d['now']:.0f} ====="
        self._stream.write(line + "\n")


def main(argv=None) -> dict:
    from ..testing.simulation import IRSSimulation

    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    latency = None
    if "--latency" in argv:
        secs = float(argv[argv.index("--latency") + 1])
        latency = lambda s, r: secs  # noqa: E731
    sim = IRSSimulation(latency_seconds=latency)
    vis = ConsoleVisualiser(as_json=as_json)
    vis.attach(sim)
    try:
        outcome = sim.run()
    finally:
        sim.stop()
    summary = {**outcome, "events": dict(vis.counts)}
    print(json.dumps(summary))
    return summary


if __name__ == "__main__":
    main()

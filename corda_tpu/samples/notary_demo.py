"""Notary demo (reference `samples/notary-demo/` Single/Raft/BFT
cordforms): notarise a stream of transactions, then demonstrate
double-spend rejection.

Modes: (default) single validating notary; `--raft` a 3-member Raft
cluster behind one composite identity with leader forwarding; `--bft` a
4-member PBFT cluster returning f+1 replica signatures.
"""
from __future__ import annotations

import sys

from ..core.contracts import Amount, Issued
from ..finance import CashIssueFlow, CashPaymentFlow
from ..node.notary import NotaryException
from ..testing import MockNetwork


def main(n_transactions: int = 10, verbose: bool = True,
         mode: str = "single") -> dict:
    log = print if verbose else (lambda *a, **k: None)
    net = MockNetwork()
    if mode == "raft":
        notary_party, members, _bus = net.create_raft_notary_cluster(3)
        notary = type("C", (), {"info": notary_party})()
        log(f"raft notary cluster: {len(members)} members, composite "
            f"identity {notary_party.name}")
    elif mode == "bft":
        notary_party, members, _bus = net.create_bft_notary_cluster(4)
        notary = type("C", (), {"info": notary_party})()
        log(f"bft notary cluster: {len(members)} members (f=1), f+1 "
            f"replica signatures per commit")
    else:
        notary = net.create_notary_node(validating=True)
    bank = net.create_node("O=Bank,L=London,C=GB")
    alice = net.create_node("O=Alice,L=London,C=GB")
    bob = net.create_node("O=Bob,L=New York,C=US")
    token = Issued(bank.info.ref(1), "USD")

    log(f"notarising {n_transactions} issue+move pairs...")
    notarised = 0
    for i in range(n_transactions):
        h = bank.start_flow(
            CashIssueFlow(Amount(100, "USD"), b"\x01", alice.info, notary.info)
        )
        net.run_network()
        h.result.result(timeout=10)
        h2 = alice.start_flow(
            CashPaymentFlow(Amount(100, token), bob.info, notary.info)
        )
        net.run_network()
        h2.result.result(timeout=10)
        notarised += 1
        log(f"  tx pair {i + 1}/{n_transactions} notarised")

    log("attempting a double spend...")
    from ..core.flows import FinalityFlow
    from ..core.transactions import TransactionBuilder
    from ..finance.cash import CashCommand, CashState

    # Hand-craft two transactions consuming the same input.
    h3 = bank.start_flow(
        CashIssueFlow(Amount(500, "USD"), b"\x01", alice.info, notary.info)
    )
    net.run_network()
    h3.result.result(timeout=10)
    ref = next(
        sr for sr in alice.services.vault_service.unconsumed_states(
            CashState.contract_name
        )
        if sr.state.data.amount.quantity == 500
    )
    spends = []
    for owner in (bob.info, alice.info):
        b = TransactionBuilder(notary=notary.info)
        b.add_input_state(ref)
        b.add_output_state(CashState(amount=Amount(500, token), owner=owner))
        b.add_command(CashCommand.Move(), alice.info.owning_key)
        spends.append(alice.services.sign_initial_transaction(b))
    h4 = alice.start_flow(FinalityFlow(spends[0]), spends[0])
    net.run_network()
    h4.result.result(timeout=10)
    double_spend_rejected = False
    h5 = alice.start_flow(FinalityFlow(spends[1]), spends[1])
    net.run_network()
    try:
        h5.result.result(timeout=10)
    except NotaryException:
        double_spend_rejected = True
    log(f"double spend rejected: {double_spend_rejected}")

    result = {
        "notarised": notarised,
        "double_spend_rejected": double_spend_rejected,
    }
    net.stop_nodes()
    assert double_spend_rejected
    return result


if __name__ == "__main__":
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    mode = (
        "raft" if "--raft" in sys.argv
        else "bft" if "--bft" in sys.argv
        else "single"
    )
    main(int(args[0]) if args else 10, mode=mode)

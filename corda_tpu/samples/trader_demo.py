"""Trader demo (reference `samples/trader-demo/`): bank issues cash to the
buyer, the seller self-issues commercial paper, then a delivery-vs-payment
trade moves paper against cash atomically."""
from __future__ import annotations

from ..core.contracts import Amount, Issued, TimeWindow
from ..core.flows import FinalityFlow
from ..core.transactions import TransactionBuilder
from ..finance import CashIssueFlow, CashState, SellerFlow
from ..finance.commercial_paper import CommercialPaperState, CPCommand
from ..testing import MockNetwork


def balance(node) -> int:
    return sum(
        sr.state.data.amount.quantity
        for sr in node.services.vault_service.unconsumed_states(
            CashState.contract_name
        )
    )


def main(verbose: bool = True) -> dict:
    log = print if verbose else (lambda *a, **k: None)
    net = MockNetwork()
    notary = net.create_notary_node(validating=True)
    bank = net.create_node("O=BankOfCorda,L=London,C=GB")
    seller = net.create_node("O=BankA,L=London,C=GB")
    buyer = net.create_node("O=BankB,L=New York,C=US")

    log("issuing $30,000 to the buyer...")
    h = bank.start_flow(
        CashIssueFlow(Amount(30_000_00, "USD"), b"\x01", buyer.info, notary.info)
    )
    net.run_network()
    h.result.result(timeout=10)

    log("seller issues $10,000 of commercial paper...")
    now = int(seller.services.clock() * 1_000_000_000)
    token = Issued(bank.info.ref(1), "USD")
    paper = CommercialPaperState(
        issuance=seller.info.ref(1),
        owner=seller.info,
        face_value=Amount(10_000_00, token),
        maturity_date=now + int(30 * 86400 * 1e9),
    )
    b = TransactionBuilder(notary=notary.info)
    b.add_output_state(paper)
    b.add_command(CPCommand.Issue(), seller.info.owning_key)
    b.set_time_window(TimeWindow.with_tolerance(now, int(300 * 1e9)))
    stx = seller.services.sign_initial_transaction(b)
    h2 = seller.start_flow(FinalityFlow(stx), stx)
    net.run_network()
    h2.result.result(timeout=10)

    log("running the DvP trade: paper for $9,000...")
    h3 = seller.start_flow(
        SellerFlow(buyer.info, stx.tx.out_ref(0), Amount(9_000_00, token),
                   notary.info),
        buyer.info,
    )
    net.run_network()
    h3.result.result(timeout=10)

    result = {
        "seller_cash": balance(seller),
        "buyer_cash": balance(buyer),
        "buyer_paper": len(
            buyer.services.vault_service.unconsumed_states(
                CommercialPaperState.contract_name
            )
        ),
    }
    log(f"done: {result}")
    net.stop_nodes()
    assert result == {
        "seller_cash": 9_000_00, "buyer_cash": 21_000_00, "buyer_paper": 1
    }
    return result


if __name__ == "__main__":
    main()

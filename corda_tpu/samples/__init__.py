"""corda_tpu.samples: runnable demos (reference `samples/`).

Each module has a `main()` and runs as `python -m corda_tpu.samples.<name>`:
  * trader_demo      — DvP: bank issues cash, buyer buys commercial paper
  * notary_demo      — N transactions notarised incl. a double-spend rejection
  * bank_of_corda    — issuer node servicing cash-issue requests
  * attachment_demo  — send a transaction with an attachment, fetch it back
"""

"""IRS demo: a rate-fix oracle signing over FilteredTransaction tear-offs,
driving an interest-rate-swap state through the scheduler.

Reference parity: `samples/irs-demo/src/main/kotlin/net/corda/irs/api/
NodeInterestRates.kt` (the Oracle: query + sign-over-filtered — the only
reference workload exercising third-party tear-off signing end to end) and
`samples/irs-demo/.../flows/RatesFixFlow.kt` (query -> tolerance check ->
embed Fix command -> filtered signing round-trip), with the IRS state's
fixing dates firing through the scheduler (`NodeSchedulerService`).

Privacy property demonstrated: the oracle sees ONLY the Fix commands it
is asked to attest (everything else in the transaction is pruned to
Merkle hashes), yet its signature covers the whole transaction id.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, replace
from typing import List, Optional, Tuple

from ..core.contracts import (
    Command,
    CommandData,
    Contract,
    ContractState,
    ScheduledActivity,
    SchedulableState,
    StateRef,
    TransactionVerificationError,
    TypeOnlyCommandData,
    contract,
)
from ..core.crypto.signing import DigitalSignatureWithKey
from ..core.flows import (
    FinalityFlow,
    FlowException,
    FlowLogic,
    initiated_by,
    initiating_flow,
    schedulable_flow,
    startable_by_rpc,
)
from ..core.identity import Party
from ..core.serialization.codec import corda_serializable, register_adapter
from ..core.transactions import TransactionBuilder
from ..core.transactions.filtered import FilteredTransaction


# ---------------------------------------------------------------------------
# Fix model (reference contracts Fix / FixOf)
# ---------------------------------------------------------------------------

@corda_serializable
@dataclass(frozen=True)
class FixOf:
    """Identifies a rate: e.g. LIBOR / 2026-07-30 / 3M."""

    name: str
    for_day: str   # ISO date
    tenor: str


@corda_serializable
@dataclass(frozen=True)
class Fix(CommandData):
    """An observed rate embedded as a command (oracle signs over it)."""

    of: FixOf
    value: float


class UnknownFix(FlowException):
    def __init__(self, of: FixOf):
        super().__init__(f"unknown fix {of}")


class FixOutOfRange(FlowException):
    def __init__(self, by_amount: float):
        super().__init__(f"fix out of range by {by_amount}")


# ---------------------------------------------------------------------------
# The oracle service (reference NodeInterestRates.Oracle)
# ---------------------------------------------------------------------------

class RateOracle:
    """Holds known fixes; answers queries; signs tear-offs.

    sign() accepts a FilteredTransaction whose REVEALED components must all
    be Fix commands naming this oracle as a signer and matching known
    rates; the signature is over the Merkle root == transaction id, so it
    commits to the whole (mostly hidden) transaction."""

    def __init__(self, identity: Party, key_management):
        self.identity = identity
        self._kms = key_management
        self._fixes = {}
        self._lock = threading.Lock()

    def add_fix(self, fix: Fix) -> None:
        with self._lock:
            self._fixes[fix.of] = fix

    def query(self, queries: List[FixOf]) -> List[Fix]:
        if not queries:
            raise FlowException("empty fix query")
        with self._lock:
            out = []
            for q in queries:
                fix = self._fixes.get(q)
                if fix is None:
                    raise UnknownFix(q)
                out.append(fix)
            return out

    def sign(self, ftx: FilteredTransaction) -> DigitalSignatureWithKey:
        ftx.verify()  # Merkle proof against the root

        def check(elem) -> bool:
            if not isinstance(elem, Command):
                raise FlowException(
                    "oracle received data of different type than expected"
                )
            if not isinstance(elem.value, Fix):
                raise FlowException("oracle received a non-Fix command")
            if not any(
                k.encoded == self.identity.owning_key.encoded
                for k in elem.signers
            ):
                raise FlowException("oracle is not a signer of the command")
            with self._lock:
                known = self._fixes.get(elem.value.of)
            if known is None or known != elem.value:
                raise UnknownFix(elem.value.of)
            return True

        if not ftx.check_with_fun(check):
            raise FlowException("nothing to attest")
        return self._kms.sign(ftx.id.bytes, self.identity.owning_key)


# ---------------------------------------------------------------------------
# Oracle protocol flows (reference RatesFixFlow.FixQueryFlow/FixSignFlow)
# ---------------------------------------------------------------------------

@corda_serializable
@dataclass(frozen=True)
class QueryRequest:
    queries: Tuple[FixOf, ...]


@corda_serializable
@dataclass(frozen=True)
class QueryResponse:
    fixes: Tuple[Fix, ...]


@corda_serializable
@dataclass(frozen=True)
class SignRequest:
    ftx: FilteredTransaction


def _oracle_of(service_hub) -> RateOracle:
    oracle = getattr(service_hub, "rate_oracle", None)
    if oracle is None:
        raise FlowException("this node does not run a rate oracle")
    return oracle


@initiating_flow
class FixQueryFlow(FlowLogic):
    def __init__(self, fix_of: FixOf, oracle: Party):
        self.fix_of = fix_of
        self.oracle = oracle

    def call(self):
        resp = yield self.send_and_receive(
            self.oracle, QueryRequest((self.fix_of,)), QueryResponse
        )
        return resp.fixes[0]


@initiated_by(FixQueryFlow)
class FixQueryHandler(FlowLogic):
    def __init__(self, counterparty: Party):
        self.counterparty = counterparty

    def call(self):
        request = yield self.receive(self.counterparty, QueryRequest)
        oracle = _oracle_of(self.service_hub)
        fixes = oracle.query(list(request.queries))
        yield self.send(self.counterparty, QueryResponse(tuple(fixes)))


@initiating_flow
class FixSignFlow(FlowLogic):
    def __init__(self, ftx: FilteredTransaction, oracle: Party):
        self.ftx = ftx
        self.oracle = oracle

    def call(self):
        sig = yield self.send_and_receive(
            self.oracle, SignRequest(self.ftx), DigitalSignatureWithKey
        )
        if not self.oracle.owning_key.is_fulfilled_by({sig.by}):
            raise FlowException("signature is not the oracle's")
        if not sig.is_valid(self.ftx.id.bytes):
            raise FlowException("invalid oracle signature")
        return sig


@initiated_by(FixSignFlow)
class FixSignHandler(FlowLogic):
    def __init__(self, counterparty: Party):
        self.counterparty = counterparty

    def call(self):
        request = yield self.receive(self.counterparty, SignRequest)
        oracle = _oracle_of(self.service_hub)
        yield self.send(self.counterparty, oracle.sign(request.ftx))


class RatesFixFlow(FlowLogic):
    """Query the oracle, check tolerance, embed the Fix command, have the
    oracle sign a tear-off revealing ONLY the Fix commands it attests
    (reference RatesFixFlow.call + filtering)."""

    def __init__(self, builder: TransactionBuilder, oracle: Party,
                 fix_of: FixOf, expected_rate: float, tolerance: float):
        self.builder = builder
        self.oracle = oracle
        self.fix_of = fix_of
        self.expected_rate = expected_rate
        self.tolerance = tolerance

    def filtering(self, elem) -> bool:
        """Reveal exactly the Fix commands signed by the oracle."""
        return (
            isinstance(elem, Command)
            and isinstance(elem.value, Fix)
            and any(
                k.encoded == self.oracle.owning_key.encoded
                for k in elem.signers
            )
        )

    def call(self):
        fix = yield from self.sub_flow(FixQueryFlow(self.fix_of, self.oracle))
        if abs(fix.value - self.expected_rate) > self.tolerance:
            raise FixOutOfRange(abs(fix.value - self.expected_rate))
        self.builder.add_command(fix, self.oracle.owning_key)
        wtx = yield self.record(self.builder.to_wire_transaction)
        ftx = wtx.build_filtered_transaction(self.filtering)
        sig = yield from self.sub_flow(FixSignFlow(ftx, self.oracle))
        return wtx, fix, sig


# ---------------------------------------------------------------------------
# A minimal IRS state: fixing dates fire through the scheduler
# ---------------------------------------------------------------------------

@corda_serializable
@dataclass(frozen=True)
class InterestRateSwapState(SchedulableState):
    """Fixed-vs-floating swap caricature: each fixing replaces the floating
    leg's rate with the oracle's fix (reference InterestRateSwap.State's
    nextFixingOf/evaluateCalculation, radically simplified — the full
    OpenGamma analytics are out of scope for a framework demo)."""

    fixed_leg_payer: Party = None
    floating_leg_payer: Party = None
    notional: int = 0
    fixed_rate: float = 0.0
    oracle_name: str = ""
    fix_of: FixOf = None
    floating_rate: Optional[float] = None   # set by the fixing
    next_fixing_at: Optional[int] = None    # unix nanos
    contract_name = "corda_tpu.samples.IRS"

    @property
    def participants(self) -> List:
        return [self.fixed_leg_payer, self.floating_leg_payer]

    def next_scheduled_activity(self, this_state_ref: StateRef) -> Optional[ScheduledActivity]:
        if self.next_fixing_at is None or self.floating_rate is not None:
            return None
        return ScheduledActivity(
            flow_name="corda_tpu.samples.irs_demo.FixingFlow",
            flow_args=(this_state_ref,),
            scheduled_at=self.next_fixing_at,
        )


@corda_serializable
@dataclass(frozen=True)
class IRSCommand(TypeOnlyCommandData):
    kind: str = "Agree"   # Agree | Fixing


@contract(name="corda_tpu.samples.IRS")
class IRSContract(Contract):
    def verify(self, tx) -> None:
        irs_cmds = [
            c for c in tx.commands if isinstance(c.value, IRSCommand)
        ]
        if not irs_cmds:
            raise TransactionVerificationError(tx.id, "no IRS command")
        kind = irs_cmds[0].value.kind
        if kind == "Fixing":
            fixes = [c for c in tx.commands if isinstance(c.value, Fix)]
            if len(fixes) != 1:
                raise TransactionVerificationError(
                    tx.id, "a fixing needs exactly one Fix command"
                )
            outs = tx.outputs_of_type(InterestRateSwapState)
            if len(outs) != 1 or outs[0].floating_rate != fixes[0].value.value:
                raise TransactionVerificationError(
                    tx.id, "output floating rate must equal the attested fix"
                )


@schedulable_flow
@startable_by_rpc
class FixingFlow(FlowLogic):
    """Fired by the scheduler when a fixing date arrives: asks the oracle
    for the rate, gets its tear-off signature over the final transaction,
    finalises the fixed state (reference FixingFlow.Fixer)."""

    TOLERANCE = 10.0

    def __init__(self, ref: StateRef):
        self.ref = ref

    def call(self):
        from ..core.contracts import StateAndRef
        from ..core.transactions.signed import SignedTransaction

        hub = self.service_hub
        ts = hub.load_state(self.ref)
        irs: InterestRateSwapState = ts.data
        # Role split (reference TwoPartyDealFlow Fixer/Floater): the state is
        # relevant to both legs so BOTH nodes' schedulers fire this flow;
        # only the fixed-leg payer runs the fixing, the other side no-ops
        # and learns the result through FinalityFlow broadcast.
        if hub.my_info.name != irs.fixed_leg_payer.name:
            return None
        oracle = hub.identity_service.party_from_name(irs.oracle_name)
        if oracle is None:
            raise FlowException(f"oracle {irs.oracle_name} not known")

        fix = yield from self.sub_flow(FixQueryFlow(irs.fix_of, oracle))
        if abs(fix.value - irs.fixed_rate) > self.TOLERANCE:
            raise FixOutOfRange(abs(fix.value - irs.fixed_rate))

        builder = TransactionBuilder(notary=ts.notary)
        builder.add_input_state(StateAndRef(ts, self.ref))
        builder.add_output_state(
            replace(irs, floating_rate=fix.value, next_fixing_at=None)
        )
        builder.add_command(
            IRSCommand("Fixing"), irs.fixed_leg_payer.owning_key
        )
        builder.add_command(fix, oracle.owning_key)
        wtx = yield self.record(builder.to_wire_transaction)

        def filtering(elem) -> bool:
            # Reveal exactly the Fix commands signed by the oracle.
            return (
                isinstance(elem, Command)
                and isinstance(elem.value, Fix)
                and any(
                    k.encoded == oracle.owning_key.encoded
                    for k in elem.signers
                )
            )

        ftx = wtx.build_filtered_transaction(filtering)
        oracle_sig = yield from self.sub_flow(FixSignFlow(ftx, oracle))
        my_sig = hub.key_management_service.sign(
            wtx.id.bytes, irs.fixed_leg_payer.owning_key
        )
        stx = SignedTransaction.of(wtx, (my_sig, oracle_sig))
        result = yield from self.sub_flow(FinalityFlow(stx))
        return result


def main(verbose: bool = True) -> dict:
    """Run the demo: two banks agree a swap, the scheduler fires the
    fixing, the oracle attests LIBOR over a tear-off, the state updates
    (reference irs-demo Main.kt, reduced to one fixing)."""
    import time as _time

    from ..testing.mocknetwork import MockNetwork

    def log(msg):
        # demo progress is console UX AND an operational event: the
        # print is the UI, the emit keeps the flight recorder complete
        from ..utils import eventlog

        eventlog.emit("info", "irs_demo", msg)
        if verbose:
            print(f"[irs-demo] {msg}")

    net = MockNetwork()
    notary = net.create_notary_node(validating=True)
    bank_a = net.create_node("O=Bank A,L=London,C=GB")
    oracle_node = net.create_node("O=Rates Oracle,L=Zurich,C=CH")
    oracle = RateOracle(
        oracle_node.info, oracle_node.services.key_management_service
    )
    oracle_node.services.rate_oracle = oracle
    fix_of = FixOf("LIBOR", "2026-07-30", "3M")
    oracle.add_fix(Fix(fix_of, 3.25))
    log("oracle knows LIBOR 3M @ 3.25")

    builder = TransactionBuilder(notary=notary.info)
    swap = InterestRateSwapState(
        fixed_leg_payer=bank_a.info,
        floating_leg_payer=bank_a.info,
        notional=10_000_000,
        fixed_rate=3.0,
        oracle_name=oracle_node.info.name,
        fix_of=fix_of,
        next_fixing_at=int((_time.time() - 1) * 1_000_000_000),
    )
    builder.add_output_state(swap)
    builder.add_command(IRSCommand("Agree"), bank_a.info.owning_key)
    stx = bank_a.services.sign_initial_transaction(builder)
    bank_a.services.record_transactions([stx])
    log(f"swap agreed: notional {swap.notional}, fixing due")

    started = bank_a.scheduler.wake()
    net.run_network()
    bank_a.smm.flows[started[0]].result.result(timeout=10)
    fixed = bank_a.services.vault_service.unconsumed_states(
        InterestRateSwapState.contract_name
    )[0].state.data
    log(f"fixing applied by scheduler+oracle: floating rate {fixed.floating_rate}")
    net.stop_nodes()
    assert fixed.floating_rate == 3.25
    return {"floating_rate": fixed.floating_rate}


if __name__ == "__main__":
    main()

"""Bank-of-Corda demo (reference `samples/bank-of-corda/`): an issuer node
services cash-issue requests from other parties via an issuer flow pair."""
from __future__ import annotations

from dataclasses import dataclass

from ..core.contracts import Amount
from ..core.flows import FlowException, FlowLogic, initiated_by, initiating_flow
from ..core.identity import Party
from ..core.serialization.codec import register_adapter
from ..finance import CashIssueFlow, CashState
from ..testing import MockNetwork


@dataclass(frozen=True)
class IssueRequest:
    amount: Amount
    issuer_ref: bytes


register_adapter(
    IssueRequest, "IssueRequest",
    lambda r: {"amount": r.amount, "ref": r.issuer_ref},
    lambda d: IssueRequest(d["amount"], d["ref"]),
)


@initiating_flow
class IssuanceRequester(FlowLogic):
    """Ask the bank to issue cash to us (reference IssuerFlow.IssuanceRequester)."""

    def __init__(self, bank: Party, amount: Amount, issuer_ref: bytes = b"\x01"):
        self.bank = bank
        self.amount = amount
        self.issuer_ref = issuer_ref

    def call(self):
        confirmation = yield self.send_and_receive(
            self.bank, IssueRequest(self.amount, self.issuer_ref), bytes
        )
        if confirmation != b"issued":
            raise FlowException(f"bank refused: {confirmation!r}")
        return confirmation


@initiated_by(IssuanceRequester)
class IssuerFlow(FlowLogic):
    """Bank side: validate and run the actual CashIssueFlow (reference
    IssuerFlow.Issuer)."""

    MAX_ISSUE = 1_000_000_00

    def __init__(self, counterparty: Party):
        self.counterparty = counterparty

    def call(self):
        request = yield self.receive(self.counterparty, IssueRequest)
        if request.amount.quantity > self.MAX_ISSUE:
            raise FlowException("issuance cap exceeded")
        notary = self.service_hub.network_map_cache.get_notary()
        result = yield from self.sub_flow(
            CashIssueFlow(
                request.amount, request.issuer_ref, self.counterparty, notary
            )
        )
        yield self.send(self.counterparty, b"issued")
        return result


def main(verbose: bool = True) -> dict:
    log = print if verbose else (lambda *a, **k: None)
    net = MockNetwork()
    net.create_notary_node(validating=True)
    bank = net.create_node("O=BankOfCorda,L=London,C=GB")
    alice = net.create_node("O=BigCorporation,L=New York,C=US")

    log("requesting $1,000 issuance from the bank...")
    h = alice.start_flow(
        IssuanceRequester(bank.info, Amount(1_000_00, "USD")), bank.info
    )
    net.run_network()
    h.result.result(timeout=10)
    states = alice.services.vault_service.unconsumed_states(
        CashState.contract_name
    )
    total = sum(sr.state.data.amount.quantity for sr in states)
    log(f"alice now holds {total} cents of issued USD")

    log("requesting an over-cap issuance (should be refused)...")
    h2 = alice.start_flow(
        IssuanceRequester(bank.info, Amount(9_999_999_00, "USD")), bank.info
    )
    net.run_network()
    refused = False
    try:
        h2.result.result(timeout=10)
    except FlowException:
        refused = True
    log(f"over-cap refused: {refused}")

    net.stop_nodes()
    assert total == 1_000_00 and refused
    return {"issued": total, "over_cap_refused": refused}


if __name__ == "__main__":
    main()

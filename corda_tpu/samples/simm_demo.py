"""SIMM valuation demo (reference `samples/simm-valuation-demo/` — two
nodes agree a portfolio of IRS trades, compute initial margin, and agree
the valuation via flows).

TPU-first redesign of the analytics: the reference bolts on OpenGamma's
Strata library and computes curve sensitivities by bump-and-revalue; here
pricing is a pure JAX function of the zero curve, so

  * portfolio PV is a single vectorised evaluation over (trades x tenors)
    on the accelerator, and
  * the SIMM delta ladder is `jax.jacrev` of that function — exact
    sensitivities from autodiff, no bumping, one compiled program.

The margin aggregation is the ISDA-SIMM-style formula
IM = sqrt(s^T C s) with weighted sensitivities s and tenor correlation C.

Run: python -m corda_tpu.samples.simm_demo
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from ..core.contracts.structures import (
    Contract,
    ContractState,
    TransactionVerificationError,
    TypeOnlyCommandData,
    contract,
)
from ..core.flows.api import (
    FlowException,
    FlowLogic,
    initiated_by,
    initiating_flow,
    startable_by_rpc,
)
from ..core.identity import Party
from ..core.serialization.codec import corda_serializable


# --- trade + portfolio model -------------------------------------------------

#: standard SIMM-ish tenor buckets (years)
TENORS: Tuple[float, ...] = (0.5, 1.0, 2.0, 3.0, 5.0, 10.0, 20.0, 30.0)

#: per-tenor risk weights (bp of sensitivity, demo calibration)
RISK_WEIGHTS: Tuple[float, ...] = (113, 111, 93, 80, 69, 61, 60, 59)

#: inter-tenor correlation falls off with tenor distance (demo calibration)
def _correlation_matrix() -> np.ndarray:
    t = np.asarray(TENORS)
    lt = np.log(t)
    return np.exp(-0.35 * np.abs(lt[:, None] - lt[None, :]))


@corda_serializable(name="simm.IRSTrade")
@dataclass(frozen=True)
class IRSTrade:
    """Vanilla fixed-vs-float swap, annual payments (demo granularity)."""

    trade_id: str = ""
    notional: int = 0          # minor units
    fixed_rate: float = 0.0    # decimal, e.g. 0.03
    maturity_years: float = 0.0
    pay_fixed: bool = True     # True: we pay fixed, receive floating


@corda_serializable(name="simm.PortfolioState")
@dataclass(frozen=True)
class PortfolioState(ContractState):
    party_a: Party = None
    party_b: Party = None
    trades: Tuple = ()
    portfolio_id: str = ""  # the id valuation requests select on
    contract_name = "corda_tpu.samples.Portfolio"

    def __post_init__(self):
        object.__setattr__(self, "trades", tuple(self.trades))

    @property
    def participants(self) -> List:
        return [self.party_a, self.party_b]


@corda_serializable(name="simm.PortfolioCommand")
@dataclass(frozen=True)
class PortfolioCommand(TypeOnlyCommandData):
    kind: str = "Agree"


@contract(name="corda_tpu.samples.Portfolio")
class PortfolioContract(Contract):
    def verify(self, tx) -> None:
        cmds = [
            c for c in tx.commands if isinstance(c.value, PortfolioCommand)
        ]
        if not cmds:
            raise TransactionVerificationError(tx.id, "no portfolio command")
        outs = tx.outputs_of_type(PortfolioState)
        if cmds[0].value.kind == "Agree":
            if len(outs) != 1 or not outs[0].trades:
                raise TransactionVerificationError(
                    tx.id, "agree: one non-empty portfolio output"
                )
            signers = {k.encoded for k in cmds[0].signers}
            for p in outs[0].participants:
                if p.owning_key.encoded not in signers:
                    raise TransactionVerificationError(
                        tx.id, f"agree: {p.name} must sign the portfolio"
                    )


# --- JAX analytics -----------------------------------------------------------

def _trade_arrays(trades) -> dict:
    return {
        "notional": np.asarray([t.notional for t in trades], np.float64),
        "fixed_rate": np.asarray([t.fixed_rate for t in trades], np.float64),
        "maturity": np.asarray(
            [t.maturity_years for t in trades], np.float64
        ),
        "direction": np.asarray(
            [1.0 if t.pay_fixed else -1.0 for t in trades], np.float64
        ),
    }


def _swap_pricing_core(zero_rates, maturity):
    """THE pricing model, shared by valuation AND calibration (one
    definition of payment schedule, discounting, annuity and par — if
    the model changes, both change together by construction).

    zero_rates: (K,) pillar zeros at TENORS; maturity: (M,) maturities.
    Returns (df_T, annuity, par) each (M,): yearly payments, linear
    zero interpolation, par = (1 - df_T) / annuity."""
    import jax.numpy as jnp

    tenors = jnp.asarray(TENORS)
    years = jnp.arange(1.0, 31.0)                      # (Y,)
    r = jnp.interp(years, tenors, zero_rates)          # (Y,)
    df = jnp.exp(-r * years)                           # (Y,)
    alive = (years[None, :] <= maturity[:, None])      # (M, Y)
    annuity = jnp.sum(df[None, :] * alive, axis=1)     # (M,)
    df_T = jnp.exp(-jnp.interp(maturity, tenors, zero_rates) * maturity)
    par = (1.0 - df_T) / jnp.maximum(annuity, 1e-9)
    return df_T, annuity, par


def _pv_vector_fn(arrs):
    """Returns pv_vec(zero_rates) -> (T,) per-trade PVs; pure JAX, so
    values, jacobians, and masked aggregations all compile."""
    import jax.numpy as jnp

    notional = jnp.asarray(arrs["notional"])
    fixed = jnp.asarray(arrs["fixed_rate"])
    maturity = jnp.asarray(arrs["maturity"])
    direction = jnp.asarray(arrs["direction"])

    def pv_vec(zero_rates):
        _, annuity, par = _swap_pricing_core(zero_rates, maturity)
        # payer-fixed swap PV = notional * (par - fixed) * annuity
        return direction * notional * (par - fixed) * annuity

    return pv_vec


def _pv_fn(arrs):
    """Scalar portfolio PV over the per-trade vector."""
    import jax.numpy as jnp

    pv_vec = _pv_vector_fn(arrs)
    return lambda zero_rates: jnp.sum(pv_vec(zero_rates))


def portfolio_pv(trades, zero_rates) -> float:
    import jax

    pv = jax.jit(_pv_fn(_trade_arrays(trades)))
    return float(pv(np.asarray(zero_rates, np.float64)))


def delta_ladder(trades, zero_rates) -> np.ndarray:
    """dPV/dr per tenor bucket via reverse-mode autodiff (replaces the
    reference's OpenGamma bump-and-revalue sensitivity calc)."""
    import jax

    grad = jax.jit(jax.grad(_pv_fn(_trade_arrays(trades))))
    return np.asarray(grad(np.asarray(zero_rates, np.float64)))


def simm_initial_margin(trades, zero_rates) -> float:
    """ISDA-SIMM-style IR delta margin: weighted sensitivities aggregated
    under the tenor correlation matrix, IM = sqrt(s^T C s)."""
    deltas = delta_ladder(trades, zero_rates) / 10_000.0  # per bp
    s = deltas * np.asarray(RISK_WEIGHTS)
    c = _correlation_matrix()
    return float(np.sqrt(np.maximum(s @ c @ s, 0.0)))


def per_trade_pvs(trades, zero_rates) -> np.ndarray:
    """(T,) present values, one vectorised evaluation (the reference
    prices per trade in a Java loop, AnalyticsEngine.kt:83-91)."""
    return portfolio_analytics(trades, zero_rates)["per_trade_pvs"]


_VALUE_AND_JAC = None  # module-level jit: cached across calls/requests


def _value_and_jac_fn():
    global _VALUE_AND_JAC
    if _VALUE_AND_JAC is None:
        import jax

        @jax.jit
        def value_and_jac(notional, fixed, maturity, direction, r):
            def pv_vec(rr):
                _, annuity, par = _swap_pricing_core(rr, maturity)
                return direction * notional * (par - fixed) * annuity

            return pv_vec(r), jax.jacrev(pv_vec)(r)

        _VALUE_AND_JAC = value_and_jac
    return _VALUE_AND_JAC


def portfolio_analytics(trades, zero_rates) -> dict:
    """EVERY analytic from one compiled evaluation: per-trade PVs and
    the per-trade delta matrix D come from a single (value, jacobian)
    program — a MODULE-LEVEL jit, so repeat calls (the web valuation
    route serves one per request) reuse the compiled executable for
    each portfolio shape; portfolio PV, the delta ladder, total IM and
    every leave-one-out marginal IM are numpy aggregations of those.

    The reference re-runs the whole OpenGamma pipeline once per omitted
    trade for the marginal margins (AnalyticsEngine.kt:139,
    `trades.omit(it)` in a loop); here T portfolio revaluations
    collapse into row-wise weighted quadratic forms over
    (D_total - D_i)."""
    arrs = _trade_arrays(trades)
    pvs, D = _value_and_jac_fn()(
        arrs["notional"], arrs["fixed_rate"], arrs["maturity"],
        arrs["direction"], np.asarray(zero_rates),
    )
    pvs = np.asarray(pvs)
    D = np.asarray(D)                                        # (T, K)
    deltas = D.sum(axis=0)                                   # dPV/dr
    Dbp = D / 10_000.0                                       # per bp
    w = np.asarray(RISK_WEIGHTS)
    c = _correlation_matrix()
    s_total = Dbp.sum(axis=0) * w                            # (K,)
    im_all = float(np.sqrt(np.maximum(s_total @ c @ s_total, 0.0)))
    s_without = s_total[None, :] - Dbp * w[None, :]          # (T, K)
    im_without = np.sqrt(
        np.maximum(np.einsum("tk,kj,tj->t", s_without, c, s_without), 0.0)
    )
    return {
        "per_trade_pvs": pvs,
        "pv": float(pvs.sum()),
        "delta_ladder": deltas,
        "initial_margin": im_all,
        "marginal_im": im_all - im_without,
    }


def marginal_im(trades, zero_rates) -> np.ndarray:
    """(T,) leave-one-out margin contributions: IM(all) - IM(all \\ i)."""
    return portfolio_analytics(trades, zero_rates)["marginal_im"]


def calibrate_curve(par_rates, n_iter: int = 30) -> np.ndarray:
    """Bootstrap the zero curve from par swap quotes at TENORS.

    The reference calibrates its rates provider from market-quote CSVs
    through OpenGamma's RatesCalibrationCsvLoader
    (AnalyticsEngine.kt:114-126). Here calibration is root-finding on
    the SAME pricing function the valuations use: find zero rates r
    such that par(T_i; r) == quote_i, by damped Newton with the
    jacobian from autodiff — one jittable program, no bump-and-reprice,
    and perfectly consistent with the PV/delta analytics by
    construction."""
    import jax
    import jax.numpy as jnp

    quotes = jnp.asarray(par_rates)  # framework default precision
    tenors = jnp.asarray(TENORS)

    def par_curve(zero_rates):
        # the SHARED pricing core — calibration literally prices the
        # same instruments the valuations do
        df_T, _, swap_par = _swap_pricing_core(zero_rates, tenors)
        # sub-1y pillars have no coupon in the annual-payment swap
        # model: quote them as money-market deposits,
        # rate = (1/df - 1)/T (simple accrual), like the short end of
        # the reference's calibration instrument set
        depo = (1.0 / df_T - 1.0) / tenors
        return jnp.where(tenors < 1.0, depo, swap_par)

    def newton_step(r, _):
        resid = par_curve(r) - quotes
        J = jax.jacfwd(par_curve)(r)
        # levenberg-style ridge, SCALED so it is meaningful at float32
        # (an absolute 1e-10 vanishes against O(1) diagonal entries)
        JtJ = J.T @ J
        ridge = 1e-6 * jnp.trace(JtJ) / len(TENORS)
        delta = jnp.linalg.solve(
            JtJ + ridge * jnp.eye(len(TENORS)), J.T @ resid
        )
        return r - delta, None

    @jax.jit
    def solve(start):
        final, _ = jax.lax.scan(newton_step, start, None, length=n_iter)
        return final

    zero = np.asarray(solve(quotes))  # par quotes are a good start
    resid = np.asarray(par_curve(jnp.asarray(zero))) - np.asarray(par_rates)
    # JAX default precision is float32 (x64 is off framework-wide): a
    # 5e-7 absolute residual is ~0.005bp on the par rate — calibration
    # noise far below the demo's cent-rounding of PV/IM
    if float(np.max(np.abs(resid))) > 5e-7:
        raise ValueError(
            f"curve calibration did not converge (max residual "
            f"{float(np.max(np.abs(resid))):.2e})"
        )
    return zero


@corda_serializable(name="simm.Valuation")
@dataclass(frozen=True)
class Valuation:
    """What the two parties must agree on, to the cent."""

    portfolio_id: str = ""
    pv: int = 0              # minor units, rounded
    initial_margin: int = 0  # minor units, rounded
    curve: Tuple = ()        # the zero curve used

    def __post_init__(self):
        object.__setattr__(self, "curve", tuple(self.curve))


def compute_valuation(portfolio_id: str, trades, zero_rates) -> Valuation:
    return Valuation(
        portfolio_id=portfolio_id,
        pv=int(round(portfolio_pv(trades, zero_rates))),
        initial_margin=int(round(simm_initial_margin(trades, zero_rates))),
        curve=tuple(float(r) for r in zero_rates),
    )


# --- flows -------------------------------------------------------------------

class ValuationMismatch(FlowException):
    pass


def _portfolio_by_id(hub, portfolio_id: str):
    """The unconsumed PortfolioState matching the requested id (both sides
    must price the SAME book, not whichever state comes first)."""
    states = hub.vault_service.unconsumed_states(
        PortfolioState.contract_name
    )
    return next(
        (
            s.state.data for s in states
            if s.state.data.portfolio_id == portfolio_id
        ),
        None,
    )


@initiating_flow
@startable_by_rpc
class RequestValuationFlow(FlowLogic):
    """Both sides price the SAME portfolio on the SAME curve and must agree
    bit-for-bit (reference simm-valuation-demo's agree-on-valuation round)."""

    def __init__(self, counterparty: Party, portfolio_id: str, curve: Tuple):
        self.counterparty = counterparty
        self.portfolio_id = portfolio_id
        self.curve = tuple(curve)

    def _my_valuation(self):
        portfolio = _portfolio_by_id(self.service_hub, self.portfolio_id)
        if portfolio is None:
            raise FlowException(
                f"no portfolio {self.portfolio_id!r} in the vault"
            )
        return compute_valuation(
            self.portfolio_id, portfolio.trades, self.curve
        )

    def call(self):
        mine = yield self.record(self._my_valuation)
        theirs = yield self.send_and_receive(
            self.counterparty,
            [self.portfolio_id, list(self.curve)],  # codec ships lists
            Valuation,
        )
        if theirs != mine:
            raise ValuationMismatch(
                f"valuations diverge: mine {mine.pv}/{mine.initial_margin} "
                f"theirs {theirs.pv}/{theirs.initial_margin}"
            )
        return mine


@initiated_by(RequestValuationFlow)
class RespondValuationFlow(FlowLogic):
    def __init__(self, counterparty: Party):
        self.counterparty = counterparty

    def call(self):
        req = yield self.receive(self.counterparty, list)
        portfolio_id, curve = req[0], tuple(req[1])
        portfolio = _portfolio_by_id(self.service_hub, portfolio_id)
        if portfolio is None:
            raise FlowException(
                f"responder has no portfolio {portfolio_id!r}"
            )
        valuation = yield self.record(
            lambda: compute_valuation(portfolio_id, portfolio.trades, curve)
        )
        yield self.send(self.counterparty, valuation)
        return valuation


# --- demo driver -------------------------------------------------------------

DEMO_CURVE = (0.031, 0.032, 0.034, 0.035, 0.037, 0.040, 0.042, 0.043)


# --- web API (reference PortfolioApi.kt: the demo's REST surface) -----------

class SimmApiPlugin:
    """`/api/simmvaluationdemo/...` over the webserver plugin registry
    (reference PortfolioApi.kt mounts the same surface via JAX-RS from
    the CorDapp jar). Portfolio-scoped where the reference is
    counterparty-scoped — one portfolio per counterparty pair in both.

    Routes:
      GET business-date
      GET portfolios
      GET <portfolio-id>/trades
      GET <portfolio-id>/trades/<trade-id>
      GET <portfolio-id>/valuation[?curve=r1,r2,...]   (full analytics)
    """

    @staticmethod
    def _trade_json(t: IRSTrade) -> dict:
        return {
            "id": t.trade_id,
            "notional": t.notional,
            "fixedRate": t.fixed_rate,
            "maturityYears": t.maturity_years,
            "payFixed": t.pay_fixed,
        }

    def _portfolios(self, ops):
        out = {}
        for sar in ops.vault_query(PortfolioState.contract_name):
            state = sar.state.data
            out[state.portfolio_id] = state
        return out

    def handle(self, ops, method, subpath, params, body):
        if method != "GET":
            return 405, {"error": "read-only API"}
        if subpath in ("", "business-date"):
            import time as _time

            return 200, {"businessDate": _time.strftime("%Y-%m-%d")}
        if subpath == "portfolios":
            return 200, {
                "portfolios": [
                    {
                        "id": pid,
                        "parties": [s.party_a.name, s.party_b.name],
                        "trades": len(s.trades),
                    }
                    for pid, s in sorted(self._portfolios(ops).items())
                ]
            }
        parts = subpath.split("/")
        state = self._portfolios(ops).get(parts[0])
        if state is None:
            return 404, {"error": f"no portfolio {parts[0]!r}"}
        if len(parts) == 2 and parts[1] == "trades":
            return 200, {
                "trades": [self._trade_json(t) for t in state.trades]
            }
        if len(parts) == 3 and parts[1] == "trades":
            t = next(
                (t for t in state.trades if t.trade_id == parts[2]), None
            )
            if t is None:
                return 404, {"error": f"no trade {parts[2]!r}"}
            return 200, self._trade_json(t)
        if len(parts) == 2 and parts[1] == "valuation":
            curve = DEMO_CURVE
            if params.get("curve"):
                try:
                    curve = tuple(
                        float(x) for x in params["curve"].split(",")
                    )
                except ValueError:
                    return 400, {"error": "curve must be comma floats"}
                if len(curve) != len(TENORS):
                    return 400, {
                        "error": f"curve needs {len(TENORS)} tenors"
                    }
            trades = state.trades
            # one compiled (value, jacobian) evaluation serves the whole
            # response — PVs, ladder, IM and marginals are aggregations
            a = portfolio_analytics(trades, curve)
            return 200, {
                "portfolio": state.portfolio_id,
                "curve": list(curve),
                "presentValue": a["pv"],
                "perTradePV": {
                    t.trade_id: float(pv)
                    for t, pv in zip(trades, a["per_trade_pvs"])
                },
                "deltaLadder": dict(
                    zip(
                        (str(x) for x in TENORS),
                        (float(d) for d in a["delta_ladder"]),
                    )
                ),
                "initialMargin": a["initial_margin"],
                "marginalIM": {
                    t.trade_id: float(m)
                    for t, m in zip(trades, a["marginal_im"])
                },
            }
        return 404, {"error": f"no route {subpath!r}"}

    def web_apis(self):
        return {
            "simmvaluationdemo": lambda ops, method, subpath, params, body:
                self.handle(ops, method, subpath, params, body)
        }

    def static_serve_dirs(self):
        return {}


def register_simm_web_api() -> None:
    """Idempotent plugin registration (reference: SimmPlugin discovered
    via ServiceLoader; here nodes list this module in `cordapps`)."""
    from ..webserver.plugins import register_web_plugin, registered_plugins

    if not any(isinstance(p, SimmApiPlugin) for p in registered_plugins()):
        register_web_plugin(SimmApiPlugin())


register_simm_web_api()

DEMO_TRADES = (
    IRSTrade("T1", 10_000_000_00, 0.030, 5.0, True),
    IRSTrade("T2", 25_000_000_00, 0.041, 10.0, False),
    IRSTrade("T3", 5_000_000_00, 0.035, 3.0, True),
    IRSTrade("T4", 50_000_000_00, 0.044, 20.0, False),
)


def main(verbose: bool = True) -> dict:
    import jax

    # accelerator if reachable, else CPU (demo must run anywhere). The
    # probe is TIME-BOUNDED via the dispatch layer's backend resolver: a
    # half-dead tunnel hangs jax.devices() forever (observed live), and
    # a demo that hangs before printing anything is worse than one on CPU.
    from ..core.crypto import batch as crypto_batch

    if crypto_batch._backend() not in crypto_batch._ACCEL_BACKENDS:
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass  # backend already initialized

    from ..core.flows.library import FinalityFlow
    from ..core.transactions.builder import TransactionBuilder
    from ..testing.mocknetwork import MockNetwork

    def log(msg):
        # print is the demo's UI; the emit keeps the flight recorder
        # complete (nothing bypasses it, verbose or not)
        from ..utils import eventlog

        eventlog.emit("info", "simm_demo", msg)
        if verbose:
            print(f"[simm-demo] {msg}")

    net = MockNetwork()
    notary = net.create_notary_node(validating=True)
    bank_a = net.create_node("O=Bank A,L=London,C=GB")
    bank_b = net.create_node("O=Bank B,L=New York,C=US")

    # agree the portfolio (both sign; broadcast via finality)
    portfolio = PortfolioState(
        bank_a.info, bank_b.info, DEMO_TRADES, "PORTFOLIO-1"
    )
    builder = TransactionBuilder(notary=notary.info)
    builder.add_output_state(portfolio)
    builder.add_command(
        PortfolioCommand("Agree"),
        bank_a.info.owning_key, bank_b.info.owning_key,
    )
    stx = bank_a.services.sign_initial_transaction(builder)
    sig_b = bank_b.services.key_management_service.sign(
        stx.id.bytes, bank_b.info.owning_key
    )
    stx = stx.with_additional_signature(sig_b)
    h = bank_a.start_flow(FinalityFlow(stx), stx)
    net.run_network()
    h.result.result(timeout=30)
    log(f"portfolio of {len(DEMO_TRADES)} IRS trades agreed + broadcast")

    # both banks value the same book on the same curve and must agree
    h = bank_a.start_flow(
        RequestValuationFlow(bank_b.info, "PORTFOLIO-1", DEMO_CURVE),
        bank_b.info, "PORTFOLIO-1", DEMO_CURVE,
    )
    net.run_network()
    valuation = h.result.result(timeout=60)
    log(f"agreed PV            : {valuation.pv / 100:,.2f}")
    log(f"agreed initial margin: {valuation.initial_margin / 100:,.2f}")
    deltas = delta_ladder(DEMO_TRADES, DEMO_CURVE)
    log("delta ladder (per bp): "
        + ", ".join(f"{t}y={d / 10_000 / 100:,.0f}"
                    for t, d in zip(TENORS, deltas)))
    net.stop_nodes()
    return {
        "pv": valuation.pv,
        "initial_margin": valuation.initial_margin,
    }


if __name__ == "__main__":
    main()

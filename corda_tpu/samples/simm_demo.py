"""SIMM valuation demo (reference `samples/simm-valuation-demo/` — two
nodes agree a portfolio of IRS trades, compute initial margin, and agree
the valuation via flows).

TPU-first redesign of the analytics: the reference bolts on OpenGamma's
Strata library and computes curve sensitivities by bump-and-revalue; here
pricing is a pure JAX function of the zero curve, so

  * portfolio PV is a single vectorised evaluation over (trades x tenors)
    on the accelerator, and
  * the SIMM delta ladder is `jax.jacrev` of that function — exact
    sensitivities from autodiff, no bumping, one compiled program.

The margin aggregation is the ISDA-SIMM-style formula
IM = sqrt(s^T C s) with weighted sensitivities s and tenor correlation C.

Run: python -m corda_tpu.samples.simm_demo
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from ..core.contracts.structures import (
    Contract,
    ContractState,
    TransactionVerificationError,
    TypeOnlyCommandData,
    contract,
)
from ..core.flows.api import (
    FlowException,
    FlowLogic,
    initiated_by,
    initiating_flow,
    startable_by_rpc,
)
from ..core.identity import Party
from ..core.serialization.codec import corda_serializable


# --- trade + portfolio model -------------------------------------------------

#: standard SIMM-ish tenor buckets (years)
TENORS: Tuple[float, ...] = (0.5, 1.0, 2.0, 3.0, 5.0, 10.0, 20.0, 30.0)

#: per-tenor risk weights (bp of sensitivity, demo calibration)
RISK_WEIGHTS: Tuple[float, ...] = (113, 111, 93, 80, 69, 61, 60, 59)

#: inter-tenor correlation falls off with tenor distance (demo calibration)
def _correlation_matrix() -> np.ndarray:
    t = np.asarray(TENORS)
    lt = np.log(t)
    return np.exp(-0.35 * np.abs(lt[:, None] - lt[None, :]))


@corda_serializable(name="simm.IRSTrade")
@dataclass(frozen=True)
class IRSTrade:
    """Vanilla fixed-vs-float swap, annual payments (demo granularity)."""

    trade_id: str = ""
    notional: int = 0          # minor units
    fixed_rate: float = 0.0    # decimal, e.g. 0.03
    maturity_years: float = 0.0
    pay_fixed: bool = True     # True: we pay fixed, receive floating


@corda_serializable(name="simm.PortfolioState")
@dataclass(frozen=True)
class PortfolioState(ContractState):
    party_a: Party = None
    party_b: Party = None
    trades: Tuple = ()
    portfolio_id: str = ""  # the id valuation requests select on
    contract_name = "corda_tpu.samples.Portfolio"

    def __post_init__(self):
        object.__setattr__(self, "trades", tuple(self.trades))

    @property
    def participants(self) -> List:
        return [self.party_a, self.party_b]


@corda_serializable(name="simm.PortfolioCommand")
@dataclass(frozen=True)
class PortfolioCommand(TypeOnlyCommandData):
    kind: str = "Agree"


@contract(name="corda_tpu.samples.Portfolio")
class PortfolioContract(Contract):
    def verify(self, tx) -> None:
        cmds = [
            c for c in tx.commands if isinstance(c.value, PortfolioCommand)
        ]
        if not cmds:
            raise TransactionVerificationError(tx.id, "no portfolio command")
        outs = tx.outputs_of_type(PortfolioState)
        if cmds[0].value.kind == "Agree":
            if len(outs) != 1 or not outs[0].trades:
                raise TransactionVerificationError(
                    tx.id, "agree: one non-empty portfolio output"
                )
            signers = {k.encoded for k in cmds[0].signers}
            for p in outs[0].participants:
                if p.owning_key.encoded not in signers:
                    raise TransactionVerificationError(
                        tx.id, f"agree: {p.name} must sign the portfolio"
                    )


# --- JAX analytics -----------------------------------------------------------

def _trade_arrays(trades) -> dict:
    return {
        "notional": np.asarray([t.notional for t in trades], np.float64),
        "fixed_rate": np.asarray([t.fixed_rate for t in trades], np.float64),
        "maturity": np.asarray(
            [t.maturity_years for t in trades], np.float64
        ),
        "direction": np.asarray(
            [1.0 if t.pay_fixed else -1.0 for t in trades], np.float64
        ),
    }


def _pv_fn(arrs):
    """Returns pv(zero_rates) -> scalar portfolio PV; pure JAX, so both
    the value and its curve jacobian compile to one program each."""
    import jax.numpy as jnp

    tenors = jnp.asarray(TENORS)
    notional = jnp.asarray(arrs["notional"])
    fixed = jnp.asarray(arrs["fixed_rate"])
    maturity = jnp.asarray(arrs["maturity"])
    direction = jnp.asarray(arrs["direction"])

    def pv(zero_rates):
        # linear interpolation of the zero curve at yearly payment times
        years = jnp.arange(1.0, 31.0)                      # (Y,)
        r = jnp.interp(years, tenors, zero_rates)          # (Y,)
        df = jnp.exp(-r * years)                           # (Y,)
        alive = (years[None, :] <= maturity[:, None])      # (T, Y)
        annuity = jnp.sum(df[None, :] * alive, axis=1)     # (T,)
        # par swap rate from the curve: (1 - df_T) / annuity
        df_T = jnp.exp(-jnp.interp(maturity, tenors, zero_rates) * maturity)
        par = (1.0 - df_T) / jnp.maximum(annuity, 1e-9)
        # payer-fixed swap PV = notional * (par - fixed) * annuity
        return jnp.sum(direction * notional * (par - fixed) * annuity)

    return pv


def portfolio_pv(trades, zero_rates) -> float:
    import jax

    pv = jax.jit(_pv_fn(_trade_arrays(trades)))
    return float(pv(np.asarray(zero_rates, np.float64)))


def delta_ladder(trades, zero_rates) -> np.ndarray:
    """dPV/dr per tenor bucket via reverse-mode autodiff (replaces the
    reference's OpenGamma bump-and-revalue sensitivity calc)."""
    import jax

    grad = jax.jit(jax.grad(_pv_fn(_trade_arrays(trades))))
    return np.asarray(grad(np.asarray(zero_rates, np.float64)))


def simm_initial_margin(trades, zero_rates) -> float:
    """ISDA-SIMM-style IR delta margin: weighted sensitivities aggregated
    under the tenor correlation matrix, IM = sqrt(s^T C s)."""
    deltas = delta_ladder(trades, zero_rates) / 10_000.0  # per bp
    s = deltas * np.asarray(RISK_WEIGHTS)
    c = _correlation_matrix()
    return float(np.sqrt(np.maximum(s @ c @ s, 0.0)))


@corda_serializable(name="simm.Valuation")
@dataclass(frozen=True)
class Valuation:
    """What the two parties must agree on, to the cent."""

    portfolio_id: str = ""
    pv: int = 0              # minor units, rounded
    initial_margin: int = 0  # minor units, rounded
    curve: Tuple = ()        # the zero curve used

    def __post_init__(self):
        object.__setattr__(self, "curve", tuple(self.curve))


def compute_valuation(portfolio_id: str, trades, zero_rates) -> Valuation:
    return Valuation(
        portfolio_id=portfolio_id,
        pv=int(round(portfolio_pv(trades, zero_rates))),
        initial_margin=int(round(simm_initial_margin(trades, zero_rates))),
        curve=tuple(float(r) for r in zero_rates),
    )


# --- flows -------------------------------------------------------------------

class ValuationMismatch(FlowException):
    pass


def _portfolio_by_id(hub, portfolio_id: str):
    """The unconsumed PortfolioState matching the requested id (both sides
    must price the SAME book, not whichever state comes first)."""
    states = hub.vault_service.unconsumed_states(
        PortfolioState.contract_name
    )
    return next(
        (
            s.state.data for s in states
            if s.state.data.portfolio_id == portfolio_id
        ),
        None,
    )


@initiating_flow
@startable_by_rpc
class RequestValuationFlow(FlowLogic):
    """Both sides price the SAME portfolio on the SAME curve and must agree
    bit-for-bit (reference simm-valuation-demo's agree-on-valuation round)."""

    def __init__(self, counterparty: Party, portfolio_id: str, curve: Tuple):
        self.counterparty = counterparty
        self.portfolio_id = portfolio_id
        self.curve = tuple(curve)

    def _my_valuation(self):
        portfolio = _portfolio_by_id(self.service_hub, self.portfolio_id)
        if portfolio is None:
            raise FlowException(
                f"no portfolio {self.portfolio_id!r} in the vault"
            )
        return compute_valuation(
            self.portfolio_id, portfolio.trades, self.curve
        )

    def call(self):
        mine = yield self.record(self._my_valuation)
        theirs = yield self.send_and_receive(
            self.counterparty,
            [self.portfolio_id, list(self.curve)],  # codec ships lists
            Valuation,
        )
        if theirs != mine:
            raise ValuationMismatch(
                f"valuations diverge: mine {mine.pv}/{mine.initial_margin} "
                f"theirs {theirs.pv}/{theirs.initial_margin}"
            )
        return mine


@initiated_by(RequestValuationFlow)
class RespondValuationFlow(FlowLogic):
    def __init__(self, counterparty: Party):
        self.counterparty = counterparty

    def call(self):
        req = yield self.receive(self.counterparty, list)
        portfolio_id, curve = req[0], tuple(req[1])
        portfolio = _portfolio_by_id(self.service_hub, portfolio_id)
        if portfolio is None:
            raise FlowException(
                f"responder has no portfolio {portfolio_id!r}"
            )
        valuation = yield self.record(
            lambda: compute_valuation(portfolio_id, portfolio.trades, curve)
        )
        yield self.send(self.counterparty, valuation)
        return valuation


# --- demo driver -------------------------------------------------------------

DEMO_CURVE = (0.031, 0.032, 0.034, 0.035, 0.037, 0.040, 0.042, 0.043)

DEMO_TRADES = (
    IRSTrade("T1", 10_000_000_00, 0.030, 5.0, True),
    IRSTrade("T2", 25_000_000_00, 0.041, 10.0, False),
    IRSTrade("T3", 5_000_000_00, 0.035, 3.0, True),
    IRSTrade("T4", 50_000_000_00, 0.044, 20.0, False),
)


def main(verbose: bool = True) -> dict:
    import jax

    try:  # accelerator if reachable, else CPU (demo must run anywhere)
        jax.devices()
    except RuntimeError:
        jax.config.update("jax_platforms", "cpu")

    from ..core.flows.library import FinalityFlow
    from ..core.transactions.builder import TransactionBuilder
    from ..testing.mocknetwork import MockNetwork

    def log(msg):
        if verbose:
            print(f"[simm-demo] {msg}")

    net = MockNetwork()
    notary = net.create_notary_node(validating=True)
    bank_a = net.create_node("O=Bank A,L=London,C=GB")
    bank_b = net.create_node("O=Bank B,L=New York,C=US")

    # agree the portfolio (both sign; broadcast via finality)
    portfolio = PortfolioState(
        bank_a.info, bank_b.info, DEMO_TRADES, "PORTFOLIO-1"
    )
    builder = TransactionBuilder(notary=notary.info)
    builder.add_output_state(portfolio)
    builder.add_command(
        PortfolioCommand("Agree"),
        bank_a.info.owning_key, bank_b.info.owning_key,
    )
    stx = bank_a.services.sign_initial_transaction(builder)
    sig_b = bank_b.services.key_management_service.sign(
        stx.id.bytes, bank_b.info.owning_key
    )
    stx = stx.with_additional_signature(sig_b)
    h = bank_a.start_flow(FinalityFlow(stx), stx)
    net.run_network()
    h.result.result(timeout=30)
    log(f"portfolio of {len(DEMO_TRADES)} IRS trades agreed + broadcast")

    # both banks value the same book on the same curve and must agree
    h = bank_a.start_flow(
        RequestValuationFlow(bank_b.info, "PORTFOLIO-1", DEMO_CURVE),
        bank_b.info, "PORTFOLIO-1", DEMO_CURVE,
    )
    net.run_network()
    valuation = h.result.result(timeout=60)
    log(f"agreed PV            : {valuation.pv / 100:,.2f}")
    log(f"agreed initial margin: {valuation.initial_margin / 100:,.2f}")
    deltas = delta_ladder(DEMO_TRADES, DEMO_CURVE)
    log("delta ladder (per bp): "
        + ", ".join(f"{t}y={d / 10_000 / 100:,.0f}"
                    for t, d in zip(TENORS, deltas)))
    net.stop_nodes()
    return {
        "pv": valuation.pv,
        "initial_margin": valuation.initial_margin,
    }


if __name__ == "__main__":
    main()

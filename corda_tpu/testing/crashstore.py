"""Simulated power-cut storage (ISSUE 20's tentpole, docs/robustness.md §7).

A `CrashDisk` interposes on every durable write path that goes through a
swappable IO namespace — the broker journal (`messaging.broker.jio`),
atomic-JSON files (`utils.atomicfile.io`) — and RECORDS which sqlite
databases a node opens (`node.database.connect_factory`). Writes live in
memory while interposed; `power_cut()` then decides, seeded, what a real
disk would have kept:

  * buffered writes VANISH unless fsync'd — `flush()` only moves bytes
    from the app buffer to the simulated OS cache, exactly the page
    cache a power cut eats; `fsync_fh` is what makes data durable;
  * torn writes — an unsynced write survives per 512-byte page, and a
    surviving page can be CUT at an arbitrary byte boundary;
  * reordered unsynced blocks — each page survives independently, so a
    LATER page can persist while an earlier one does not (the write
    reordering disk schedulers actually do);
  * metadata (create/rename/remove) journals PER DIRECTORY in order: an
    unsynced tail survives only as a prefix, `fsync_dir` pins it. A
    rename that survives while its target's data did not yields the
    classic zero-length/torn destination file — the exact bug
    utils/atomicfile.py exists to prevent.

`proc_crash()` models plain process death instead: the OS cache
survives, only app-buffered (unflushed) bytes are lost.

Both calls MATERIALIZE the surviving filesystem onto the real disk, so
recovery code (journal replay, node restart) runs against genuine files
with no simulation in the loop. sqlite tearing is applied to the real
files afterwards via `tear_sqlite_wal()` (sqlite's own WAL checksums
must cope — that is the assertion).

Driven by the seeded `testing/faults.py` machinery: the workload runs
under `faults.inject(seed=...)` with "crash" rules on registered
durability barriers (utils/faultpoints.CRASH_POINTS), and this module's
randomness comes from one `random.Random` the caller seeds — a failing
crash-matrix cell replays exactly. tools/crashmc.py is the driver.
"""
from __future__ import annotations

import contextlib
import os
import random
from typing import Dict, List, Optional, Tuple

#: survival granularity: disks commit caches in pages; 512 is the
#: traditional sector size (torn boundaries inside a page come from the
#: additional byte-level tear below)
PAGE = 512


class CrashFile:
    """One open handle on the simulated disk. Writes buffer in the app
    until `flush()` (close flushes, like CPython file objects); reads
    see the handle's snapshot at open."""

    def __init__(self, disk: "CrashDisk", path: str, mode: str):
        self._disk = disk
        self._path = path
        self._text = "b" not in mode
        self._reading = "r" in mode and "+" not in mode
        self._buf: List[bytes] = []
        self.closed = False
        if self._reading:
            self._data = disk._read_now(path)
            self._pos = 0
        else:
            disk._open_for_write(path, truncate="w" in mode)

    # -- writer side ---------------------------------------------------------

    def write(self, data) -> int:
        if self._text and isinstance(data, str):
            data = data.encode("utf-8")
        self._buf.append(bytes(data))
        return len(data)

    def flush(self) -> None:
        """App buffer -> simulated OS cache (still NOT power-cut safe)."""
        for chunk in self._buf:
            self._disk._write(self._path, chunk)
        self._buf.clear()

    # -- reader side ---------------------------------------------------------

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            out = self._data[self._pos:]
            self._pos = len(self._data)
        else:
            out = self._data[self._pos:self._pos + n]
            self._pos += len(out)
        out = bytes(out)
        return out.decode("utf-8") if self._text else out

    # -- common --------------------------------------------------------------

    def close(self) -> None:
        if not self.closed:
            if not self._reading:
                self.flush()
            self.closed = True

    def __enter__(self) -> "CrashFile":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


# op kinds in the disk log (see power_cut's replay)
_CREATE, _WRITE, _REPLACE, _REMOVE, _FSYNC, _FSYNC_DIR = range(6)


class CrashDisk:
    """The simulated disk: duck-types `utils.atomicfile.io` (open /
    replace / fsync_fh / fsync_dir) and `messaging.broker.jio` (open /
    replace / remove / fsync_fh)."""

    def __init__(self, rng: Optional[random.Random] = None,
                 survive_p: float = 0.5, tear_p: float = 0.5):
        self.rng = rng or random.Random(0)
        self.survive_p = survive_p
        self.tear_p = tear_p
        self._log: List[tuple] = []
        self._base: Dict[str, bytes] = {}   # durable-at-first-touch
        self._fs: Dict[str, bytearray] = {}  # the live (pre-cut) view
        self._gone: set = set()              # removed since first touch
        self.sqlite_paths: List[str] = []    # recorded by interpose()
        #: power_cut() fills this: what the cut actually did, per path —
        #: tests assert "at least one demonstrably-injected torn write"
        self.last_cut: Dict[str, Dict[str, int]] = {}

    # -- live filesystem view ------------------------------------------------

    def _seed(self, path: str) -> None:
        if path in self._fs or path in self._gone:
            return
        if os.path.exists(path):
            with open(path, "rb") as fh:
                blob = fh.read()
            self._base[path] = blob
            self._fs[path] = bytearray(blob)

    def _read_now(self, path: str) -> bytes:
        self._seed(path)
        if path not in self._fs:
            raise FileNotFoundError(path)
        return bytes(self._fs[path])

    def _open_for_write(self, path: str, truncate: bool) -> None:
        self._seed(path)
        if truncate or path not in self._fs:
            self._log.append((_CREATE, path))
            self._fs[path] = bytearray()
            self._gone.discard(path)

    def _write(self, path: str, data: bytes) -> None:
        buf = self._fs[path]
        self._log.append((_WRITE, path, len(buf), data))
        buf += data

    # -- the atomicfile/jio protocol -----------------------------------------

    def open(self, path: str, mode: str = "r") -> CrashFile:
        return CrashFile(self, path, mode)

    def replace(self, src: str, dst: str) -> None:
        self._seed(src)
        self._seed(dst)
        if src not in self._fs:
            raise FileNotFoundError(src)
        self._log.append((_REPLACE, src, dst))
        self._fs[dst] = self._fs.pop(src)
        self._gone.add(src)
        self._gone.discard(dst)

    def remove(self, path: str) -> None:
        self._seed(path)
        if path not in self._fs:
            raise FileNotFoundError(path)
        self._log.append((_REMOVE, path))
        del self._fs[path]
        self._gone.add(path)

    def fsync_fh(self, fh) -> None:
        if isinstance(fh, CrashFile):
            if not fh._reading:
                fh.flush()
            self._log.append((_FSYNC, fh._path))
        else:  # a real handle that predates interposition
            fh.flush()
            os.fsync(fh.fileno())

    def fsync_dir(self, path: str) -> None:
        d = os.path.dirname(os.path.abspath(path)) or "."
        self._log.append((_FSYNC_DIR, d))

    # -- crash semantics -----------------------------------------------------

    def settle(self) -> None:
        """Clean shutdown: everything the OS saw reaches the real disk."""
        self._materialize(self._fs)
        self._reset()

    def proc_crash(self) -> None:
        """Process death, disk fine: the OS cache (every flushed write)
        survives; only app buffers on open CrashFiles are lost — and
        those never reached `_write`, so the live view IS the outcome."""
        self._materialize(self._fs)
        self._reset()

    def power_cut(self) -> Dict[str, Dict[str, int]]:
        """The plug is pulled. Replays the op log deciding survival per
        op (module docstring), materializes the surviving filesystem
        onto the real disk, and returns per-path damage stats
        ({path: {"dropped_pages": n, "torn": n, "lost_meta": n}})."""
        rng = self.rng
        stats: Dict[str, Dict[str, int]] = {}

        def stat(path: str) -> Dict[str, int]:
            return stats.setdefault(
                path, {"dropped_pages": 0, "torn": 0, "lost_meta": 0}
            )

        # 1. data durability horizon: writes to `path` before its LAST
        # fsync survive fully
        fsync_after: Dict[str, int] = {}
        for i, op in enumerate(self._log):
            if op[0] == _FSYNC:
                fsync_after[op[1]] = i
        # 2. metadata: per-directory ordered journal; everything up to
        # the last fsync_dir is pinned, the tail survives as a prefix
        dir_ops: Dict[str, List[int]] = {}
        dir_pinned: Dict[str, int] = {}
        for i, op in enumerate(self._log):
            if op[0] in (_CREATE, _REMOVE):
                d = os.path.dirname(os.path.abspath(op[1])) or "."
                dir_ops.setdefault(d, []).append(i)
            elif op[0] == _REPLACE:
                d = os.path.dirname(os.path.abspath(op[2])) or "."
                dir_ops.setdefault(d, []).append(i)
            elif op[0] == _FSYNC_DIR:
                dir_pinned[op[1]] = i
        meta_ok: set = set()
        for d, idxs in dir_ops.items():
            pinned = dir_pinned.get(d, -1)
            tail = [i for i in idxs if i > pinned]
            keep = rng.randint(0, len(tail))
            meta_ok.update(i for i in idxs if i <= pinned)
            meta_ok.update(tail[:keep])
            for i in tail[keep:]:
                op = self._log[i]
                # journaled filesystems order data-fsync behind the
                # creating dirent (ext4 auto_da_alloc et al.): a CREATE
                # whose file was later fsync'd is pinned even without
                # fsync_dir — renames get no such mercy
                if op[0] == _CREATE and fsync_after.get(op[1], -1) > i:
                    meta_ok.add(i)
                    continue
                stat(op[2] if op[0] == _REPLACE else op[1])["lost_meta"] += 1

        # 3. replay with survival decisions
        fs: Dict[str, bytearray] = {
            p: bytearray(b) for p, b in self._base.items()
        }
        for i, op in enumerate(self._log):
            kind = op[0]
            if kind == _CREATE:
                if i in meta_ok:
                    fs[op[1]] = bytearray()
            elif kind == _REMOVE:
                if i in meta_ok:
                    fs.pop(op[1], None)
            elif kind == _REPLACE:
                if i in meta_ok and op[1] in fs:
                    fs[op[2]] = fs.pop(op[1])
            elif kind == _WRITE:
                _, path, off, data = op
                if path not in fs:
                    continue  # its create never survived
                buf = fs[path]
                if i < fsync_after.get(path, -1) + 1:
                    _apply(buf, off, data)
                    continue
                # unsynced: page-granular i.i.d. survival + byte tears
                for poff in range(0, len(data), PAGE):
                    piece = data[poff:poff + PAGE]
                    if rng.random() >= self.survive_p:
                        stat(path)["dropped_pages"] += 1
                        continue
                    if len(piece) > 1 and rng.random() < self.tear_p:
                        cut = rng.randrange(1, len(piece))
                        piece = piece[:cut]
                        stat(path)["torn"] += 1
                    _apply(buf, off + poff, piece)
        self._materialize(fs)
        self._reset()
        self.last_cut = stats
        return stats

    # -- real-disk IO --------------------------------------------------------

    def _materialize(self, fs: Dict[str, "bytearray"]) -> None:
        for path in set(self._base) | set(self._fs) | self._gone:
            if path not in fs and os.path.exists(path):
                os.remove(path)
        for path, buf in fs.items():
            os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                        exist_ok=True)
            with open(path, "wb") as fh:
                fh.write(bytes(buf))

    def _reset(self) -> None:
        self._log.clear()
        self._base.clear()
        self._fs.clear()
        self._gone.clear()

    # -- sqlite (real files; the connection factory only records) ------------

    def snapshot_sqlite(self, dst_dir: str) -> Dict[str, str]:
        """Freeze each recorded database as a power-cut image: copy the
        main file and -wal byte-for-byte WHILE the owning connection is
        still live — exactly what the platter holds when the plug is
        pulled mid-flight (sqlite is built to recover such an image; the
        -shm is deliberately not copied, it is rebuilt). Returns
        {original_db_path: snapshot_db_path}; tear the snapshots with
        tear_sqlite_wal(out.values())."""
        import shutil

        os.makedirs(dst_dir, exist_ok=True)
        out: Dict[str, str] = {}
        for db_path in dict.fromkeys(self.sqlite_paths):
            if not os.path.exists(db_path):
                continue
            dst = os.path.join(dst_dir, os.path.basename(db_path))
            shutil.copyfile(db_path, dst)
            if os.path.exists(db_path + "-wal"):
                shutil.copyfile(db_path + "-wal", dst + "-wal")
            out[db_path] = dst
        return out

    def tear_sqlite_wal(self, db_paths=None) -> List[str]:
        """Truncate each database's -wal file at a seeded arbitrary
        offset — the torn tail a power cut leaves when sqlite ran
        synchronous=NORMAL (WAL fsync deferred to checkpoint). sqlite's
        per-frame checksums must absorb it: recovery opens the db and
        silently drops the tail; a node that WEDGES instead fails the
        matrix. Operates on `db_paths` (usually snapshot_sqlite output)
        or, by default, every recorded path — the files must not have a
        live writer."""
        torn: List[str] = []
        for db_path in dict.fromkeys(db_paths or self.sqlite_paths):
            wal = db_path + "-wal"
            try:
                size = os.path.getsize(wal)
            except OSError:
                continue
            if size <= 32:  # nothing beyond the WAL header
                continue
            cut = self.rng.randrange(32, size)
            with open(wal, "r+b") as fh:
                fh.truncate(cut)
            torn.append(wal)
        return torn


def _apply(buf: bytearray, off: int, data: bytes) -> None:
    """Write `data` at `off`, zero-filling any gap (a surviving block
    past holes reads back zeros, like allocated-but-unwritten extents)."""
    if off > len(buf):
        buf += b"\x00" * (off - len(buf))
    buf[off:off + len(data)] = data


@contextlib.contextmanager
def interpose(disk: Optional[CrashDisk] = None,
              rng: Optional[random.Random] = None):
    """Swap the process's durable-write seams for `disk` (or a fresh
    seeded one): atomicfile's IO, the broker journal's IO, and the
    sqlite connection factory (record-only — sqlite keeps writing real
    files; tearing happens post-cut via tear_sqlite_wal). Restores every
    seam on exit. The caller must end the simulation with one of
    power_cut() / proc_crash() / settle() — usually inside the block —
    or in-memory writes are dropped on the floor."""
    from ..messaging import broker
    from ..node import database
    from ..utils import atomicfile

    d = disk or CrashDisk(rng=rng)
    prev_io = atomicfile.io
    prev_jio = broker.jio
    prev_cf = database.connect_factory

    def recording_connect(path, *args, **kw):
        if isinstance(path, str) and path != ":memory:":
            d.sqlite_paths.append(path)
        return prev_cf(path, *args, **kw)

    atomicfile.io = d
    broker.jio = d
    database.connect_factory = recording_connect
    try:
        yield d
    finally:
        atomicfile.io = prev_io
        broker.jio = prev_jio
        database.connect_factory = prev_cf

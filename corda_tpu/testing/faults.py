"""Deterministic fault injection for recovery tests.

The process-level chaos harness (`loadtest/chaos.py`) proves recovery by
killing real OS processes, but a 10-minute soak cannot run in tier-1.
This module makes the SAME failure modes provable in fast deterministic
tests: seeded, scoped injection points on broker send/receive (drop /
delay / duplicate), the verifier worker (crash before/after ack,
corrupt response), and the notary commit path.

    from corda_tpu.testing import faults

    with faults.inject(seed=7) as fi:
        fi.rule("verifier.worker", "crash_after_ack", times=1)
        fi.rule("broker.send", "drop", match="verifier.requests",
                probability=0.5)
        ... drive the system; assert recovery invariants ...

Rules are consulted in registration order; the first armed rule whose
point, match and (seeded) probability agree supplies the action and
consumes one of its `times`. Everything random comes from ONE
`random.Random(seed)`, so a failing run replays exactly.

`fire(point)` lets test code place ITS OWN injection points (e.g. a flow
body raising a transient error on the first attempt only) through the
same seeded rule machinery as the built-in seams.
"""
from __future__ import annotations

import random
import threading
from typing import Any, List, Optional

from ..utils import faultpoints


class Rule:
    """One armed fault: point + action, optionally scoped and bounded."""

    def __init__(self, point: str, action: Any, match: Optional[str] = None,
                 times: Optional[int] = 1, probability: float = 1.0):
        self.point = point
        self.action = action
        self.match = match
        self.times = times  # None = unlimited
        self.probability = probability
        self.fired = 0

    def _matches_detail(self, detail: dict) -> bool:
        if self.match is None:
            return True
        return any(
            self.match in str(v) for v in detail.values() if v is not None
        )

    def consider(self, rng: random.Random, point: str, detail: dict):
        """The action if this rule fires for the crossing, else None."""
        if point != self.point:
            return None
        if self.times is not None and self.fired >= self.times:
            return None
        if not self._matches_detail(detail):
            return None
        if self.probability < 1.0 and rng.random() >= self.probability:
            return None
        self.fired += 1
        return self.action


class FaultInjector:
    """A seeded rule set implementing the faultpoints hook protocol."""

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self.rules: List[Rule] = []
        self._lock = threading.Lock()
        self.log: List[tuple] = []  # (point, action) of every fired fault

    def rule(self, point: str, action: Any, match: Optional[str] = None,
             times: Optional[int] = 1, probability: float = 1.0) -> Rule:
        """Arm one fault; returns the Rule (its `.fired` count is the
        assertion surface for "the fault actually happened")."""
        r = Rule(point, action, match=match, times=times,
                 probability=probability)
        with self._lock:
            self.rules.append(r)
        return r

    def __call__(self, point: str, **detail):
        with self._lock:
            for r in self.rules:
                action = r.consider(self.rng, point, detail)
                if action is not None:
                    self.log.append((point, action))
                    return action
        return None

    def fire(self, point: str, **detail):
        """Explicit injection point for test code (flow bodies, stubs):
        raises the rule's action if it is an exception instance/class,
        otherwise returns it (None when nothing fires)."""
        action = self(point, **detail)
        if isinstance(action, BaseException):
            raise action
        if isinstance(action, type) and issubclass(action, BaseException):
            raise action(f"injected fault at {point}")
        return action


class inject:
    """Scoped installation: `with faults.inject(seed=7) as fi:` arms `fi`
    as the process fault hook and restores the previous hook on exit —
    nestable, exception-safe, and never leaks into later tests."""

    def __init__(self, seed: int = 0,
                 injector: Optional[FaultInjector] = None):
        self.injector = injector or FaultInjector(seed)
        self._prev = None

    def __enter__(self) -> FaultInjector:
        self._prev = faultpoints.set_hook(self.injector)
        return self.injector

    def __exit__(self, *exc_info):
        faultpoints.set_hook(self._prev)
        return False

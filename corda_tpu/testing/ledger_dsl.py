"""Ledger/transaction test DSL (reference `test-utils/.../TestDSL.kt` +
`LedgerDSLInterpreter.kt`: the `ledger { transaction { ... verifies() } }`
pattern every reference contract test uses).

    with ledger(notary=NOTARY) as l:
        with l.transaction() as tx:
            tx.output("out1", CashState(...))
            tx.command(bank_key, CashCommand.Issue())
            tx.verifies()
        with l.transaction() as tx:
            tx.input("out1")
            tx.output("out2", CashState(...))
            tx.command(alice_key, CashCommand.Move())
            tx.fails_with("not conserved")
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..core.contracts.structures import (
    Attachment,
    Command,
    StateAndRef,
    StateRef,
    TimeWindow,
    TransactionState,
)
from ..core.crypto.secure_hash import SecureHash
from ..core.identity import Party
from ..core.transactions.builder import TransactionBuilder
from ..core.transactions.wire import WireTransaction


class DSLError(AssertionError):
    pass


class LedgerDSL:
    """Holds labelled outputs across transactions."""

    def __init__(self, notary: Party):
        self.notary = notary
        self._labelled: Dict[str, StateAndRef] = {}
        self._transactions: List[WireTransaction] = []
        self._attachments: Dict[SecureHash, Attachment] = {}

    def transaction(self, label: Optional[str] = None) -> "TransactionDSL":
        return TransactionDSL(self, label)

    def attachment(self, data: bytes) -> SecureHash:
        att = Attachment.of(data)
        self._attachments[att.id] = att
        return att.id

    def retrieve_output(self, label: str) -> StateAndRef:
        if label not in self._labelled:
            raise DSLError(f"no output labelled {label!r}")
        return self._labelled[label]

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _resolve(self, ref: StateRef) -> TransactionState:
        for wtx in self._transactions:
            if wtx.id == ref.txhash:
                return wtx.outputs[ref.index]
        raise DSLError(f"cannot resolve {ref}")


class TransactionDSL:
    def __init__(self, ledger_dsl: LedgerDSL, label: Optional[str]):
        self.ledger = ledger_dsl
        self.label = label
        self._builder = TransactionBuilder(notary=ledger_dsl.notary)
        self._output_labels: List[Optional[str]] = []
        self._verified = False

    # -- building ------------------------------------------------------------

    def input(self, label_or_state_and_ref) -> "TransactionDSL":
        if isinstance(label_or_state_and_ref, str):
            snr = self.ledger.retrieve_output(label_or_state_and_ref)
        else:
            snr = label_or_state_and_ref
        self._builder.add_input_state(snr)
        return self

    def output(self, label=None, state=None, notary=None) -> "TransactionDSL":
        if state is None:  # allow output(state) positional style
            label, state = None, label
        self._builder.add_output_state(state, notary=notary)
        self._output_labels.append(label)
        return self

    def command(self, *keys_then_value) -> "TransactionDSL":
        *keys, value = keys_then_value
        self._builder.add_command(value, *keys)
        return self

    def attachment(self, att_id: SecureHash) -> "TransactionDSL":
        self._builder.add_attachment(att_id)
        return self

    def time_window(self, tw: TimeWindow) -> "TransactionDSL":
        self._builder.set_time_window(tw)
        return self

    # -- assertions ----------------------------------------------------------

    def _to_ledger_transaction(self):
        wtx = self._builder.to_wire_transaction()
        return wtx, wtx.to_ledger_transaction(
            resolve_state=self.ledger._resolve,
            resolve_attachment=lambda h: self.ledger._attachments[h],
        )

    def verifies(self) -> "TransactionDSL":
        wtx, ltx = self._to_ledger_transaction()
        ltx.verify()
        self._commit(wtx)
        return self

    def fails(self) -> "TransactionDSL":
        _, ltx = self._to_ledger_transaction()
        try:
            ltx.verify()
        except Exception:
            return self
        raise DSLError("expected verification to fail, but it passed")

    def fails_with(self, substring: str) -> "TransactionDSL":
        _, ltx = self._to_ledger_transaction()
        try:
            ltx.verify()
        except Exception as exc:
            if substring.lower() not in str(exc).lower():
                raise DSLError(
                    f"expected failure containing {substring!r}, got: {exc}"
                )
            return self
        raise DSLError("expected verification to fail, but it passed")

    def _commit(self, wtx: WireTransaction) -> None:
        if self._verified:
            return
        self._verified = True
        self.ledger._transactions.append(wtx)
        for idx, label in enumerate(self._output_labels):
            if label is not None:
                self.ledger._labelled[label] = wtx.out_ref(idx)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def ledger(notary: Party) -> LedgerDSL:
    return LedgerDSL(notary)

"""Smoke-test utilities: launch a deployed node directory as a black box
and RPC into it (reference `smoke-test-utils/.../NodeProcess.kt:1-159` —
`Factory.create` writes the config, spawns the packaged JVM, polls the RPC
port; here the "package" is `python -m corda_tpu.node` on a cordform-style
node directory).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Dict, Optional


class SmokeTestError(Exception):
    pass


class NodeProcess:
    """A running black-box node. Use NodeProcess.Factory to create."""

    def __init__(self, proc: subprocess.Popen, node_dir: str, log_path: str):
        self._proc = proc
        self.node_dir = node_dir
        self.log_path = log_path
        self.broker_port: Optional[int] = None
        #: ops endpoint port, when the node.conf asked for one (the
        #: fleet observatory's probe target); None otherwise
        self.ops_port: Optional[int] = None
        self._clients = []

    def log(self) -> str:
        try:
            with open(self.log_path) as fh:
                return fh.read()
        except OSError:
            return ""

    def alive(self) -> bool:
        return self._proc.poll() is None

    def connect(self, username: str = "admin", password: str = "admin",
                cordapps=("corda_tpu.finance.flows",)):
        """RPC connection to the black box (reference NodeProcess.connect)."""
        import importlib

        for mod in cordapps:
            importlib.import_module(mod)
        from ..messaging.net import RemoteBroker
        from ..rpc.client import CordaRPCClient

        client = CordaRPCClient(RemoteBroker("127.0.0.1", self.broker_port))
        self._clients.append(client)
        return client.start(username, password)

    # -- fault injection (reference Disruption.kt:17-90 runs these over
    # SSH against a remote cluster; here the cluster is local processes) --

    def kill(self) -> None:
        """SIGKILL — no cleanup, no flushes (the 'kill' disruption)."""
        import signal as _signal

        if self.alive():
            self._proc.send_signal(_signal.SIGKILL)
            self._proc.wait(timeout=10)

    def suspend(self) -> None:
        """SIGSTOP — the 'hang' disruption: the process keeps its sockets
        but stops responding, exactly like a GC pause / hung JVM."""
        import signal as _signal

        self._proc.send_signal(_signal.SIGSTOP)

    def resume(self) -> None:
        import signal as _signal

        self._proc.send_signal(_signal.SIGCONT)

    def delete_message_store(self) -> None:
        """rm -rf the broker journal (the 'deleteDb' disruption wipes the
        reference's artemis dir). Only meaningful while stopped."""
        import shutil

        shutil.rmtree(
            os.path.join(self.node_dir, "journal"), ignore_errors=True
        )

    def close(self, timeout: float = 10) -> None:
        for c in self._clients:
            try:
                c.close()
            except Exception:
                pass
        self._clients.clear()
        if self.alive():
            self._proc.terminate()
            try:
                self._proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait(timeout=timeout)

    def __enter__(self) -> "NodeProcess":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class Factory:
    """Creates black-box nodes under a working directory (reference
    `NodeProcess.Factory`)."""

    def __init__(self, build_dir: str, jax_platform: Optional[str] = "cpu"):
        self.build_dir = build_dir
        self.jax_platform = jax_platform

    def create(self, conf: Dict, timeout: float = 120) -> NodeProcess:
        name = conf.get("my_legal_name", "node").replace(" ", "-").replace(
            ",", "_"
        )
        node_dir = os.path.join(self.build_dir, name)
        os.makedirs(node_dir, exist_ok=True)
        with open(os.path.join(node_dir, "node.conf"), "w") as fh:
            json.dump(conf, fh)
        return self.launch(node_dir, timeout=timeout)

    def launch(self, node_dir: str, timeout: float = 120) -> NodeProcess:
        """Boot an EXISTING node directory (e.g. one materialised by
        tools/cordform.deploy_nodes) as a black box."""
        log_path = os.path.join(node_dir, "node.log")
        # stale handshake files from a previous (killed) run would make
        # the readiness poll below return before the new process binds
        ready_file = os.path.join(node_dir, "ready.json")
        for stale in (os.path.join(node_dir, "broker.port"), ready_file):
            if os.path.exists(stale):
                os.unlink(stale)
        env = dict(os.environ)
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        )))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        # launcher death (SIGKILL, test timeout) must not leak node
        # processes that contend with the rest of the session
        env["CORDA_TPU_EXIT_ON_ORPHAN"] = "1"
        args = [sys.executable, "-m", "corda_tpu.node", node_dir,
                "--ready-file", ready_file]
        if self.jax_platform:
            args += ["--jax-platform", self.jax_platform]
        proc = subprocess.Popen(
            args, stdout=open(log_path, "w"), stderr=subprocess.STDOUT, env=env
        )
        node = NodeProcess(proc, node_dir, log_path)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if not node.alive():
                raise SmokeTestError(f"node died on startup:\n{node.log()}")
            # the ready file carries everything (broker + ops port) in
            # one atomic JSON, and lands AFTER broker.port — waiting on
            # it alone avoids racing the window between the two writes
            if os.path.exists(ready_file):
                with open(ready_file) as fh:
                    ready = json.load(fh)
                node.broker_port = int(ready["broker_port"])
                node.ops_port = ready.get("ops_port")
                return node
            time.sleep(0.1)
        node.close()
        raise SmokeTestError(f"node did not start in {timeout}s:\n{node.log()}")

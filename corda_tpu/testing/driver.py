"""Driver DSL: spawn real node/verifier OS processes for integration tests.

Reference parity: `test-utils/src/main/kotlin/net/corda/testing/driver/
Driver.kt:94-141, 252-263` (out-of-process node startup, port allocation,
RPC connection, shutdown management) and `smoke-test-utils/.../
NodeProcess.kt:1-159` (launch the packaged node as a black box, RPC in).
The verifier flavour mirrors `verifier/src/integration-test/.../
VerifierDriver.kt` — a bare broker host plus N external verifier
processes.

Usage:

    with driver() as d:
        broker = d.start_broker()                    # in-driver broker + TCP server
        v = d.start_verifier(broker.address)          # real subprocess
        node = d.start_node({"my_legal_name": "Bank A"})
        rpc = node.rpc()                              # CordaRPCClient over TCP
        ...
        v.kill()                                      # SIGKILL: redelivery proof

Subprocesses default to the CPU JAX backend (tests must not depend on TPU
hardware); pass jax_platform=None to inherit the environment's backend.
"""
from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..messaging import Broker
from ..messaging.net import BrokerServer, RemoteBroker


class DriverError(Exception):
    pass


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_for(predicate, timeout: float, what: str) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.05)
    raise DriverError(f"timed out waiting for {what}")


def _try_connect(host: str, port: int) -> bool:
    try:
        with socket.create_connection((host, port), timeout=0.25):
            return True
    except OSError:
        return False


@dataclass
class BrokerHandle:
    broker: Broker
    server: BrokerServer

    @property
    def address(self) -> str:
        return f"{self.server.host}:{self.server.port}"

    def remote(self) -> RemoteBroker:
        return RemoteBroker(self.server.host, self.server.port)


class ProcessHandle:
    """A spawned subprocess with log capture and crash-style termination."""

    def __init__(self, proc: subprocess.Popen, log_path: str, name: str):
        self.proc = proc
        self.log_path = log_path
        self.name = name

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        """SIGKILL — simulates a crash (no graceful ack/close)."""
        if self.alive():
            self.proc.kill()
            self.proc.wait(timeout=10)

    def terminate(self, timeout: float = 10) -> int:
        if self.alive():
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=5)
        return self.proc.returncode

    def log(self) -> str:
        try:
            with open(self.log_path) as fh:
                return fh.read()
        except OSError:
            return ""


class NodeHandle(ProcessHandle):
    def __init__(self, proc, log_path, name, base_dir, host, cordapps=(),
                 conf: Optional[Dict] = None):
        super().__init__(proc, log_path, name)
        self.base_dir = base_dir
        self.host = host
        self.cordapps = tuple(cordapps)
        self.conf = dict(conf or {})
        self.broker_port: Optional[int] = None

    def _client_wrap(self):
        """TLS wrap for clients of this node's broker: a driver-side dev
        identity chained to the node's trust root (shared certificates
        directory)."""
        if not self.conf.get("tls"):
            return None
        from ..core.crypto import pki

        cert_dir = self.conf.get("certificates_dir")
        if not os.path.isabs(cert_dir or ""):
            cert_dir = os.path.join(self.base_dir, cert_dir or "certificates")
        entries = pki.dev_certificates(cert_dir, "O=Driver,L=Test,C=GB")
        return pki.client_wrap(pki.client_ssl_context(cert_dir, entries))

    def rpc(self, timeout: float = 15.0):
        """CordaRPCClient over the node's TCP broker.

        Imports the node's CorDapp modules first so their serializable
        types are registered in THIS process — the analogue of putting
        CorDapp JARs on the reference RPC client's classpath."""
        import importlib

        from ..rpc.client import CordaRPCClient

        for mod in self.cordapps:
            importlib.import_module(mod)
        return CordaRPCClient(
            RemoteBroker(self.host, self.broker_port,
                         client_wrap=self._client_wrap()),
            timeout=timeout,
        )

    def remote_broker(self) -> RemoteBroker:
        return RemoteBroker(self.host, self.broker_port,
                            client_wrap=self._client_wrap())


class Driver:
    def __init__(self, base_dir: str, jax_platform: Optional[str] = "cpu"):
        self.base_dir = base_dir
        self.jax_platform = jax_platform
        self._brokers: List[BrokerHandle] = []
        self._procs: List[ProcessHandle] = []
        self._remotes: List[RemoteBroker] = []
        self._counter = 0

    # -- in-driver broker host (VerifierDriver.startVerificationRequestor) --

    def start_broker(self, journal_dir: Optional[str] = None) -> BrokerHandle:
        broker = Broker(journal_dir=journal_dir)
        server = BrokerServer(broker, port=0).start()
        h = BrokerHandle(broker, server)
        self._brokers.append(h)
        return h

    # -- subprocesses --------------------------------------------------------

    def _spawn(self, args: List[str], name: str, env_extra=None) -> ProcessHandle:
        self._counter += 1
        log_path = os.path.join(self.base_dir, f"{name}-{self._counter}.log")
        log = open(log_path, "w")
        env = dict(os.environ)
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        )))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        env.update(env_extra or {})
        proc = subprocess.Popen(
            [sys.executable, *args],
            stdout=log, stderr=subprocess.STDOUT, env=env,
        )
        h = ProcessHandle(proc, log_path, name)
        self._procs.append(h)
        return h

    def start_verifier(
        self, broker_address: str, workers: int = 1, name: str = "verifier"
    ) -> ProcessHandle:
        args = [
            "-m", "corda_tpu.verifier",
            "--connect", broker_address,
            "--workers", str(workers),
            "--name", name,
        ]
        if self.jax_platform:
            args += ["--jax-platform", self.jax_platform]
        h = self._spawn(args, name)
        host, port_s = broker_address.rsplit(":", 1)
        _wait_for(
            lambda: "verifier ready" in h.log() or not h.alive(),
            timeout=120, what=f"{name} to come up",
        )
        if not h.alive():
            raise DriverError(f"{name} died on startup:\n{h.log()}")
        return h

    def start_node(
        self, conf: Dict, name: Optional[str] = None, timeout: float = 120
    ) -> NodeHandle:
        name = name or conf.get("my_legal_name", "node").replace(" ", "-")
        node_dir = os.path.join(self.base_dir, name)
        os.makedirs(node_dir, exist_ok=True)
        with open(os.path.join(node_dir, "node.conf"), "w") as fh:
            json.dump(conf, fh)
        args = ["-m", "corda_tpu.node", node_dir]
        if self.jax_platform:
            args += ["--jax-platform", self.jax_platform]
        self._counter += 1
        log_path = os.path.join(self.base_dir, f"{name}.log")
        log = open(log_path, "w")
        env = dict(os.environ)
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        )))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, *args],
            stdout=log, stderr=subprocess.STDOUT, env=env,
        )
        from ..node.config import DEFAULTS as _NODE_DEFAULTS

        h = NodeHandle(proc, log_path, name, node_dir,
                       conf.get("broker_host", "127.0.0.1"),
                       cordapps=conf.get("cordapps", _NODE_DEFAULTS["cordapps"]),
                       conf=conf)
        self._procs.append(h)
        _wait_for(
            lambda: "node ready" in h.log() or not h.alive(),
            timeout=timeout, what=f"node {name} to come up",
        )
        if not h.alive():
            raise DriverError(f"node {name} died on startup:\n{h.log()}")
        port_file = os.path.join(node_dir, "broker.port")

        def _port_ready() -> bool:
            # tolerate a created-but-unflushed file (the node now writes
            # atomically, but old artifacts may predate that)
            if not os.path.exists(port_file):
                return False
            with open(port_file) as fh:
                return bool(fh.read().strip())

        _wait_for(_port_ready, 10, "broker.port file")
        with open(port_file) as fh:
            h.broker_port = int(fh.read().strip())
        _wait_for(
            lambda: _try_connect(h.host, h.broker_port), 10,
            "node broker port to accept",
        )
        return h

    def remote(self, address: str) -> RemoteBroker:
        host, port_s = address.rsplit(":", 1)
        r = RemoteBroker(host, int(port_s))
        self._remotes.append(r)
        return r

    def shutdown(self) -> None:
        for r in self._remotes:
            try:
                r.close()
            except Exception:
                pass
        for p in self._procs:
            try:
                p.terminate(timeout=5)
            except Exception:
                pass
        for b in self._brokers:
            try:
                b.server.stop()
                b.broker.close()
            except Exception:
                pass


class driver:
    """Context-manager entry point (the reference `driver {}` block)."""

    def __init__(self, base_dir: Optional[str] = None,
                 jax_platform: Optional[str] = "cpu"):
        self._base_dir = base_dir
        self._tmp = None
        self._jax_platform = jax_platform

    def __enter__(self) -> Driver:
        if self._base_dir is None:
            self._tmp = tempfile.TemporaryDirectory(prefix="corda-driver-")
            self._base_dir = self._tmp.name
        self._driver = Driver(self._base_dir, jax_platform=self._jax_platform)
        return self._driver

    def __exit__(self, *exc) -> None:
        self._driver.shutdown()
        if self._tmp is not None:
            self._tmp.cleanup()

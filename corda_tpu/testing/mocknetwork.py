"""MockNetwork: N nodes in one process over a deterministically pumped
in-memory transport (reference `test-utils/.../node/MockNode.kt:50-90` +
`InMemoryMessagingNetwork.kt`).

    net = MockNetwork()
    notary = net.create_notary_node("O=Notary,L=Zurich,C=CH", validating=True)
    alice = net.create_node("O=Alice,L=London,C=GB")
    handle = alice.start_flow(SomeFlow(...), ...)
    net.run_network()          # pump until quiescent
    result = handle.result.result(timeout=0)
"""
from __future__ import annotations

from typing import List, Optional

from ..core.identity import Party
from ..node.network import InMemoryMessagingNetwork
from ..node.node import AbstractNode, NodeConfiguration


class MockNode(AbstractNode):
    pass


class _RaftBus:
    """Deterministic in-process transport for one Raft consensus group
    (virtual time advances only through elect()); `kill(i)` + `elect()`
    drive leader-failover tests."""

    def __init__(self):
        from collections import deque

        self.queue = deque()
        self.nodes = {}        # raft id -> RaftNode
        self.dead = set()
        self._draining = False
        self.now = 0.0

    def send(self, src, dst, payload):
        self.queue.append((src, dst, payload))
        self.drain()

    def drain(self):
        if self._draining:
            return
        self._draining = True
        try:
            while self.queue:
                src, dst, payload = self.queue.popleft()
                if src in self.dead or dst in self.dead:
                    continue
                node = self.nodes.get(dst)
                if node is not None:
                    node.on_message(src, payload)
        finally:
            self._draining = False

    def kill(self, raft_id: str) -> None:
        self.dead.add(raft_id)

    def revive(self, raft_id: str) -> None:
        self.dead.discard(raft_id)

    def leader(self):
        from ..node.raft import LEADER

        for rid, node in self.nodes.items():
            if rid not in self.dead and node.role == LEADER:
                return node
        return None

    def elect(self, max_ticks: int = 600):
        """Advance virtual time until a live leader exists."""
        for _ in range(max_ticks):
            ldr = self.leader()
            if ldr is not None:
                return ldr
            self.now += 0.05
            for rid, node in self.nodes.items():
                if rid not in self.dead:
                    node.tick(self.now)
            self.drain()
        raise RuntimeError("no raft leader elected")


class _RaftClusterProvider:
    """Commit via the current leader, retrying across elections —
    the client-side failover the reference gets from CopycatClient."""

    def __init__(self, providers, bus):
        # raft id -> RaftUniquenessProvider; public so tests and
        # the multichip dryrun can observe per-REPLICA state
        # (replication evidence, not just the cluster answer)
        self.member_providers = providers
        self.bus = bus

    def commit(self, states, tx_id, requesting_party):
        from ..node.raft import NotLeaderError

        last_exc = None
        for _ in range(5):
            leader = self.bus.elect()
            provider = self.member_providers[leader.node_id]
            try:
                return provider.commit(states, tx_id, requesting_party)
            except NotLeaderError as exc:  # lost leadership mid-commit
                last_exc = exc
                self.bus.now += 1.0
        raise last_exc

    def commit_many(self, requests):
        """Batched commits ride ONE Raft log entry on the current
        leader (same failover-retry loop as commit)."""
        from ..node.raft import NotLeaderError

        last_exc = None
        for _ in range(5):
            leader = self.bus.elect()
            provider = self.member_providers[leader.node_id]
            try:
                return provider.commit_many(requests)
            except NotLeaderError as exc:
                last_exc = exc
                self.bus.now += 1.0
        raise last_exc

    def probe_commits(self, keys):
        """Committed-state read (sharded cross-shard prepare) from the
        current leader's APPLIED log."""
        leader = self.bus.elect()
        return self.member_providers[leader.node_id].probe_commits(keys)

    def is_consumed(self, ref) -> bool:
        return any(
            p.is_consumed(ref)
            for p in self.member_providers.values()
        )

    def replicas_consumed(self, ref) -> int:
        """How many replicas' APPLIED logs know `ref` as spent."""
        return sum(
            1 for p in self.member_providers.values()
            if p.is_consumed(ref)
        )


def make_raft_commit_group(n_replicas: int = 3, seed_base: int = 0):
    """One standalone Raft consensus group over the commit log: the
    building block a sharded notary runs PER SHARD (docs/sharding.md —
    `MockNetwork.create_sharded_notary_node`). Returns (provider, bus);
    `bus.kill(bus.elect().node_id)` is the shard-leader-kill seam."""
    from ..node.database import NodeDatabase
    from ..node.notary import RaftUniquenessProvider
    from ..node.raft import RaftNode

    bus = _RaftBus()
    ids = [f"r{i}" for i in range(n_replicas)]
    providers = {}

    def make_transport(src):
        def transport(dst, payload):
            bus.send(src, dst, payload)
        return transport

    def make_apply(rid):
        def apply(cmd):
            return providers[rid].apply(cmd)
        return apply

    for i, rid in enumerate(ids):
        node = RaftNode(
            rid, ids, make_transport(rid), make_apply(rid),
            db=NodeDatabase(":memory:"), seed=seed_base + i,
        )
        bus.nodes[rid] = node
        providers[rid] = RaftUniquenessProvider(
            node, NodeDatabase(":memory:")
        )
    bus.elect()
    return _RaftClusterProvider(providers, bus), bus


class MockNetwork:
    def __init__(self, default_clock=None, flow_lanes: int = 0):
        """default_clock: shared zero-arg clock for all nodes (a TestClock
        makes the whole network deterministic, reference Simulation style);
        None -> real time per node.

        flow_lanes: OPT-IN multi-lane continuation dispatch on the
        in-memory transport (node/flowlanes.py) — session messages run
        their handlers on N lane threads with per-flow affinity, and
        run_network() barriers on lane quiescence. The default (0) keeps
        the transport fully inline/deterministic, like
        `dispatches_blocking_off_pump` defaults off in-memory."""
        self.messaging_network = InMemoryMessagingNetwork()
        if flow_lanes:
            self.messaging_network.enable_flow_lanes(flow_lanes)
        self.nodes: List[MockNode] = []
        self._entropy = 1000
        self.default_clock = default_clock
        self._clusters: List = []  # (cluster_party, advertised_services)

    def _next_entropy(self) -> int:
        self._entropy += 1
        return self._entropy

    def create_node(
        self,
        legal_name: str,
        notary_type: Optional[str] = None,
        db_path: str = ":memory:",
        entropy: Optional[int] = None,
        clock=None,
        dev_checkpoint_check: bool = True,
        ops_port: Optional[int] = None,
        admission_rate: Optional[float] = None,
        admission_burst: Optional[float] = None,
        admission_max_flows: Optional[int] = None,
        shards: Optional[int] = None,
        domain: Optional[str] = None,
        gateway: bool = False,
    ) -> MockNode:
        """`ops_port`: pass 0 to serve this node's /metrics + /traces on
        an ephemeral port (node.ops_server.port); None = no endpoint.
        `admission_*`: overload-protection knobs (docs/robustness.md) —
        with neither rate nor max_flows set, admission is inert.
        `shards`: partition a notary node's uniqueness provider into N
        state-ref-keyed shards with two-phase cross-shard commits
        (docs/sharding.md); None keeps the unsharded default.
        `domain`/`gateway`: multi-domain federation (docs/robustness.md
        §6) — a domained node registers only with same-domain peers,
        domainless peers, and gateways, mirroring the directory node's
        scoped map; both default off, keeping the everyone-sees-everyone
        fan-out byte-identical for every existing test."""
        config = NodeConfiguration(
            my_legal_name=legal_name,
            db_path=db_path,
            notary_type=notary_type,
            identity_entropy=entropy if entropy is not None else self._next_entropy(),
            dev_checkpoint_check=dev_checkpoint_check,
            ops_port=ops_port,
            admission_rate=admission_rate,
            admission_burst=admission_burst,
            admission_max_flows=admission_max_flows,
            shards=shards,
            domain=domain,
            gateway=gateway,
        )
        node = MockNode(
            config, self.messaging_network.create_endpoint,
            clock=clock or self.default_clock,
        )
        node.start()
        # Everyone IN SCOPE learns about everyone in scope (the reference
        # MockNetwork shares a network map; with domains configured the
        # map is domain-scoped): register the new node with existing ones
        # and vice versa, each direction under the viewer's scope.
        for other in self.nodes:
            if self._visible(other.config.advertised_services,
                             node.config.advertised_services):
                other.register_peer(node.info, node.config.advertised_services)
            if self._visible(node.config.advertised_services,
                             other.config.advertised_services):
                node.register_peer(other.info, other.config.advertised_services)
        for cluster, advertised in self._clusters:
            if self._visible(node.config.advertised_services, advertised):
                node.services.network_map_cache.add_node(cluster, advertised)
                node.services.identity_service.register_identity(cluster)
        self.nodes.append(node)
        return node

    @staticmethod
    def _visible(viewer_services, target_services) -> bool:
        """Mirror of the directory node's scoped-map rule: a viewer sees
        its own domain, domainless entries, and advertised gateways; a
        domainless viewer sees everything (kill switch). A GATEWAY
        viewer also sees everything — it is the federation's routing
        anchor, serving cross-domain protocol legs (the notary-change
        ASSUME resolves its back-chain from a foreign-domain client), so
        a scoped view would strand its replies."""
        from ..node.services import NetworkMapCache as _cache

        viewer = tuple(viewer_services)
        viewer_domain = _cache.domain_of_services(viewer)
        if viewer_domain is None or _cache.GATEWAY_SERVICE in viewer:
            return True
        target = tuple(target_services)
        target_domain = _cache.domain_of_services(target)
        return (
            target_domain is None
            or target_domain == viewer_domain
            or _cache.GATEWAY_SERVICE in target
        )

    def create_notary_node(
        self, legal_name: str = "O=Notary,L=Zurich,C=CH", validating: bool = True,
        shards: Optional[int] = None, domain: Optional[str] = None,
        gateway: bool = False,
    ) -> MockNode:
        return self.create_node(
            legal_name, notary_type="validating" if validating else "simple",
            shards=shards, domain=domain, gateway=gateway,
        )

    def create_domain(
        self, name: str, n_nodes: int = 1, validating: bool = True,
        gateway: bool = False,
    ):
        """One federation domain: a GATEWAY notary pinned to `name` plus
        `n_nodes` member nodes (docs/robustness.md §6). Returns
        (notary_node, [member_nodes]). The notary is always a gateway —
        the fleet-visible anchor cross-domain notary changes route
        through; `gateway=True` additionally makes the members
        cross-domain gateways, visible from every other domain."""
        notary = self.create_notary_node(
            f"O=Notary {name},L=Zurich,C=CH", validating=validating,
            domain=name, gateway=True,
        )
        members = [
            self.create_node(
                f"O=Node {name} {i},L=London,C=GB", domain=name,
                gateway=gateway,
            )
            for i in range(n_nodes)
        ]
        return notary, members

    def _assemble_cluster(
        self, n_members, cluster_name, member_prefix, validating,
        threshold, provider_factory, domain=None,
    ):
        """Shared cluster assembly: spawn members, mint the composite
        identity, install per-member notary services on the given
        uniqueness provider, register the service address (round-robin +
        dead-member skip = client failover) and fan the identity out to
        every present and future node."""
        from ..node.cluster_identity import generate_service_identity
        from ..node.notary import SimpleNotaryService, ValidatingNotaryService
        from ..node.services import NetworkMapCache

        members = [
            self.create_node(
                f"O={member_prefix} {i},L=Zurich,C=CH",
                notary_type="validating" if validating else "simple",
                domain=domain,
            )
            for i in range(n_members)
        ]
        cluster = generate_service_identity(
            cluster_name, [m.info.owning_key for m in members], threshold
        )
        provider = provider_factory(cluster, members)
        svc_cls = ValidatingNotaryService if validating else SimpleNotaryService
        advertised = [NetworkMapCache.NOTARY_SERVICE] + (
            [NetworkMapCache.VALIDATING_NOTARY_SERVICE] if validating else []
        )
        if domain is not None:
            advertised.append(NetworkMapCache.DOMAIN_SERVICE_PREFIX + domain)
        for m in members:
            m.notary_service = svc_cls(
                m.services, m.info, uniqueness_provider=provider
            )
            m.services.notary_service = m.notary_service
            self.messaging_network.register_service_endpoint(
                cluster.name, m.info.name
            )
        for node in self.nodes:
            if self._visible(node.config.advertised_services, advertised):
                node.services.network_map_cache.add_node(cluster, advertised)
                node.services.identity_service.register_identity(cluster)
        self._clusters.append((cluster, advertised))
        return cluster, members

    def create_notary_cluster(
        self,
        n_members: int = 3,
        cluster_name: str = "O=Notary Cluster,L=Zurich,C=CH",
        validating: bool = True,
        threshold: int = 1,
        domain: Optional[str] = None,
    ):
        """A distributed notary presenting ONE composite identity
        (reference: Raft/BFT notary clusters + ServiceIdentityGenerator).

        Members share a uniqueness provider (the replicated-commit-log
        abstraction; see create_bft_notary_cluster for real PBFT) and each
        signs with its own leaf key of the composite cluster identity.

        Returns (cluster_party, [member_nodes]).
        """
        from ..node.database import NodeDatabase
        from ..node.notary import PersistentUniquenessProvider

        return self._assemble_cluster(
            n_members, cluster_name, "Notary Member", validating, threshold,
            # own DB: the commit log must survive any member's death
            lambda cluster, members: PersistentUniquenessProvider(
                NodeDatabase(":memory:")
            ),
            domain=domain,
        )

    def create_bft_notary_cluster(
        self,
        n_members: int = 4,
        cluster_name: str = "O=BFT Notary,L=Zurich,C=CH",
        vote_scheme: str = "ed25519",
    ):
        """Byzantine notary cluster: every member runs a PBFT replica of
        the commit log; commits carry f+1 replica signatures over the tx
        id, which fulfil the f+1-threshold composite cluster identity the
        client validates (reference BFTNonValidatingNotaryService +
        BFTSMaRt response extractor).

        vote_scheme="bls" runs the AGGREGATING committee (dev BLS keys +
        proofs of possession distributed to every replica): prepare votes
        are BLS-signed and commit certification is one aggregate check
        per block instead of per-vote verifies (docs/bls-aggregation.md).

        Returns (cluster_party, [member_nodes], bft_bus).
        """
        from collections import deque

        from ..node.bft import BFTClient, BFTReplica, dev_bls_committee
        from ..node.database import NodeDatabase
        from ..node.notary import BFTUniquenessProvider

        class _Bus:
            """Synchronous in-process message bus: every enqueue drains
            unless a drain is already running (replica handlers are not
            re-entered). `dead` simulates crashed/partitioned replicas."""

            def __init__(self):
                self.queue = deque()
                self.replicas = []
                self.client = None
                self._draining = False
                self.dead = set()

            def drain(self):
                if self._draining:
                    return
                self._draining = True
                try:
                    while self.queue:
                        kind, a, b, c = self.queue.popleft()
                        if kind == "msg" and b not in self.dead and a not in self.dead:
                            self.replicas[b].on_message(a, c)
                        elif kind == "req" and b not in self.dead:
                            self.replicas[b].on_request(c)
                        elif kind == "reply" and a not in self.dead:
                            self.client.on_reply(a, b, c)
                finally:
                    self._draining = False

        bus = _Bus()

        def provider_factory(cluster, members):
            # a reply counts toward the f+1 quorum only if conflict-laden
            # or carrying a VALID replica signature over the tx id by a
            # cluster leaf key — a Byzantine replica omitting/forging its
            # signature cannot complete the quorum and starve the client
            leaf_keys = {k.encoded for k in cluster.owning_key.keys}

            def validate_reply(command, result) -> bool:
                if not isinstance(result, dict):
                    return True
                if result.get("conflicts"):
                    return True
                tx_hex = (command or {}).get("tx_id")
                if tx_hex is None:
                    return True
                sig = result.get("tx_sig")
                if sig is None:
                    return False
                try:
                    return (
                        sig.by.encoded in leaf_keys
                        and sig.is_valid(bytes.fromhex(tx_hex))
                    )
                except Exception:
                    return False

            bus.client = BFTClient(
                "notary-cluster", len(members),
                lambda rid, req: (
                    bus.queue.append(("req", None, rid, req)), bus.drain()
                ),
                reply_validator=validate_reply,
            )

            def make_transport(src):
                def transport(dst, payload):
                    bus.queue.append(("msg", src, dst, payload))
                    bus.drain()
                return transport

            def make_reply(idx):
                def reply(client_id, request_id, result):
                    bus.queue.append(("reply", idx, request_id, result))
                    bus.drain()
                return reply

            def make_sign(member):
                def sign_tx(tx_id_bytes: bytes):
                    return member.services.key_management_service.sign(
                        tx_id_bytes, member.info.owning_key
                    )
                return sign_tx

            bls_sks = bls_pubs = bls_pops = None
            if vote_scheme == "bls":
                bls_sks, bls_pubs, bls_pops = dev_bls_committee(len(members))
            for i, m in enumerate(members):
                apply_fn, snap_fn, rest_fn, meta = (
                    BFTUniquenessProvider.make_replica_state(
                        NodeDatabase(":memory:"), sign_tx_fn=make_sign(m)
                    )
                )
                bus.replicas.append(
                    BFTReplica(
                        i, len(members), make_transport(i), apply_fn,
                        make_reply(i), snapshot_fn=snap_fn,
                        restore_fn=rest_fn, meta_store=meta,
                        bls_signing_key=(
                            bls_sks[i] if bls_sks is not None else None
                        ),
                        replica_bls_pubs=bls_pubs,
                        replica_bls_pops=bls_pops,
                    )
                )
            return BFTUniquenessProvider(bus.client, replicas=bus.replicas)

        f = (n_members - 1) // 3
        cluster, members = self._assemble_cluster(
            n_members, cluster_name, "BFT Member", validating=False,
            threshold=f + 1, provider_factory=provider_factory,
        )
        return cluster, members, bus

    def create_raft_notary_cluster(
        self,
        n_members: int = 3,
        cluster_name: str = "O=Raft Notary,L=Zurich,C=CH",
        validating: bool = True,
    ):
        """Crash-fault-tolerant notary cluster: every member runs a Raft
        replica of the commit log (reference RaftValidatingNotaryService
        over Copycat); any member can serve — commits forward to the
        current leader — and the cluster presents a threshold-1 composite
        identity (any member's signature settles it, like the reference's
        CFT semantics).

        Returns (cluster_party, [member_nodes], raft_bus). The bus
        supports `bus.kill(i)` + `bus.elect()` for leader-failover tests.
        """
        from ..node.database import NodeDatabase
        from ..node.notary import RaftUniquenessProvider
        from ..node.raft import RaftNode

        bus = _RaftBus()

        def provider_factory(cluster, members):
            ids = [f"r{i}" for i in range(len(members))]
            providers = {}

            def make_transport(src):
                def transport(dst, payload):
                    bus.send(src, dst, payload)
                return transport

            def make_apply(rid):
                def apply(cmd):
                    return providers[rid].apply(cmd)
                return apply

            for i, rid in enumerate(ids):
                node = RaftNode(
                    rid, ids, make_transport(rid), make_apply(rid),
                    db=NodeDatabase(":memory:"), seed=i,
                )
                bus.nodes[rid] = node
                providers[rid] = RaftUniquenessProvider(
                    node, NodeDatabase(":memory:")
                )
            bus.elect()
            return _RaftClusterProvider(providers, bus)

        cluster, members = self._assemble_cluster(
            n_members, cluster_name, "Raft Member", validating=validating,
            threshold=1, provider_factory=provider_factory,
        )
        return cluster, members, bus

    def create_sharded_notary_node(
        self,
        n_shards: int = 2,
        legal_name: str = "O=Sharded Notary,L=Zurich,C=CH",
        validating: bool = True,
        raft_members: int = 3,
    ):
        """ONE notary node whose uniqueness provider partitions the
        commit log across `n_shards` INDEPENDENT Raft consensus groups
        (one group per shard — the segmented multi-domain topology,
        docs/sharding.md). Returns (node, sharded_provider, [bus per
        shard]); `buses[k].kill(buses[k].elect().node_id)` is the
        shard-leader-kill seam, quorum re-election included."""
        from ..node.notary import maybe_coalesced
        from ..node.sharded_notary import ShardedUniquenessProvider

        node = self.create_node(
            legal_name, notary_type="validating" if validating else "simple",
        )
        groups = [
            make_raft_commit_group(raft_members, seed_base=100 * i)
            for i in range(n_shards)
        ]
        provider = ShardedUniquenessProvider([g for g, _ in groups])
        node.notary_service.uniqueness_provider = maybe_coalesced(provider)
        return node, provider, [bus for _, bus in groups]

    @property
    def tracer(self):
        """The tracing spine every in-process node records into: one
        process-global tracer, so a trace started on one mock node and
        continued on another assembles in a single span store (what a
        per-node tracer would need a collector for)."""
        from ..utils.tracing import get_tracer

        return get_tracer()

    def run_network(self, max_messages: int = 100_000) -> int:
        """Pump messages until the network is quiescent."""
        return self.messaging_network.run(max_messages)

    def pump(self) -> bool:
        return self.messaging_network.pump()

    def stop_nodes(self) -> None:
        for node in self.nodes:
            node.stop()
        self.nodes.clear()
        lanes = self.messaging_network.lane_executor
        if lanes is not None:
            lanes.stop(drain=False, timeout=2)

"""MockNetwork: N nodes in one process over a deterministically pumped
in-memory transport (reference `test-utils/.../node/MockNode.kt:50-90` +
`InMemoryMessagingNetwork.kt`).

    net = MockNetwork()
    notary = net.create_notary_node("O=Notary,L=Zurich,C=CH", validating=True)
    alice = net.create_node("O=Alice,L=London,C=GB")
    handle = alice.start_flow(SomeFlow(...), ...)
    net.run_network()          # pump until quiescent
    result = handle.result.result(timeout=0)
"""
from __future__ import annotations

from typing import List, Optional

from ..core.identity import Party
from ..node.network import InMemoryMessagingNetwork
from ..node.node import AbstractNode, NodeConfiguration


class MockNode(AbstractNode):
    pass


class MockNetwork:
    def __init__(self, default_clock=None):
        """default_clock: shared zero-arg clock for all nodes (a TestClock
        makes the whole network deterministic, reference Simulation style);
        None -> real time per node."""
        self.messaging_network = InMemoryMessagingNetwork()
        self.nodes: List[MockNode] = []
        self._entropy = 1000
        self.default_clock = default_clock
        self._clusters: List = []  # (cluster_party, advertised_services)

    def _next_entropy(self) -> int:
        self._entropy += 1
        return self._entropy

    def create_node(
        self,
        legal_name: str,
        notary_type: Optional[str] = None,
        db_path: str = ":memory:",
        entropy: Optional[int] = None,
        clock=None,
    ) -> MockNode:
        config = NodeConfiguration(
            my_legal_name=legal_name,
            db_path=db_path,
            notary_type=notary_type,
            identity_entropy=entropy if entropy is not None else self._next_entropy(),
        )
        node = MockNode(
            config, self.messaging_network.create_endpoint,
            clock=clock or self.default_clock,
        )
        node.start()
        # Everyone learns about everyone (the reference MockNetwork shares a
        # network map): register the new node with existing ones and vice versa.
        for other in self.nodes:
            other.register_peer(node.info, node.config.advertised_services)
            node.register_peer(other.info, other.config.advertised_services)
        for cluster, advertised in self._clusters:
            node.services.network_map_cache.add_node(cluster, advertised)
            node.services.identity_service.register_identity(cluster)
        self.nodes.append(node)
        return node

    def create_notary_node(
        self, legal_name: str = "O=Notary,L=Zurich,C=CH", validating: bool = True,
    ) -> MockNode:
        return self.create_node(
            legal_name, notary_type="validating" if validating else "simple"
        )

    def create_notary_cluster(
        self,
        n_members: int = 3,
        cluster_name: str = "O=Notary Cluster,L=Zurich,C=CH",
        validating: bool = True,
        threshold: int = 1,
    ):
        """A distributed notary presenting ONE composite identity
        (reference: Raft/BFT notary clusters + ServiceIdentityGenerator).

        Members share a uniqueness provider (the replicated-commit-log
        abstraction; swap in RaftUniquenessProvider replicas for consensus
        tests), register under the cluster's service address
        (round-robin + dead-member skip = client failover), and each signs
        with its own leaf key of the composite cluster identity.

        Returns (cluster_party, [member_nodes]).
        """
        from ..node.cluster_identity import generate_service_identity
        from ..node.notary import (
            PersistentUniquenessProvider,
            SimpleNotaryService,
            ValidatingNotaryService,
        )
        from ..node.services import NetworkMapCache

        members = [
            self.create_node(
                f"O=Notary Member {i},L=Zurich,C=CH",
                notary_type="validating" if validating else "simple",
            )
            for i in range(n_members)
        ]
        cluster = generate_service_identity(
            cluster_name, [m.info.owning_key for m in members], threshold
        )
        # own DB: the commit log must survive any single member's death
        from ..node.database import NodeDatabase

        shared_provider = PersistentUniquenessProvider(NodeDatabase(":memory:"))
        svc_cls = ValidatingNotaryService if validating else SimpleNotaryService
        advertised = [NetworkMapCache.NOTARY_SERVICE] + (
            [NetworkMapCache.VALIDATING_NOTARY_SERVICE] if validating else []
        )
        for m in members:
            m.notary_service = svc_cls(
                m.services, m.info, uniqueness_provider=shared_provider
            )
            m.services.notary_service = m.notary_service
            self.messaging_network.register_service_endpoint(
                cluster.name, m.info.name
            )
        # every node (present and future) resolves the cluster identity
        for node in self.nodes:
            node.services.network_map_cache.add_node(cluster, advertised)
            node.services.identity_service.register_identity(cluster)
        self._clusters.append((cluster, advertised))
        return cluster, members

    def run_network(self, max_messages: int = 100_000) -> int:
        """Pump messages until the network is quiescent."""
        return self.messaging_network.run(max_messages)

    def pump(self) -> bool:
        return self.messaging_network.pump()

    def stop_nodes(self) -> None:
        for node in self.nodes:
            node.stop()
        self.nodes.clear()

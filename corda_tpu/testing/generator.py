"""Generator: monadic random-data generator (reference
`client/mock/src/main/kotlin/net/corda/client/mock/Generator.kt` — the
property-style generator used by verifier tests and loadtest).

    g = Generator.int_range(0, 10).bind(
            lambda n: Generator.list_of(Generator.choice("abc"), n))
    value = g.generate(random.Random(42))
"""
from __future__ import annotations

import random
import string
from typing import Callable, Generic, List, Sequence, TypeVar

A = TypeVar("A")
B = TypeVar("B")


class Generator(Generic[A]):
    def __init__(self, fn: Callable[[random.Random], A]):
        self._fn = fn

    def generate(self, rng: random.Random) -> A:
        return self._fn(rng)

    # -- monad ---------------------------------------------------------------

    @staticmethod
    def pure(value: A) -> "Generator[A]":
        return Generator(lambda rng: value)

    def map(self, f: Callable[[A], B]) -> "Generator[B]":
        return Generator(lambda rng: f(self._fn(rng)))

    def bind(self, f: Callable[[A], "Generator[B]"]) -> "Generator[B]":
        return Generator(lambda rng: f(self._fn(rng)).generate(rng))

    @staticmethod
    def sequence(gens: Sequence["Generator"]) -> "Generator[list]":
        return Generator(lambda rng: [g.generate(rng) for g in gens])

    @staticmethod
    def zip2(ga: "Generator[A]", gb: "Generator[B]") -> "Generator[tuple]":
        return Generator(lambda rng: (ga.generate(rng), gb.generate(rng)))

    # -- primitives ----------------------------------------------------------

    @staticmethod
    def int_range(lo: int, hi: int) -> "Generator[int]":
        return Generator(lambda rng: rng.randint(lo, hi))

    @staticmethod
    def choice(options: Sequence[A]) -> "Generator[A]":
        return Generator(lambda rng: rng.choice(list(options)))

    @staticmethod
    def frequency(weighted: Sequence[tuple]) -> "Generator[A]":
        """[(weight, generator)] — pick by weight, then generate."""
        gens = [g for _, g in weighted]
        weights = [w for w, _ in weighted]
        return Generator(
            lambda rng: rng.choices(gens, weights=weights, k=1)[0].generate(rng)
        )

    @staticmethod
    def list_of(gen: "Generator[A]", size: int) -> "Generator[List[A]]":
        return Generator(lambda rng: [gen.generate(rng) for _ in range(size)])

    @staticmethod
    def sized_list_of(gen: "Generator[A]", lo: int, hi: int) -> "Generator[List[A]]":
        return Generator(
            lambda rng: [gen.generate(rng) for _ in range(rng.randint(lo, hi))]
        )

    @staticmethod
    def bytes_of(size: int) -> "Generator[bytes]":
        return Generator(lambda rng: rng.randbytes(size))

    @staticmethod
    def string(size: int = 8) -> "Generator[str]":
        return Generator(
            lambda rng: "".join(
                rng.choice(string.ascii_letters) for _ in range(size)
            )
        )

    @staticmethod
    def pick_n(options: Sequence[A], n: int) -> "Generator[List[A]]":
        return Generator(lambda rng: rng.sample(list(options), n))

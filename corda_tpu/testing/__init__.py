"""corda_tpu.testing: test infrastructure (reference `test-utils/`)."""
from . import faults
from .expect import ExpectRecorder
from .faults import FaultInjector
from .generated_ledger import GeneratedLedger, generate_ledger, ledger_generator
from .generator import Generator
from .ledger_dsl import LedgerDSL, TransactionDSL, ledger
from .mocknetwork import MockNetwork, MockNode

__all__ = [
    "ExpectRecorder",
    "FaultInjector", "faults",
    "GeneratedLedger", "generate_ledger", "ledger_generator",
    "Generator",
    "LedgerDSL", "TransactionDSL", "ledger",
    "MockNetwork", "MockNode",
]

"""corda_tpu.testing: test infrastructure (reference `test-utils/`)."""
from .mocknetwork import MockNetwork, MockNode

__all__ = ["MockNetwork", "MockNode"]

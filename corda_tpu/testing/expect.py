"""Expect DSL: assert on event streams (reference
`test-utils/src/main/kotlin/net/corda/testing/Expect.kt`).

    events = ExpectRecorder(observable)
    ... drive the system ...
    events.expect(lambda e: e.done, "a finished event")
    events.expect_sequence(pred1, pred2)
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional


class ExpectRecorder:
    def __init__(self, observable=None):
        self.events: List = []
        self._lock = threading.Lock()
        if observable is not None:
            observable.subscribe(self.record)

    def record(self, event) -> None:
        with self._lock:
            self.events.append(event)

    def expect(
        self, predicate: Callable, description: str = "event",
        timeout: float = 5.0,
    ):
        """Wait until some recorded event satisfies predicate; return it."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                for e in self.events:
                    if predicate(e):
                        return e
            if time.monotonic() >= deadline:
                with self._lock:
                    seen = list(self.events)
                raise AssertionError(
                    f"expected {description}; saw {len(seen)} events: {seen!r}"
                )
            time.sleep(0.01)

    def expect_sequence(self, *predicates: Callable, timeout: float = 5.0):
        """The predicates must match a subsequence of events, in order."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                events = list(self.events)
            i = 0
            for e in events:
                if i < len(predicates) and predicates[i](e):
                    i += 1
            if i == len(predicates):
                return
            if time.monotonic() >= deadline:
                raise AssertionError(
                    f"matched {i}/{len(predicates)} expected events; "
                    f"saw: {events!r}"
                )
            time.sleep(0.01)

"""Simulation framework: scripted multi-node scenarios on a shared TestClock
(reference `samples/network-visualiser/.../simulation/Simulation.kt:39-50` +
`IRSSimulation.kt`, asserted by `IRSSimulationTest.kt`).

The reference drives a MockNetwork with a TestClock and latency injection,
emitting events a JavaFX visualiser animates. The GUI is out of scope for a
TPU-first framework; the *event stream* is the product here: every message
delivery, flow start/finish, progress step and clock advance surfaces on
`Simulation.events`, consumable by tests, the headless text visualiser
(`samples/visualiser.py`) or any external UI.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..utils.clocks import TestClock
from ..utils.observable import Observable
from .mocknetwork import MockNetwork


@dataclass(frozen=True)
class SimulationEvent:
    kind: str          # message | flow | progress | clock
    detail: Dict = field(default_factory=dict)


# Reference Simulation uses a bank-name list (banksAndDiplomacy); same idea.
BANK_NAMES = [
    "O=Bank of Breakfast Tea,L=London,C=GB",
    "O=Bank of Big Apples,L=New York,C=US",
    "O=Bank of Baguettes,L=Paris,C=FR",
    "O=Bank of Bratwurst,L=Frankfurt,C=DE",
    "O=Bank of Maple Syrup,L=Toronto,C=CA",
]


class Simulation:
    """Base harness: N bank nodes + a validating notary + a rates-oracle
    node on one TestClock, with optional messaging latency."""

    def __init__(
        self,
        n_banks: int = 2,
        latency_seconds: Optional[Callable[[str, str], float]] = None,
        start_time: float = 1_400_000_000.0,
    ):
        self.clock = TestClock(start_time)
        self.events: Observable = Observable()
        self.net = MockNetwork(default_clock=self.clock)
        mn = self.net.messaging_network
        mn.clock = self.clock
        if latency_seconds is not None:
            mn.latency = lambda sender, recipient: latency_seconds(
                sender.name, recipient
            )
        mn.observer = lambda msg: self.events.on_next(
            SimulationEvent(
                "message",
                {
                    "from": msg.sender.name,
                    "to": msg.recipient,
                    "topic": msg.topic,
                    "bytes": len(msg.payload),
                },
            )
        )
        self.notary = self.net.create_notary_node(validating=True)
        self.banks = [
            self.net.create_node(BANK_NAMES[i % len(BANK_NAMES)])
            for i in range(n_banks)
        ]
        from ..samples.irs_demo import RateOracle

        self.oracle_node = self.net.create_node("O=Rates Service,L=Madrid,C=ES")
        self.oracle = RateOracle(
            self.oracle_node.info,
            self.oracle_node.services.key_management_service,
        )
        self.oracle_node.services.rate_oracle = self.oracle
        for node in self.all_nodes:
            node.smm.track(self._flow_observer(node))

    @property
    def all_nodes(self) -> List:
        return [self.notary, *self.banks, self.oracle_node]

    def _flow_observer(self, node):
        def obs(event: str, fsm) -> None:
            self.events.on_next(
                SimulationEvent(
                    "flow",
                    {
                        "node": node.info.name,
                        "event": event,
                        "flow": fsm.flow.flow_name(),
                        "id": fsm.flow_id,
                    },
                )
            )
            tracker = getattr(fsm.flow, "progress_tracker", None)
            if event == "started" and tracker is not None:
                tracker.subscribe(
                    lambda label: self.events.on_next(
                        SimulationEvent(
                            "progress",
                            {"node": node.info.name, "step": label},
                        )
                    )
                )

        return obs

    # -- time + network driving ----------------------------------------------

    def settle(self, max_messages: int = 100_000) -> int:
        """Pump until quiescent at the current clock."""
        return self.net.messaging_network.run(max_messages)

    def advance(self, seconds: float) -> None:
        """Advance the shared clock, firing due schedulers and delivering
        newly-due delayed messages until the network settles."""
        self.clock.advance_by(seconds)
        self.events.on_next(
            SimulationEvent("clock", {"now": self.clock.now()})
        )
        self._drain()

    def settle_messages(self, max_hops: int = 1000) -> None:
        """Drain in-flight messages, hopping the clock over wire latency —
        but never to future *scheduled activities* (use run_until_quiet to
        fire those too)."""
        for _ in range(max_hops):
            self.settle()
            nxt = self.net.messaging_network.next_due()
            if nxt is None:
                return
            self.clock.set_to(max(nxt, self.clock.now()))
        raise RuntimeError("messages did not drain")

    def run_until_quiet(self, max_hops: int = 1000) -> None:
        """Repeatedly settle + hop the clock to the next delayed message or
        scheduled activity until nothing remains."""
        for _ in range(max_hops):
            self._drain()
            nxt = self._next_event_time()
            if nxt is None:
                return
            self.clock.set_to(max(nxt, self.clock.now()))
            self.events.on_next(
                SimulationEvent("clock", {"now": self.clock.now()})
            )
        raise RuntimeError("simulation did not quiesce")

    def _drain(self) -> None:
        while True:
            for node in self.all_nodes:
                node.scheduler.wake()
            if self.settle() == 0 and not any(
                node.scheduler.wake() for node in self.all_nodes
            ):
                return

    def _next_event_time(self) -> Optional[float]:
        candidates = []
        msg = self.net.messaging_network.next_due()
        if msg is not None:
            candidates.append(msg)
        for node in self.all_nodes:
            t = node.scheduler.next_scheduled_time()
            if t is not None:
                candidates.append(t / 1_000_000_000)
        return min(candidates) if candidates else None

    def stop(self) -> None:
        self.net.stop_nodes()


class IRSSimulation(Simulation):
    """Scripted scenario (reference `IRSSimulation.kt`): two banks agree an
    interest-rate swap; on the fixing date the scheduler fires a FixingFlow,
    the oracle attests LIBOR over a FilteredTransaction tear-off, and both
    banks' vaults hold the fixed state."""

    FIXED_RATE = 3.0
    ORACLE_RATE = 3.25
    NOTIONAL = 25_000_000

    def __init__(self, latency_seconds=None):
        super().__init__(n_banks=2, latency_seconds=latency_seconds)
        from ..samples.irs_demo import Fix, FixOf

        self.fix_of = FixOf("LIBOR", "2026-09-01", "3M")
        self.oracle.add_fix(Fix(self.fix_of, self.ORACLE_RATE))

    def run(self) -> Dict:
        """Execute the full scripted scenario; returns the outcome."""
        from dataclasses import replace as _replace

        from ..core.transactions.builder import TransactionBuilder
        from ..samples.irs_demo import InterestRateSwapState, IRSCommand
        from ..core.flows.library import FinalityFlow

        bank_a, bank_b = self.banks
        fixing_at = int((self.clock.now() + 24 * 3600) * 1_000_000_000)
        swap = InterestRateSwapState(
            fixed_leg_payer=bank_a.info,
            floating_leg_payer=bank_b.info,
            notional=self.NOTIONAL,
            fixed_rate=self.FIXED_RATE,
            oracle_name=self.oracle_node.info.name,
            fix_of=self.fix_of,
            next_fixing_at=fixing_at,
        )
        builder = TransactionBuilder(notary=self.notary.info)
        builder.add_output_state(swap)
        builder.add_command(IRSCommand("Agree"), bank_a.info.owning_key)
        stx = bank_a.services.sign_initial_transaction(builder)
        handle = bank_a.start_flow(FinalityFlow(stx), stx)
        self.settle_messages()
        handle.result.result(timeout=30)

        # both banks should now hold the unfixed swap
        for bank in self.banks:
            states = bank.services.vault_service.unconsumed_states(
                InterestRateSwapState.contract_name
            )
            assert len(states) == 1, f"{bank.info.name} missing the swap"

        # jump past the fixing date: scheduler fires, oracle attests
        self.run_until_quiet()

        fixed = bank_a.services.vault_service.unconsumed_states(
            InterestRateSwapState.contract_name
        )[0].state.data
        return {
            "floating_rate": fixed.floating_rate,
            "clock": self.clock.now(),
        }

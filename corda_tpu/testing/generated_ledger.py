"""GeneratedLedger: property-generate always-valid transaction DAGs
(reference `verifier/src/integration-test/.../GeneratedLedger.kt:20-60`,
which feeds the verifier scale tests with arbitrary valid ledgers).

Produces chains of signed Cash issue/move transactions over a party pool;
every generated transaction verifies (contracts + signatures), so any
rejection downstream is a bug in the system under test, not the data.
"""
from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Dict, List, Tuple

from ..core.contracts import Amount, Issued, StateAndRef
from ..core.crypto import crypto
from ..core.crypto.signing import sign_bytes
from ..core.identity import Party
from ..core.transactions import TransactionBuilder
from ..core.transactions.signed import SignedTransaction
from ..finance.cash import CashCommand, CashState
from .generator import Generator


@dataclass
class GeneratedLedger:
    transactions: List[SignedTransaction]
    unconsumed: Dict[object, StateAndRef]  # ref -> StateAndRef
    parties: List[Tuple[Party, object]]  # (party, keypair)
    notary: Party
    notary_keypair: object

    def resolve_state(self, ref):
        for stx in self.transactions:
            if stx.id == ref.txhash:
                return stx.tx.outputs[ref.index]
        raise KeyError(ref)


def generate_ledger(
    rng: random.Random,
    n_parties: int = 4,
    n_transactions: int = 20,
    entropy_base: int = 40_000,
) -> GeneratedLedger:
    parties = []
    for i in range(n_parties):
        kp = crypto.entropy_to_keypair(entropy_base + i)
        parties.append(
            (Party(f"O=Party{i},L=City{i},C=GB", kp.public), kp)
        )
    notary_kp = crypto.entropy_to_keypair(entropy_base + n_parties)
    notary = Party("O=GenNotary,L=Zurich,C=CH", notary_kp.public)
    bank, bank_kp = parties[0]
    token = Issued(bank.ref(1), "USD")

    transactions: List[SignedTransaction] = []
    unconsumed: Dict[object, StateAndRef] = {}

    def sign(builder, keypairs, with_notary=False):
        wtx = builder.to_wire_transaction()
        signers = list(keypairs) + ([notary_kp] if with_notary else [])
        sigs = [
            sign_bytes(kp.private, kp.public, wtx.id.bytes) for kp in signers
        ]
        return SignedTransaction.of(wtx, sigs)

    for _ in range(n_transactions):
        do_issue = not unconsumed or rng.random() < 0.3
        if do_issue:
            recipient, _ = rng.choice(parties)
            amount = Amount(rng.randint(1, 1000) * 100, token)
            b = TransactionBuilder(notary=notary)
            b.add_output_state(CashState(amount=amount, owner=recipient))
            b.add_command(CashCommand.Issue(), bank.owning_key)
            stx = sign(b, [bank_kp])
        else:
            ref = rng.choice(list(unconsumed))
            snr = unconsumed[ref]
            owner_kp = next(
                kp for p, kp in parties if p == snr.state.data.owner
            )
            recipient, _ = rng.choice(parties)
            b = TransactionBuilder(notary=notary)
            b.add_input_state(snr)
            amount = snr.state.data.amount
            if amount.quantity > 100 and rng.random() < 0.5:
                split = (amount.quantity // 200) * 100
                b.add_output_state(CashState(
                    amount=Amount(split, token), owner=recipient))
                b.add_output_state(CashState(
                    amount=Amount(amount.quantity - split, token),
                    owner=snr.state.data.owner))
            else:
                b.add_output_state(CashState(amount=amount, owner=recipient))
            b.add_command(
                CashCommand.Move(), snr.state.data.owner.owning_key
            )
            stx = sign(b, [owner_kp], with_notary=True)
            del unconsumed[ref]
        transactions.append(stx)
        for idx in range(len(stx.tx.outputs)):
            snr = stx.tx.out_ref(idx)
            unconsumed[snr.ref] = snr

    return GeneratedLedger(transactions, unconsumed, parties, notary, notary_kp)


def ledger_generator(
    n_parties: int = 4, n_transactions: int = 20
) -> Generator:
    return Generator(
        lambda rng: generate_ledger(rng, n_parties, n_transactions)
    )

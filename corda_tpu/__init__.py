"""corda_tpu: a TPU-native distributed-ledger framework.

A ground-up rebuild of the capabilities of Corda (reference: Kerwong/corda
0.14-SNAPSHOT) designed for TPU hardware: JAX/XLA/Pallas batch crypto kernels
on the verification hot path, asyncio flows instead of Quasar fibers, a
deterministic canonical serialization instead of Kryo, and jax.sharding
meshes instead of an Artemis broker for intra-pod batch distribution.

Layer map (mirrors reference SURVEY.md section 1):
  corda_tpu.core      -- L0 stable API: contracts, transactions, crypto, flows-as-API
  corda_tpu.ops       -- TPU batch kernels (sha256/sha512/ed25519/secp256)
  corda_tpu.parallel  -- device-mesh sharding of verification batches
  corda_tpu.verifier  -- L3 out-of-process verification worker + batching seam
  corda_tpu.node      -- L2 node runtime (state machine, messaging, persistence)
  corda_tpu.notary    -- uniqueness consensus (simple / validating / raft)
  corda_tpu.rpc       -- RPC server/client with streaming feeds
  corda_tpu.finance   -- L6 domain contracts (Cash, CommercialPaper, Obligation)
  corda_tpu.testing   -- MockNetwork, ledger DSL, driver
  corda_tpu.loadtest  -- load-test harness producing BASELINE metrics
"""

__version__ = "0.1.0"
platform_version = 1

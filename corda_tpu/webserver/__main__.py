"""Standalone webserver process: REST gateway to a remote node
(`python -m corda_tpu.webserver --connect HOST:PORT`).

Reference parity: the webserver runs as its own process talking RPC to the
node (`webserver/src/main/kotlin/net/corda/webserver/WebServer.kt`,
spawned separately by demobench/cordformation).
"""
from __future__ import annotations

import argparse
import importlib
import signal
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="corda_tpu.webserver")
    ap.add_argument("--connect", required=True, help="node broker HOST:PORT")
    ap.add_argument("--user", default="admin")
    ap.add_argument("--password", default="admin")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--cordapps", default="corda_tpu.finance.flows")
    args = ap.parse_args(argv)

    for mod in args.cordapps.split(","):
        if mod:
            importlib.import_module(mod)

    from ..messaging.net import RemoteBroker
    from ..rpc.client import CordaRPCClient
    from .server import WebServer

    host, port_s = args.connect.rsplit(":", 1)
    client = CordaRPCClient(RemoteBroker(host, int(port_s)))
    conn = client.start(args.user, args.password)
    web = WebServer(conn.proxy, host=args.host, port=args.port)
    print(f"webserver ready: http://{args.host}:{web.port}/api/status", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    try:
        while not stop.wait(0.5):
            pass
    finally:
        web.stop()
        conn.close()
        client.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())

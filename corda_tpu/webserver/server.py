"""HTTP API server bridging REST to the node's RPC surface (reference
`webserver/src/main/kotlin/net/corda/webserver/` — Jetty/Jersey replaced by
the stdlib http.server on a background thread).

Endpoints (reference servlet/resource parity):
  GET  /api/status                       -> "started"
  GET  /api/info                         -> node identity
  GET  /api/network                      -> network map snapshot
  GET  /api/notaries                     -> notary identities
  GET  /api/vault[?contract=&status=&notary=&page=&page_size=&sort=&dir=]
                                         -> paged criteria query
  GET  /api/attachments/{hash}           -> attachment bytes
  POST /api/attachments                  -> upload, returns hash
  POST /api/flows/{flow_name}            -> start flow (JSON args), returns id
  GET  /api/flows/{flow_id}              -> flow result (blocks briefly)
  POST /action/issue                     -> CashIssueFlow from the dashboard
                                            form (amount, currency)
  POST /action/pay                       -> CashPaymentFlow (amount,
                                            currency, peer name)
  GET  /api/metrics                      -> metric registry snapshot (JSON)
  GET  /api/transactions[?limit=N]       -> newest validated-tx summaries
  GET  /api/statemachines                -> in-flight flow snapshot
  GET  /                                 -> dashboard (the web GUI tier)
"""
from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..client.jackson import from_json_value, to_json
from ..core.crypto.secure_hash import SecureHash


class WebServer:
    def __init__(self, ops, host: str = "127.0.0.1", port: int = 0):
        """ops: a CordaRPCOps (direct or via RPC client proxy)."""
        self.ops = ops
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _send(self, code: int, body: bytes,
                      content_type: str = "application/json"):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, code: int, value):
                self._send(code, to_json(value).encode())

            def do_GET(self):
                try:
                    outer._get(self)
                except Exception as exc:
                    if getattr(self, "_streaming", False):
                        # headers already sent: a JSON 500 would corrupt
                        # the body; drop the connection instead
                        self.close_connection = True
                    else:
                        self._json(500, {"error": str(exc)})

            def do_POST(self):
                try:
                    outer._post(self)
                except Exception as exc:
                    self._json(500, {"error": str(exc)})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="webserver", daemon=True
        )
        self._thread.start()

    # -- routing -------------------------------------------------------------

    def _get(self, req) -> None:
        path, _, query = req.path.partition("?")
        params = dict(
            p.split("=", 1) for p in query.split("&") if "=" in p
        )
        if path in ("/", "/ui", "/ui/"):
            # the web GUI tier (reference explorer/network-visualiser
            # JavaFX shells): a self-contained dashboard over this
            # gateway's own JSON API, shipped as package data
            import os

            page = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "static", "dashboard.html",
            )
            with open(page, "rb") as fh:
                req._send(200, fh.read(), "text/html; charset=utf-8")
        elif path == "/api/status":
            req._send(200, b"started", "text/plain")
        elif path == "/api/info":
            req._json(200, self.ops.node_info())
        elif path == "/api/network":
            req._json(200, self.ops.network_map_snapshot())
        elif path == "/api/notaries":
            req._json(200, self.ops.notary_identities())
        elif path == "/api/vault":
            from ..node.vault_query import (
                PageSpecification,
                Sort,
                VaultQueryCriteria,
            )

            criteria = VaultQueryCriteria(
                status=params.get("status", "UNCONSUMED").upper(),
                contract_names=(
                    (params["contract"],) if params.get("contract") else ()
                ),
                notary_names=(
                    (params["notary"],) if params.get("notary") else ()
                ),
            )
            paging = PageSpecification(
                page_number=int(params.get("page", 1)),
                page_size=int(params.get("page_size", 200)),
            )
            sort = Sort(
                column=params.get("sort", "recorded_at"),
                descending=params.get("dir", "asc").lower() == "desc",
            )
            page = self.ops.vault_query_by(criteria, paging, sort)
            req._json(
                200,
                {
                    "total": page.total_states_available,
                    "page": page.page_number,
                    "page_size": page.page_size,
                    "states": list(page.states),
                },
            )
        elif path == "/api/metrics":
            req._json(200, self.ops.node_metrics())
        elif path == "/api/transactions":
            # newest-first summaries (explorer parity: the JavaFX
            # explorer's transaction table). Snapshot-only ops call:
            # tapping a DataFeed per poll would leak a server-side
            # subscription on every dashboard refresh over RPC.
            req._json(
                200,
                self.ops.recent_transactions(int(params.get("limit", 25))),
            )
        elif path == "/api/statemachines":
            req._json(200, self.ops.state_machines_snapshot())
        elif m := re.fullmatch(r"/api/attachments/([0-9A-Fa-f]{64})", path):
            att_id = SecureHash(bytes.fromhex(m.group(1)))
            size = self.ops.attachment_size(att_id)
            if size is None:
                req._json(404, {"error": "no such attachment"})
            else:
                # stream in bounded chunks: neither this gateway nor the
                # RPC frames ever hold the whole blob
                req.send_response(200)
                req.send_header("Content-Type", "application/octet-stream")
                req.send_header("Content-Length", str(size))
                req.end_headers()
                req._streaming = True  # headers sent: no JSON error now
                offset = 0
                while offset < size:
                    chunk = self.ops.attachment_chunk(att_id, offset)
                    if not chunk:
                        # can't honour Content-Length: kill the connection
                        # rather than hand the client a short 200 body
                        req.close_connection = True
                        raise IOError(
                            f"attachment {att_id} truncated at {offset}"
                        )
                    req.wfile.write(chunk)
                    offset += len(chunk)
        elif m := re.fullmatch(r"/api/flows/([0-9a-f-]{36})", path):
            try:
                result = self.ops.flow_result(m.group(1), timeout=10)
                req._json(200, {"result": result})
            except Exception as exc:
                req._json(500, {"error": str(exc)})
        elif self._try_plugins(req, "GET", path, params, None):
            pass
        else:
            req._json(404, {"error": f"no route {path}"})

    def _try_plugins(self, req, method: str, path: str, params, body) -> bool:
        """Mounted plugin APIs (/api/<prefix>/...) and static dirs
        (/web/<prefix>/...) — the WebServerPluginRegistry extension
        point. Returns True when a plugin handled the request."""
        from .plugins import registered_plugins

        for plugin in registered_plugins():
            for prefix, handler in plugin.web_apis().items():
                mount = f"/api/{prefix}"
                if path == mount or path.startswith(mount + "/"):
                    subpath = path[len(mount):].lstrip("/")
                    code, value = handler(
                        self.ops, method, subpath, params, body
                    )
                    req._json(code, value)
                    return True
            if method != "GET":
                continue
            for prefix, directory in plugin.static_serve_dirs().items():
                mount = f"/web/{prefix}/"
                if not path.startswith(mount):
                    continue
                import mimetypes
                import os

                root = os.path.realpath(directory)
                target = os.path.realpath(
                    os.path.join(root, path[len(mount):])
                )
                # traversal hardening: the resolved path must stay inside
                if not (target == root or target.startswith(root + os.sep)):
                    req._json(403, {"error": "forbidden"})
                    return True
                if not os.path.isfile(target):
                    req._json(404, {"error": "no such file"})
                    return True
                with open(target, "rb") as fh:
                    data = fh.read()
                ctype = (
                    mimetypes.guess_type(target)[0]
                    or "application/octet-stream"
                )
                req._send(200, data, ctype)
                return True
        return False

    # -- dashboard actions ---------------------------------------------------

    def _form(self, body: bytes) -> dict:
        """application/x-www-form-urlencoded (the dashboard's POST
        forms) or a JSON object body — one parser for both, so curl and
        fetch() drive the same route."""
        text = body.decode(errors="replace")
        if text.lstrip().startswith("{"):
            return json.loads(text)
        from urllib.parse import parse_qsl

        return dict(parse_qsl(text))

    def _action(self, req, flow_name: str, build_args) -> None:
        """Run one dashboard action flow synchronously with TYPED error
        rendering: an admission shed comes back as HTTP 429 with the
        node's own retry_after_ms hint (the overload contract,
        docs/robustness.md) so the GUI can back off instead of
        hammering; everything else is a named-exception 4xx/5xx."""
        from ..node.admission import NodeOverloadedError

        try:
            args = build_args()
            # ONE round trip (start_flow_and_wait): on a sharded node the
            # request queue is competing-consumer across worker
            # processes, and start+wait is served wholly by whichever
            # worker starts the flow
            result = self.ops.start_flow_and_wait(
                flow_name, *args, timeout=60
            )
            tx_id = getattr(result, "id", None)
            req._json(200, {
                "flow": flow_name,
                "tx_id": str(tx_id) if tx_id is not None else None,
            })
        except NodeOverloadedError as exc:
            req._json(429, {
                "error": "overloaded",
                "message": str(exc),
                "retry_after_ms": exc.retry_after_ms,
            })
        except (ValueError, KeyError) as exc:
            req._json(400, {
                "error": type(exc).__name__, "message": str(exc),
            })
        except Exception as exc:
            req._json(500, {
                "error": type(exc).__name__, "message": str(exc),
            })

    def _resolve_peer(self, name: str):
        """A network-map party by exact X.500 name or unique O= match —
        the dashboard sends whatever its peer dropdown held."""
        peers = self.ops.network_map_snapshot()
        exact = [p for p in peers if p.name == name]
        if exact:
            return exact[0]
        loose = [p for p in peers if name in p.name]
        if len(loose) == 1:
            return loose[0]
        raise ValueError(
            f"peer {name!r} is {'ambiguous' if loose else 'unknown'} "
            f"in the network map"
        )

    def _post(self, req) -> None:
        length = int(req.headers.get("Content-Length", 0))
        body = req.rfile.read(length) if length else b""
        path = req.path
        if path == "/action/issue":
            from ..core.contracts import Amount

            def build_issue():
                form = self._form(body)
                amount = Amount(
                    int(form["amount"]), form.get("currency", "USD")
                )
                me = self.ops.node_info()
                notary = self.ops.notary_identities()[0]
                return amount, b"\x01", me, notary

            self._action(req, "CashIssueFlow", build_issue)
        elif path == "/action/pay":
            from ..core.contracts import Amount
            from ..core.contracts.amount import Issued

            def build_pay():
                form = self._form(body)
                me = self.ops.node_info()
                token = Issued(me.ref(1), form.get("currency", "USD"))
                amount = Amount(int(form["amount"]), token)
                peer = self._resolve_peer(form["peer"])
                notary = self.ops.notary_identities()[0]
                return amount, peer, notary

            self._action(req, "CashPaymentFlow", build_pay)
        elif path == "/api/attachments":
            # class constant, NOT getattr on self.ops: an RPC proxy
            # fabricates a callable for any attribute name
            from ..rpc.ops import CordaRPCOps

            chunk = CordaRPCOps.ATTACHMENT_CHUNK
            if len(body) > chunk:
                # large upload rides the chunk protocol so no single RPC
                # frame carries the whole blob
                upload_id = self.ops.upload_attachment_begin()
                for off in range(0, len(body), chunk):
                    self.ops.upload_attachment_chunk(
                        upload_id, body[off : off + chunk]
                    )
                att_id = self.ops.upload_attachment_end(upload_id)
            else:
                att_id = self.ops.upload_attachment(body)
            req._json(200, {"id": att_id})
        elif m := re.fullmatch(r"/api/flows/([A-Za-z0-9_.]+)", path):
            args = from_json_value(json.loads(body.decode() or "[]"))
            if isinstance(args, dict):
                flow_id = self.ops.start_flow_dynamic(m.group(1), **args)
            else:
                flow_id = self.ops.start_flow_dynamic(m.group(1), *args)
            req._json(200, {"flow_id": flow_id})
        elif self._try_plugins(req, "POST", path, {}, body):
            pass
        else:
            req._json(404, {"error": f"no route {path}"})

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)

"""Webserver plugin extension point (reference
`webserver/src/main/kotlin/net/corda/webserver/services/WebServerPluginRegistry.kt`:
CorDapps contribute `webApis` (JAX-RS resources) and `staticServeDirs`;
the webserver mounts them next to the built-in API).

TPU-build shape: a plugin exposes
  * `web_apis()` -> {prefix: handler} where handler(ops, method, subpath,
    params, body) returns (status_code, jsonable) and is mounted at
    `/api/<prefix>/...`;
  * `static_serve_dirs()` -> {prefix: directory} served read-only at
    `/web/<prefix>/...` (path-traversal hardened).

CorDapp modules call `register_web_plugin(...)` at import time — the same
moment their flows register — so a node's `cordapps` config lights up
both RPC flows and web endpoints (reference: plugins discovered via
ServiceLoader from the CorDapp jars).
"""
from __future__ import annotations

from typing import Callable, Dict, List

Handler = Callable[..., tuple]


class WebServerPlugin:
    """Subclass (or duck-type) and register; both hooks are optional."""

    def web_apis(self) -> Dict[str, Handler]:
        return {}

    def static_serve_dirs(self) -> Dict[str, str]:
        return {}


_REGISTRY: List[WebServerPlugin] = []


def register_web_plugin(plugin: WebServerPlugin) -> None:
    if plugin not in _REGISTRY:
        _REGISTRY.append(plugin)


def registered_plugins() -> List[WebServerPlugin]:
    return list(_REGISTRY)


def clear_web_plugins() -> None:
    """Test hook."""
    _REGISTRY.clear()

"""corda_tpu.webserver: HTTP/REST API server over RPC (reference
`webserver/` — the standalone Jetty/Jersey server that talks RPC to a
node)."""
from .server import WebServer

__all__ = ["WebServer"]

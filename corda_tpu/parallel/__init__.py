"""corda_tpu.parallel: device-mesh distribution of verification batches.

The reference scales verification by adding competing-consumer worker
processes on an Artemis queue (SURVEY.md section 2.10 item 2).  On TPU the
same axis is widened twice: vmap across a batch on one chip
(corda_tpu.ops), and shard_map across a jax.sharding.Mesh so a 10k-100k
signature burst rides ICI collectives across every chip in the slice.
DCN-side elasticity (worker processes) stays on the broker; ICI-side
data parallelism lives here.
"""
from .mesh import (
    DistributedVerifier,
    data_mesh,
    shard_layout,
    shard_verify,
    shard_verify_ed25519,
    worker_slot_mesh,
)

__all__ = [
    "DistributedVerifier",
    "data_mesh",
    "shard_layout",
    "shard_verify",
    "shard_verify_ed25519",
    "worker_slot_mesh",
]

"""Data-parallel sharding of signature-verification batches over a Mesh.

Design: the batch is the only sharded axis ("data").  Each device verifies
its shard with the single-chip kernel for its scheme — a per-scheme kernel
table covers ed25519 (ops.ed25519_batch.verify_kernel) and both ECDSA
curves (ops.ecdsa_batch._verify_kernel), so scale-out applies to all
device-kernel work uniformly, matching the reference's competing-consumer
model (`VerifierTests.kt:54-71` scales all verify requests, not one
scheme).  A psum collective gives every shard the global valid-count (the
notary wants it before committing a uniqueness batch).  All shapes are
static: the host pads the batch to a multiple of the mesh size, using the
same power-of-two bucketing as the single-chip path so XLA compiles one
executable per (scheme, bucket, mesh) triple.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np


def data_mesh(n_devices: Optional[int] = None, axis: str = "data"):
    """A 1-D mesh over the first n (default: all) local devices."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), axis_names=(axis,))


def worker_slot_mesh(n_devices: int, slot: int, axis: str = "data"):
    """A 1-D mesh over worker slot `slot`'s disjoint device slice.

    M co-located workers each pin devices [slot*n, (slot+1)*n) of the
    local device set, so their kernels never contend for a chip
    (CORDA_TPU_MESH_WORKER_SLOT in docs/perf-pipeline.md).
    """
    import jax
    from jax.sharding import Mesh

    if n_devices < 1 or slot < 0:
        raise ValueError(f"bad worker slot ({slot}) x devices ({n_devices})")
    devices = jax.devices()
    lo, hi = slot * n_devices, (slot + 1) * n_devices
    if len(devices) < hi:
        raise ValueError(
            f"worker slot {slot} needs devices [{lo}, {hi}), have "
            f"{len(devices)}"
        )
    return Mesh(np.array(devices[lo:hi]), axis_names=(axis,))


def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def _bucket_per_device(per_device: int) -> int:
    """Next power of two (min 8) so the per-shard shape set stays small."""
    return max(8, 1 << math.ceil(math.log2(max(per_device, 1))))


# --- per-scheme kernel table -------------------------------------------------
#
# Each entry: prepare(pubs, sigs, msgs, pad_to) -> (ordered arg tuple, n)
# and kernel(*args) -> mask.  Argument sharding specs are derived from
# array rank (batch is always axis 0; 2-D args carry a trailing limb/word
# dim).  Adding a scheme = adding one entry; the sharded step, caching and
# host padding are scheme-agnostic.

_ED25519_ARGS = ("y_a", "sign_a", "y_r", "sign_r", "s_words", "h_words", "s_ok")
_ECDSA_ARGS = ("qx", "qy", "u1_words", "u2_words", "r_cmp", "ok")


def _mesh_on_tpu(mesh) -> bool:
    """Kernel selection keys off the MESH's devices, not the process
    default backend: a CPU fallback mesh on a TPU-latched host must not
    trace Mosaic kernels, and a TPU mesh in a CPU-defaulted process must
    still take the Pallas path (round-3 review finding)."""
    return mesh.devices.flat[0].platform == "tpu"


def _ed25519_entry(on_tpu: bool):
    import jax.numpy as jnp

    from ..ops import ed25519_batch

    def prepare(pubs, sigs, msgs, pad_to):
        kwargs, n = ed25519_batch.prepare_batch(pubs, sigs, msgs, pad_to=pad_to)
        return tuple(kwargs[k] for k in _ED25519_ARGS), n

    def kernel(*args):
        kw = dict(zip(_ED25519_ARGS, args))
        # per-shard kernel selection happens at trace time on static
        # shapes: on TPU, BLK-divisible shards take the Pallas ladder —
        # the same kernel the single-device production path uses — so
        # N-chip throughput is N x the Pallas rate, not N x the slower
        # portable-XLA rate (round-3 review finding)
        from ..ops import ed25519_pallas as epl

        if on_tpu and kw["y_a"].shape[0] % epl.BLK == 0:
            mask = epl.verify_kernel_pallas(
                kw["y_a"].T,
                kw["sign_a"][None, :],
                kw["y_r"].T,
                kw["sign_r"][None, :],
                kw["s_words"].T,
                kw["h_words"].T,
                kw["s_ok"][None, :].astype(jnp.uint32),
            )
            return mask[0].astype(bool)
        return ed25519_batch.verify_kernel(**kw)

    from ..ops import ed25519_pallas as epl

    ranks = (2, 1, 2, 1, 2, 2, 1)  # y_a, sign_a, y_r, sign_r, s, h, s_ok
    return prepare, kernel, ranks, epl.BLK


def _ecdsa_entry(curve_name: str, on_tpu: bool):
    import jax.numpy as jnp

    from ..ops import ecdsa_batch

    def prepare(pubs, sigs, msgs, pad_to):
        kwargs, n = ecdsa_batch.prepare_batch(
            curve_name, pubs, sigs, msgs, pad_to=pad_to
        )
        return tuple(kwargs[k] for k in _ECDSA_ARGS), n

    def kernel(*args):
        kw = dict(zip(_ECDSA_ARGS, args))
        from ..ops import ecdsa_pallas as ecpl

        if on_tpu and kw["qx"].shape[0] % ecpl.BLK == 0:
            mask = ecpl.verify_kernel_pallas(
                curve_name,
                kw["qx"].T,
                kw["qy"].T,
                kw["u1_words"].T,
                kw["u2_words"].T,
                kw["r_cmp"].T,
                kw["ok"][None, :].astype(jnp.uint32),
            )
            return mask[0].astype(bool)
        return ecdsa_batch._verify_kernel(curve_name, **kw)

    from ..ops import ecdsa_pallas as ecpl

    ranks = (2, 2, 2, 2, 2, 1)  # qx, qy, u1, u2, r_cmp, ok
    return prepare, kernel, ranks, ecpl.BLK


_SCHEME_KERNELS = {
    "ed25519": _ed25519_entry,
    "secp256k1": lambda on_tpu: _ecdsa_entry("secp256k1", on_tpu),
    "secp256r1": lambda on_tpu: _ecdsa_entry("secp256r1", on_tpu),
}

# jit cache: one compiled sharded step per (mesh, scheme) (jax.jit's own
# cache is keyed on function identity, so the closure must be built once —
# rebuilding it per call would force a full retrace + XLA compile per batch).
_SHARDED_STEP_CACHE: dict = {}


def _sharded_step(mesh, scheme: str):
    import jax
    import jax.numpy as jnp

    try:
        from jax import shard_map  # jax >= 0.5
    except ImportError:  # the pre-0.5 experimental home
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    # Content-based key: id(mesh) could be reused by a new mesh after the
    # old one is garbage-collected, resurrecting a closure over dead
    # devices.  Device objects are per-backend singletons, so two meshes
    # with the same (platform, device-id) layout share one executable.
    key = (
        scheme,
        tuple((d.platform, d.id) for d in mesh.devices.flat),
        mesh.devices.shape,
        mesh.axis_names,
    )
    cached = _SHARDED_STEP_CACHE.get(key)
    if cached is not None:
        return cached
    axis = mesh.axis_names[0]
    prepare, kernel, ranks, blk = _SCHEME_KERNELS[scheme](_mesh_on_tpu(mesh))
    specs = tuple(P(axis, None) if r == 2 else P(axis) for r in ranks)

    def step(*args):
        mask = kernel(*args)
        total = jax.lax.psum(jnp.sum(mask.astype(jnp.int32)), axis)
        return mask, total

    # check_vma off: the kernels' fori_loop carries start from unvarying
    # constant points (identity / generator), which the varying-manual-axes
    # checker rejects even though the per-shard computation is correct.
    # (jax < 0.5 spells the same knob check_rep.)
    try:
        sharded = shard_map(
            step, mesh=mesh, in_specs=specs, out_specs=(P(axis), P()),
            check_vma=False,
        )
    except TypeError:
        sharded = shard_map(
            step, mesh=mesh, in_specs=specs, out_specs=(P(axis), P()),
            check_rep=False,
        )
    fn = jax.jit(sharded)
    cached = (prepare, fn, specs, blk)
    _SHARDED_STEP_CACHE[key] = cached
    # each new (scheme, mesh layout) closure compiles its own sharded
    # executable downstream — a compile event the flight ledger links
    # mesh-routed dispatch records against (utils/profiling)
    from ..utils import profiling

    profiling.record_compile(
        f"mesh.{scheme}.step", bucket=str(mesh.devices.size)
    )
    return cached


def shard_layout(mesh, scheme: str, n: int):
    """(per_device, padded, occupancy) for an n-row batch on `mesh`.

    The padding math in one place: each shard gets the same power-of-two
    bucket (`_bucket_per_device`), the batch pads to `per_device * n_dev`,
    and `occupancy[k]` is the count of REAL rows shard k carries (the
    ragged tail leaves trailing shards partially — or fully — padding).
    """
    n_dev = mesh.devices.size
    _, _, _, blk = _sharded_step(mesh, scheme)
    per_device = _bucket_per_device(_round_up(max(n, 1), n_dev) // n_dev)
    if _mesh_on_tpu(mesh):
        per_device = max(per_device, blk)
    padded = per_device * n_dev
    occupancy = [
        max(0, min(per_device, n - k * per_device)) for k in range(n_dev)
    ]
    return per_device, padded, occupancy


def shard_verify(
    mesh,
    scheme: str,
    public_keys: Sequence[bytes],
    signatures: Sequence[bytes],
    messages: Sequence[bytes],
    return_total: bool = False,
):
    """Verify a batch of one scheme sharded across `mesh`; returns bool[n].

    `scheme` is a kernel-table key: "ed25519", "secp256k1" or "secp256r1".
    The verdict mask comes back per-shard (P("data")); the psum'd global
    count stays on device as a cheap all-reduce the caller can block on —
    `return_total=True` reads it back as `(mask, total)` so the notary
    gets the mesh-wide valid count without a host-side re-reduction.
    Padding rows verify as invalid (prepare_batch's `*_ok` flags are zero
    off the real batch), so the psum total counts REAL valid rows only
    and a padding row can never flip a verdict.  The compiled executable
    is cached per (scheme, mesh, padded shape) — repeated bursts pay zero
    compilation.
    """
    import jax
    from jax.sharding import NamedSharding

    n = len(public_keys)
    prepare, fn, specs, _blk = _sharded_step(mesh, scheme)
    _, padded, _ = shard_layout(mesh, scheme, n)

    args, _ = prepare(public_keys, signatures, messages, padded)
    device_args = tuple(
        jax.device_put(a, NamedSharding(mesh, s)) for a, s in zip(args, specs)
    )
    mask, total = fn(*device_args)
    mask = np.asarray(mask)[:n]
    if return_total:
        return mask, int(total)
    return mask


def shard_verify_ed25519(
    mesh,
    public_keys: Sequence[bytes],
    signatures: Sequence[bytes],
    messages: Sequence[bytes],
) -> np.ndarray:
    """Back-compat wrapper: ed25519 via the scheme-generic `shard_verify`."""
    return shard_verify(mesh, "ed25519", public_keys, signatures, messages)


# -- scaling-curve microbench -------------------------------------------------
#
# `python -m corda_tpu.parallel.mesh --bench --devices N` prints one JSON
# point of the mesh_sigs_s scaling curve.  bench.py's mesh stage and
# `tools/tune_kernel.py --mesh-ns` both spawn this in a SUBPROCESS per N:
# the forced host device count (--xla_force_host_platform_device_count)
# must be set before the CPU backend first initializes, so the parent
# sets XLA_FLAGS in the child's env rather than re-initializing its own.


def _bench_items(rows: int):
    from ..core.crypto import ed25519_math

    rng = np.random.default_rng(11)
    pubs, sigs, msgs = [], [], []
    for i in range(rows):
        seed = rng.bytes(32)
        msg = rng.bytes(48)
        sig = ed25519_math.sign(seed, msg)
        if i % 7 == 3:  # a few invalid rows keep the verdict path honest
            sig = bytes([sig[0] ^ 0xFF]) + sig[1:]
        pubs.append(ed25519_math.public_from_seed(seed))
        sigs.append(sig)
        msgs.append(msg)
    return pubs, sigs, msgs


def microbench(n_devices: int, rows: int = 256, repeats: int = 3) -> dict:
    """One point of the mesh scaling curve: ed25519 verify throughput at
    `n_devices` (0 = the all-off comparator, i.e. today's single-device
    ops path — exactly what CORDA_TPU_MESH_DEVICES=0 dispatches).  The
    first run pays the XLA compile (excluded); `wall_s` is the best of
    `repeats` steady-state runs."""
    import time

    import jax

    pubs, sigs, msgs = _bench_items(rows)
    if n_devices <= 0:
        from ..ops import ed25519_batch

        def run():
            return np.asarray(ed25519_batch.verify_batch(pubs, sigs, msgs))
    else:
        mesh = data_mesh(n_devices)

        def run():
            return shard_verify(mesh, "ed25519", pubs, sigs, msgs)

    mask = run()  # warmup: compile + first dispatch
    valid = int(np.asarray(mask).sum())
    best = None
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        run()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return {
        "n_devices": int(max(0, n_devices)),
        "rows": int(rows),
        "valid": valid,
        "backend": jax.default_backend(),
        "wall_s": round(best, 6),
        "sigs_s": round(rows / best, 3) if best > 0 else 0.0,
    }


def _bench_main(argv=None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(prog="corda_tpu.parallel.mesh")
    ap.add_argument("--bench", action="store_true", required=True)
    ap.add_argument("--devices", type=int, default=1)
    ap.add_argument("--rows", type=int, default=256)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args(argv)
    print(json.dumps(microbench(args.devices, args.rows, args.repeats),
                     sort_keys=True), flush=True)
    return 0


class DistributedVerifier:
    """Mesh-wide batch signature verifier with the host-path API.

    Drop-in for the single-chip device path in `core.crypto.batch`: give it
    (key, sig, content) triples, get a positional verdict list.  Construct
    once (mesh creation and jit cache are reused across calls).
    """

    def __init__(self, mesh=None, n_devices: Optional[int] = None):
        self.mesh = mesh if mesh is not None else data_mesh(n_devices)

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    def verify(
        self,
        scheme: str,
        public_keys: Sequence[bytes],
        signatures: Sequence[bytes],
        messages: Sequence[bytes],
    ) -> List[bool]:
        mask = shard_verify(
            self.mesh, scheme, public_keys, signatures, messages
        )
        return [bool(b) for b in mask]

    def verify_ed25519(
        self,
        public_keys: Sequence[bytes],
        signatures: Sequence[bytes],
        messages: Sequence[bytes],
    ) -> List[bool]:
        return self.verify("ed25519", public_keys, signatures, messages)


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    import sys

    sys.exit(_bench_main())

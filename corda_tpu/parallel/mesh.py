"""Data-parallel sharding of signature-verification batches over a Mesh.

Design: the batch is the only sharded axis ("data").  Each device verifies
its shard with the single-chip kernel (ops.ed25519_batch.verify_kernel);
a psum collective gives every shard the global valid-count (the notary
wants it before committing a uniqueness batch).  All shapes are static:
the host pads the batch to a multiple of the mesh size, using the same
power-of-two bucketing as the single-chip path so XLA compiles one
executable per (bucket, mesh) pair.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np


def data_mesh(n_devices: Optional[int] = None, axis: str = "data"):
    """A 1-D mesh over the first n (default: all) local devices."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), axis_names=(axis,))


def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def _bucket_per_device(per_device: int) -> int:
    """Next power of two (min 8) so the per-shard shape set stays small."""
    return max(8, 1 << math.ceil(math.log2(max(per_device, 1))))


# jit cache: one compiled sharded step per mesh (jax.jit's own cache is
# keyed on function identity, so the closure must be built once per mesh —
# rebuilding it per call would force a full retrace + XLA compile per batch).
_SHARDED_STEP_CACHE: dict = {}

# Field layout of a prepared batch (matches ops.ed25519_batch.prepare_batch).
_ARG_NAMES = ("y_a", "sign_a", "y_r", "sign_r", "s_words", "h_words", "s_ok")


def _sharded_step(mesh):
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from ..ops import ed25519_batch

    # Content-based key: id(mesh) could be reused by a new mesh after the
    # old one is garbage-collected, resurrecting a closure over dead
    # devices.  Device objects are per-backend singletons, so two meshes
    # with the same (platform, device-id) layout share one executable.
    key = (
        tuple((d.platform, d.id) for d in mesh.devices.flat),
        mesh.devices.shape,
        mesh.axis_names,
    )
    cached = _SHARDED_STEP_CACHE.get(key)
    if cached is not None:
        return cached
    axis = mesh.axis_names[0]
    # y_a, y_r, s_words, h_words are 2-D [batch, limbs]; the rest 1-D.
    specs = (
        P(axis, None), P(axis), P(axis, None), P(axis),
        P(axis, None), P(axis, None), P(axis),
    )

    def step(y_a, sign_a, y_r, sign_r, s_words, h_words, s_ok):
        mask = ed25519_batch.verify_kernel(
            y_a=y_a, sign_a=sign_a, y_r=y_r, sign_r=sign_r,
            s_words=s_words, h_words=h_words, s_ok=s_ok,
        )
        total = jax.lax.psum(jnp.sum(mask.astype(jnp.int32)), axis)
        return mask, total

    # check_vma off: the kernel's fori_loop carry starts from unvarying
    # constant identity points, which the varying-manual-axes checker
    # rejects even though the per-shard computation is correct.
    fn = jax.jit(
        shard_map(
            step, mesh=mesh, in_specs=specs, out_specs=(P(axis), P()),
            check_vma=False,
        )
    )
    _SHARDED_STEP_CACHE[key] = (fn, specs)
    return fn, specs


def shard_verify_ed25519(
    mesh,
    public_keys: Sequence[bytes],
    signatures: Sequence[bytes],
    messages: Sequence[bytes],
) -> np.ndarray:
    """Verify a batch sharded across `mesh`; returns bool[n] host array.

    The verdict mask comes back per-shard (P("data")); the psum'd global
    count stays on device as a cheap all-reduce the caller can block on.
    The compiled executable is cached per (mesh, padded shape) — repeated
    bursts pay zero compilation.
    """
    import jax
    from jax.sharding import NamedSharding

    from ..ops import ed25519_batch

    n = len(public_keys)
    n_dev = mesh.devices.size
    per_device = _bucket_per_device(_round_up(max(n, 1), n_dev) // n_dev)
    padded = per_device * n_dev

    kwargs, _ = ed25519_batch.prepare_batch(
        public_keys, signatures, messages, pad_to=padded
    )
    args = tuple(kwargs[k] for k in _ARG_NAMES)
    fn, specs = _sharded_step(mesh)
    device_args = tuple(
        jax.device_put(a, NamedSharding(mesh, s)) for a, s in zip(args, specs)
    )
    mask, _total = fn(*device_args)
    return np.asarray(mask)[:n]


class DistributedVerifier:
    """Mesh-wide batch signature verifier with the host-path API.

    Drop-in for the single-chip device path in `core.crypto.batch`: give it
    (key, sig, content) triples, get a positional verdict list.  Construct
    once (mesh creation and jit cache are reused across calls).
    """

    def __init__(self, mesh=None, n_devices: Optional[int] = None):
        self.mesh = mesh if mesh is not None else data_mesh(n_devices)

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    def verify_ed25519(
        self,
        public_keys: Sequence[bytes],
        signatures: Sequence[bytes],
        messages: Sequence[bytes],
    ) -> List[bool]:
        mask = shard_verify_ed25519(
            self.mesh, public_keys, signatures, messages
        )
        return [bool(b) for b in mask]

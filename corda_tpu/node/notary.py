"""Notary services: uniqueness consensus over consumed input states.

Reference (SURVEY.md section 2.6):
  * `NotaryService` base + helpers — `core/.../node/services/NotaryService.kt`
  * `NotaryFlow.Client` / `.Service`  — `core/.../flows/NotaryFlow.kt`
  * `SimpleNotaryService` — `node/.../transactions/SimpleNotaryService.kt`
  * `ValidatingNotaryService/Flow` — the path that drives batch verification
  * `PersistentUniquenessProvider` — RDBMS commit log with conflict
    detection (`PersistentUniquenessProvider.kt:62-92`)

Batch-first TPU design note: `UniquenessProvider.commit` takes the whole
input set in one call (all-or-nothing), and the validating path funnels
signature checks through the node's TransactionVerifierService / batcher
rather than per-signature host crypto.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..core.contracts.structures import StateRef, TimeWindow
from ..core.flows.api import FlowException, FlowLogic, initiated_by, initiating_flow
from ..core.identity import Party
from ..core.serialization.codec import deserialize, register_adapter, serialize
from ..core.transactions.filtered import FilteredTransaction
from ..core.transactions.signed import SignedTransaction
from .database import KVStore, NodeDatabase


# ---------------------------------------------------------------------------
# Errors (reference NotaryError sealed class, NotaryFlow.kt:140-152)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Conflict:
    tx_id: object
    consumed: Dict[str, object]  # state-ref repr -> consuming tx id


class NotaryException(FlowException):
    def __init__(self, error):
        super().__init__(f"notary error: {error}")
        self.error = error


class UniquenessException(Exception):
    def __init__(self, conflict: Conflict):
        super().__init__(f"input state conflict: {conflict}")
        self.conflict = conflict


# ---------------------------------------------------------------------------
# Uniqueness providers
# ---------------------------------------------------------------------------

class UniquenessProvider:
    def commit(self, states: List[StateRef], tx_id, requesting_party: Party):
        """Consume `states` for `tx_id` or raise UniquenessException.

        May return a list of notary signatures over the tx id when the
        commit protocol itself produces them (the BFT provider returns
        the f+1 replica signatures); None otherwise."""
        raise NotImplementedError


class PersistentUniquenessProvider(UniquenessProvider):
    """Single-node commit log in the node DB. All-or-nothing batch commit
    with conflict reporting (reference PersistentUniquenessProvider)."""

    def __init__(self, db: NodeDatabase):
        self._map = KVStore(db, "uniqueness")
        self._db = db

    @staticmethod
    def _key(ref: StateRef) -> bytes:
        return ref.txhash.bytes + ref.index.to_bytes(4, "big")

    def commit(self, states: List[StateRef], tx_id, requesting_party: Party) -> None:
        with self._db.lock:
            conflicts: Dict[str, object] = {}
            for ref in states:
                existing = self._map.get(self._key(ref))
                if existing is not None:
                    consuming = deserialize(existing)
                    if consuming["tx_id"] != tx_id:
                        conflicts[repr(ref)] = consuming["tx_id"]
            if conflicts:
                raise UniquenessException(Conflict(tx_id, conflicts))
            blob = serialize({"tx_id": tx_id, "by": requesting_party.name})
            for ref in states:
                self._map.put(self._key(ref), blob)


class RaftUniquenessProvider(UniquenessProvider):
    """Replicated commit log over the framework's own Raft (reference
    `RaftUniquenessProvider.kt:71-156` which delegates to Copycat).

    The state machine is a persisted map StateRef-key -> consuming tx; a
    `putall` command checks-and-inserts the whole input set atomically and
    deterministically on every replica.  Only the leader accepts commits;
    notary cluster clients fail over between members
    (send_and_receive_with_retry, reference FlowLogic.kt:98-110).
    """

    def __init__(self, raft_node, db: NodeDatabase,
                 forwarding_retry: bool = False):
        self.raft = raft_node
        # real-time clusters (OS processes) forward follower commits to
        # the leader and retry across elections; virtual-time test buses
        # keep the fail-fast behavior and drive retries themselves
        self.forwarding_retry = forwarding_retry
        self._map = KVStore(db, "raft_uniqueness")
        # Log compaction (reference DistributedImmutableMap's snapshottable
        # state machine): the Raft log's applied prefix folds into a dump
        # of the uniqueness map.
        if getattr(raft_node, "snapshot_fn", None) is None:
            raft_node.snapshot_fn = self.snapshot
        if getattr(raft_node, "restore_fn", None) is None:
            raft_node.restore_fn = self.restore

    def snapshot(self) -> bytes:
        return serialize([[bytes(k), bytes(v)] for k, v in self._map.items()])

    def restore(self, data: bytes) -> None:
        for k, _ in list(self._map.items()):
            self._map.delete(k)
        for k, v in deserialize(data):
            self._map.put(bytes(k), bytes(v))

    def is_consumed(self, ref: StateRef) -> bool:
        """Whether this REPLICA's applied log knows `ref` as spent —
        a replication observability hook (cluster tests, dryrun)."""
        return self._map.get(PersistentUniquenessProvider._key(ref)) is not None

    def apply(self, command: dict):
        """State-machine apply (runs on every replica, in log order)."""
        if command.get("kind") != "putall":
            return None
        conflicts = {}
        for key_hex, consuming_blob in command["entries"].items():
            existing = self._map.get(bytes.fromhex(key_hex))
            if existing is not None:
                mine = deserialize(consuming_blob)["tx_id"]
                theirs = deserialize(existing)["tx_id"]
                if mine != theirs:
                    conflicts[key_hex] = theirs
        if not conflicts:
            for key_hex, consuming_blob in command["entries"].items():
                self._map.put(bytes.fromhex(key_hex), consuming_blob)
        return {"conflicts": {k: v for k, v in conflicts.items()}}

    def commit(self, states: List[StateRef], tx_id, requesting_party: Party) -> None:
        import time as _time
        from concurrent.futures import TimeoutError as _FuturesTimeout

        from .raft import NotLeaderError

        blob = serialize({"tx_id": tx_id, "by": requesting_party.name})
        entries = {
            PersistentUniquenessProvider._key(ref).hex(): blob for ref in states
        }
        command = {"kind": "putall", "entries": entries}
        if not self.forwarding_retry:
            result = self.raft.submit(command).result(timeout=30)
        else:
            # Any member accepts the commit: leaders apply locally,
            # followers forward (raft.submit_anywhere); NotLeaderError
            # during elections retries until the cluster converges
            # (reference CopycatClient). putall is idempotent for the
            # same tx_id, so a retried commit cannot double-spend itself.
            deadline = _time.monotonic() + 30
            while True:
                fut = self.raft.submit_anywhere(command)
                try:
                    result = fut.result(timeout=5)
                    break
                except NotLeaderError:
                    if _time.monotonic() > deadline:
                        raise
                    _time.sleep(0.2)
                except (TimeoutError, _FuturesTimeout):
                    # distinct classes on 3.10; aliases from 3.11 on
                    if _time.monotonic() > deadline:
                        raise
        if result["conflicts"]:
            by_key = {
                PersistentUniquenessProvider._key(ref).hex(): ref
                for ref in states
            }
            raise UniquenessException(Conflict(
                tx_id,
                {
                    repr(by_key[k]): v
                    for k, v in result["conflicts"].items()
                    if k in by_key
                },
            ))


class BFTUniquenessProvider(UniquenessProvider):
    """Byzantine-fault-tolerant commit log over the framework's own PBFT
    (reference `BFTSMaRt.kt` Client/Replica wrapping the BFT-SMaRt library;
    see corda_tpu.node.bft for the replica protocol).  The provider is the
    client side: it submits the putall and accepts the verdict once f+1
    replicas agree."""

    def __init__(self, bft_client):
        self.client = bft_client

    def commit(self, states: List[StateRef], tx_id, requesting_party: Party) -> None:
        entries = {
            PersistentUniquenessProvider._key(ref).hex():
                serialize({"tx_id": tx_id, "by": requesting_party.name}).hex()
            for ref in states
        }
        fut = self.client.submit({
            "kind": "putall", "entries": entries,
            "tx_id": tx_id.bytes.hex(),
        })
        result = fut.result(timeout=30)
        if result["conflicts"]:
            by_key = {
                PersistentUniquenessProvider._key(ref).hex(): ref
                for ref in states
            }
            raise UniquenessException(Conflict(
                tx_id,
                {
                    repr(by_key[k]): deserialize(bytes.fromhex(v))["tx_id"]
                    for k, v in result["conflicts"].items()
                    if k in by_key
                },
            ))
        # the f+1 replica signatures over the tx id, returned per-request
        # so concurrent notarisations of the same tx cannot cross wires
        return list(result.get("tx_sigs") or []) or None

    @staticmethod
    def make_replica_apply(db: NodeDatabase, sign_tx_fn=None):
        """The deterministic state-machine applied on every BFT replica.

        sign_tx_fn(tx_id_bytes) -> DigitalSignatureWithKey: when given, a
        conflict-free commit reply carries this replica's signature over
        the transaction id (reference BFTNonValidatingNotaryService:
        per-replica signatures returned to the client, which aggregates
        f+1 of them into the notary response)."""
        umap = KVStore(db, "bft_uniqueness")

        def apply(command: dict):
            if command.get("kind") != "putall":
                return None
            conflicts = {}
            for key_hex, blob_hex in command["entries"].items():
                existing = umap.get(bytes.fromhex(key_hex))
                if existing is not None and existing != bytes.fromhex(blob_hex):
                    conflicts[key_hex] = existing.hex()
            if not conflicts:
                for key_hex, blob_hex in command["entries"].items():
                    umap.put(bytes.fromhex(key_hex), bytes.fromhex(blob_hex))
            result = {"conflicts": conflicts}
            if not conflicts and sign_tx_fn is not None:
                tx_id = command.get("tx_id")
                if tx_id is not None:
                    result["tx_sig"] = sign_tx_fn(bytes.fromhex(tx_id))
            return result

        return apply

    @staticmethod
    def make_replica_state(db: NodeDatabase, sign_tx_fn=None):
        """(apply_fn, snapshot_fn, restore_fn, meta_store) over ONE durable
        db — everything a BFTReplica needs to survive restarts and serve
        catch-up state transfer (reference DefaultRecoverable's
        getSnapshot/installSnapshot, `BFTSMaRt.kt:150-276`)."""
        apply = BFTUniquenessProvider.make_replica_apply(db, sign_tx_fn)
        umap = KVStore(db, "bft_uniqueness")  # same store apply writes
        meta = KVStore(db, "bft_replica_meta")

        def snapshot() -> bytes:
            # SORTED: the f+1 state-transfer agreement compares digests
            # of this dump across replicas; sqlite row order without an
            # ORDER BY is unspecified, so byte-determinism must be
            # imposed here or honest replicas could never agree
            return serialize(sorted(
                [bytes(k), bytes(v)] for k, v in umap.items()
            ))

        def restore(data: bytes) -> None:
            # atomic: a crash mid-restore must never leave the uniqueness
            # map partially cleared (holes there would answer 'no
            # conflict' for already-spent states — silent Byzantine)
            with db.transaction():
                for k, _ in list(umap.items()):
                    umap.delete(k)
                for k, v in deserialize(data):
                    umap.put(bytes(k), bytes(v))

        return apply, snapshot, restore, meta


# ---------------------------------------------------------------------------
# Notary services
# ---------------------------------------------------------------------------

class NotaryService:
    """Base notary (reference TrustedAuthorityNotaryService)."""

    validating = False

    def __init__(self, services, identity: Party,
                 uniqueness_provider: Optional[UniquenessProvider] = None):
        self.services = services
        self.identity = identity
        self.uniqueness_provider = (
            uniqueness_provider or PersistentUniquenessProvider(services.db)
        )

    def validate_time_window(self, time_window: Optional[TimeWindow]) -> None:
        if time_window is None:
            return
        now = int(self.services.clock() * 1_000_000_000)
        if not time_window.contains(now):
            raise NotaryException("time-window invalid")

    def commit_input_states(self, inputs: List[StateRef], tx_id):
        """Commit; returns the commit protocol's notary signatures when it
        produced them (BFT: f+1 replica signatures), else None."""
        audit = getattr(self.services, "audit_service", None)
        try:
            sigs = self.uniqueness_provider.commit(
                inputs, tx_id, self.identity
            )
        except UniquenessException as e:
            if audit is not None:
                audit.record_event(
                    self.identity.name, "notary.conflict",
                    tx_id=tx_id.bytes.hex(), inputs=len(inputs),
                )
            raise NotaryException(e.conflict)
        if audit is not None:
            audit.record_event(
                self.identity.name, "notary.commit",
                tx_id=tx_id.bytes.hex(), inputs=len(inputs),
            )
        return sigs

    def sign(self, tx_id) -> object:
        return self.services.key_management_service.sign(
            tx_id.bytes, self.identity.owning_key
        )


class SimpleNotaryService(NotaryService):
    """Non-validating single-node notary (reference SimpleNotaryService)."""
    validating = False


class ValidatingNotaryService(NotaryService):
    """Fully validates transactions before committing: resolves the chain,
    checks signatures (batched) and runs contracts via the node's
    TransactionVerifierService (reference ValidatingNotaryService/Flow —
    the batch-scale verification path)."""
    validating = True


# ---------------------------------------------------------------------------
# Flows
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class NotarisationPayload:
    """What the client sends: full stx to validating notaries, tear-off to
    non-validating ones (reference NotaryFlow.Client:66-74).

    `dependencies` piggybacks the sender's locally-stored dependency
    chain (bounded) so a validating notary resolves WITHOUT opening
    fetch dialogues back to the client — the hop-count tax of pull-based
    resolution was ~half the per-transaction message count (round-3
    system profile). The notary verifies every pushed transaction
    exactly as it verifies fetched ones; anything missing still pulls."""
    signed_transaction: Optional[SignedTransaction]
    filtered_transaction: Optional[FilteredTransaction]
    dependencies: Tuple = ()


@dataclass(frozen=True)
class NotarisationResponse:
    signatures: Tuple  # DigitalSignatureWithKey over the tx id


register_adapter(
    NotarisationPayload, "NotarisationPayload",
    lambda p: {"stx": p.signed_transaction, "ftx": p.filtered_transaction,
               "deps": list(p.dependencies)},
    lambda d: NotarisationPayload(
        d["stx"], d["ftx"], tuple(d.get("deps") or ())
    ),
)
register_adapter(
    NotarisationResponse, "NotarisationResponse",
    lambda r: {"sigs": list(r.signatures)},
    lambda d: NotarisationResponse(tuple(d["sigs"])),
)


def notary_tearoff_filter(obj: object) -> bool:
    """What a non-validating notary may see: inputs (StateRef), the time
    window, and the notary identity (Party).  Outputs, commands and
    attachments stay pruned — that privacy is the point of the tear-off
    (reference NotaryFlow.Client, NotaryFlow.kt:66-74)."""
    return isinstance(obj, (StateRef, TimeWindow, Party))


@initiating_flow
class NotaryClientFlow(FlowLogic):
    """Client side (reference NotaryFlow.Client, NotaryFlow.kt:33-95)."""

    def __init__(self, stx: SignedTransaction, notary_validating: Optional[bool] = None):
        self.stx = stx
        # None -> ask the network map (single-notary networks); explicit for
        # multi-notary setups.
        self.notary_validating = notary_validating

    def call(self):
        from ..core.transactions.notary_change import (
            NotaryChangeWireTransaction,
        )

        stx = self.stx
        notary = stx.notary
        if notary is None:
            raise FlowException("transaction has no notary set")
        is_notary_change = isinstance(stx.tx, NotaryChangeWireTransaction)
        if is_notary_change:
            # The instigator holds the input states; full pre-notarisation
            # check (signers resolved from input participants).
            stx.check_signatures_are_valid()
            try:
                stx.tx.check_inputs_and_signatures(
                    stx.sigs, self.service_hub.load_state, exclude_notary=True
                )
            except ValueError as exc:
                raise FlowException(str(exc))
        elif stx.inputs:
            # All non-notary signatures must already be present and valid.
            stx.verify_signatures_except(notary.owning_key)
        validating = self.notary_validating
        if validating is None:
            validating = self.service_hub.network_map_cache.is_validating_notary(
                notary
            )
        if validating or is_notary_change:
            # Tear-offs don't apply to notary-change transactions
            # (reference NotaryChangeTransactions.kt: filtering n/a).
            # Piggyback the local dependency chain so the validating
            # notary resolves without fetch dialogues back to us.
            from ..core.flows.library import collect_dependencies

            payload = NotarisationPayload(
                stx, None,
                collect_dependencies(stx, self.service_hub)
                if not is_notary_change else (),
            )
        else:
            # Reveal only what a non-validating notary needs: inputs
            # (StateRef), the time window, and the notary identity (Party).
            # Outputs/commands/attachments stay pruned to hashes — the
            # privacy point of the tear-off (reference NotaryFlow.Client).
            # check_all_inputs_revealed + the GROUP_SIZES leaf give the
            # notary completeness without a full reveal.
            wtx = stx.tx
            ftx = wtx.build_filtered_transaction(notary_tearoff_filter)
            payload = NotarisationPayload(None, ftx)
        response = yield self.send_and_receive_with_retry(
            notary, payload, NotarisationResponse
        )
        sigs = list(response.signatures)
        if not sigs:
            raise NotaryException("notary returned no signatures")
        for sig in sigs:
            # every signer must belong to the notary identity (leaf of a
            # composite cluster key, or the key itself)
            leaf_keys = getattr(
                notary.owning_key, "keys", frozenset({notary.owning_key})
            )
            if sig.by not in leaf_keys and not notary.owning_key.is_fulfilled_by(
                {sig.by}
            ):
                raise NotaryException(
                    f"signature from {sig.by} is not the notary's"
                )
            if not sig.is_valid(stx.id.bytes):
                raise NotaryException("invalid notary signature")
        # COLLECTIVE fulfillment: a composite cluster identity (reference
        # distributed notary service keys) may need several distinct
        # members' signatures to reach its threshold (BFT: f+1)
        if not notary.owning_key.is_fulfilled_by({s.by for s in sigs}):
            raise NotaryException(
                "notary signatures do not fulfil the cluster identity"
            )
        return sigs


@initiated_by(NotaryClientFlow)
class NotaryServiceFlow(FlowLogic):
    """Server side template (reference NotaryFlow.Service:106-129)."""

    def __init__(self, counterparty: Party):
        self.counterparty = counterparty

    def call(self):
        service: NotaryService = getattr(self.service_hub, "notary_service", None)
        if service is None:
            raise FlowException("this node is not a notary")
        payload = yield self.receive(self.counterparty, NotarisationPayload)
        tx_id, inputs, time_window = yield from self._receive_and_verify(
            service, payload
        )
        service.validate_time_window(time_window)
        # off-pump: a cluster commit can block on consensus (leader
        # election, member outage) and must not starve the messaging
        # pump that delivers the consensus traffic itself
        commit_sigs = yield self.await_blocking(
            lambda: service.commit_input_states(inputs, tx_id)
        )
        # the commit protocol's own signatures (BFT: f+1 replicas) win;
        # otherwise the serving identity signs
        sigs = tuple(commit_sigs) if commit_sigs else (service.sign(tx_id),)
        yield self.send(self.counterparty, NotarisationResponse(sigs))

    def _receive_and_verify(self, service: NotaryService, payload):
        from ..core.transactions.notary_change import (
            NotaryChangeWireTransaction,
        )

        stx = payload.signed_transaction
        if stx is not None and isinstance(stx.tx, NotaryChangeWireTransaction):
            return (yield from self._verify_notary_change(stx, service))
        if service.validating:
            stx = payload.signed_transaction
            if stx is None:
                raise NotaryException(
                    "validating notary requires the full transaction"
                )
            notary_key = stx.notary.owning_key if stx.notary else None
            # Signature hot loop -> the node's CROSS-transaction batcher
            # (verifier service SignatureBatcher): concurrent notarise
            # flows accumulate into one device-worthy flush instead of
            # each paying its own dispatch. The flow parks off-pump while
            # the batch resolves, so other flows keep feeding the batch.
            svc = getattr(
                self.service_hub, "transaction_verifier_service", None
            )
            if (
                svc is not None and stx.sigs
                and os.environ.get("CORDA_TPU_NOTARY_BATCHED", "1") != "0"
            ):
                futs = svc.verify_signatures(stx.signature_check_items())
                # deterministic single-pump networks (MockNetwork) run
                # the await INLINE: nothing else can feed the batch while
                # we block, so waiting out the linger is pure latency
                inline = (
                    not self.state_machine.smm.dispatches_blocking_off_pump
                )

                def _collect():
                    if inline:
                        svc.flush_signatures()
                    return [
                        i for i, f in enumerate(futs) if not f.result(120)
                    ]

                bad = yield self.await_blocking(_collect)
                if bad:
                    raise NotaryException(
                        f"invalid signature(s) at positions {bad} on {stx.id}"
                    )
                stx.check_required_keys_except(notary_key)
            else:
                stx.verify_signatures_except(notary_key)
            resolved = yield from self.sub_flow(
                ResolveTransactionsFlow(
                    stx, self.counterparty,
                    pool=getattr(payload, "dependencies", ()),
                )
            )
            missing_atts = [
                h for h in stx.tx.attachments
                if not self.service_hub.attachments.has_attachment(h)
            ]
            if missing_atts:
                yield from self.sub_flow(
                    FetchAttachmentsFlow(tuple(missing_atts), self.counterparty)
                )
            try:
                stx.verify(self.service_hub, check_sufficient_signatures=False)
            except Exception as exc:
                raise NotaryException(f"transaction invalid: {exc}")
            wtx = stx.tx
            return stx.id, list(wtx.inputs), wtx.time_window
        ftx = payload.filtered_transaction
        if ftx is None:
            raise NotaryException("non-validating notary requires a tear-off")
        ftx.verify()  # Merkle proof against the root = tx id
        # Completeness: a tear-off hiding inputs must not obtain a signature
        # (it would leave the hidden inputs spendable again).
        ftx.check_all_inputs_revealed()
        return ftx.id, list(ftx.inputs), ftx.time_window

    def _verify_notary_change(self, stx, service):
        """Notary-change txs skip contract verification. A VALIDATING
        notary resolves the back-chain and checks every participant
        signed; a NON-validating notary must NOT pull the chain — that
        would expose full historic transaction contents, the exact leak
        the tear-off model exists to prevent — so it checks only
        cryptographic signature validity and commits.
        """
        wtx = stx.tx
        # This service must BE the old notary, or a rogue client could have
        # a different notary commit inputs it does not govern (ledger fork).
        me = self.service_hub.my_info
        if wtx.notary.owning_key.encoded != me.owning_key.encoded:
            raise NotaryException(
                f"notary change names {wtx.notary.name}, not this notary"
            )
        if not service.validating:
            try:
                stx.check_signatures_are_valid()
            except Exception as exc:
                raise NotaryException(f"notary change invalid: {exc}")
            return stx.id, list(wtx.inputs), None
        yield from self.sub_flow(
            ResolveTransactionsFlow(
                [ref.txhash for ref in wtx.inputs], self.counterparty
            )
        )
        try:
            stx.check_signatures_are_valid()
            wtx.check_inputs_and_signatures(
                stx.sigs, self.service_hub.load_state, exclude_notary=True
            )
        except NotaryException:
            raise
        except Exception as exc:
            raise NotaryException(f"notary change invalid: {exc}")
        return stx.id, list(wtx.inputs), None


# Imported lazily to avoid a cycle at module load; these flows live with
# the other core library flows.
from ..core.flows.library import (  # noqa: E402
    FetchAttachmentsFlow,
    ResolveTransactionsFlow,
)

"""Notary services: uniqueness consensus over consumed input states.

Reference (SURVEY.md section 2.6):
  * `NotaryService` base + helpers — `core/.../node/services/NotaryService.kt`
  * `NotaryFlow.Client` / `.Service`  — `core/.../flows/NotaryFlow.kt`
  * `SimpleNotaryService` — `node/.../transactions/SimpleNotaryService.kt`
  * `ValidatingNotaryService/Flow` — the path that drives batch verification
  * `PersistentUniquenessProvider` — RDBMS commit log with conflict
    detection (`PersistentUniquenessProvider.kt:62-92`)

Batch-first TPU design note: `UniquenessProvider.commit` takes the whole
input set in one call (all-or-nothing), and the validating path funnels
signature checks through the node's TransactionVerifierService / batcher
rather than per-signature host crypto.
"""
from __future__ import annotations

import os
import re
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.contracts.structures import StateRef, TimeWindow
from ..core.flows.api import FlowException, FlowLogic, initiated_by, initiating_flow
from ..core.identity import Party
from ..core.serialization.codec import deserialize, register_adapter, serialize
from ..core.transactions.filtered import FilteredTransaction
from ..core.transactions.signed import SignedTransaction
from ..utils import eventlog, faultpoints, lockorder, tracing
from .database import KVStore, NodeDatabase


# ---------------------------------------------------------------------------
# Errors (reference NotaryError sealed class, NotaryFlow.kt:140-152)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Conflict:
    tx_id: object
    consumed: Dict[str, object]  # state-ref repr -> consuming tx id


class NotaryException(FlowException):
    def __init__(self, error):
        super().__init__(f"notary error: {error}")
        self.error = error


#: `repr(StateRef)` is "<64-hex txhash>(<index>)"; the Conflict's
#: consumed map renders as `'<ref repr>': SecureHash(<64-hex>)`. The
#: pattern recovers (ref, consuming tx) pairs from BOTH the structured
#: Conflict and its string form — a propagated NotaryException crosses
#: the session wire as "TypeName|message" (rebuild_flow_exception), so
#: a remote client only ever holds the text.
_CONFLICT_PAIR = re.compile(
    r"([0-9A-Fa-f]{64})\((\d+)\)'?\s*:\s*(?:SecureHash\()?([0-9A-Fa-f]{64})"
)


def conflict_consumed_refs(error) -> List[Tuple[StateRef, object]]:
    """(consumed StateRef, consuming tx id) pairs named by a notary
    conflict — from a Conflict object, a NotaryException (either the
    in-process structured form or the text a rebuilt remote exception
    carries), or raw text. The structured form renders to the same
    `'<ref repr>': SecureHash(<hex>)` pairs the wire text holds, so ONE
    parse covers both. Empty when the error names no conflict."""
    from ..core.crypto.secure_hash import SecureHash

    text = str(getattr(error, "error", None) or error)
    return [
        (StateRef(SecureHash(bytes.fromhex(h)), int(idx)),
         SecureHash(bytes.fromhex(consumer)))
        for h, idx, consumer in _CONFLICT_PAIR.findall(text)
    ]


class NotaryUnavailableError(NotaryException):
    """Infrastructure outage verdict (overload shed, service down) —
    never a conflict/validation verdict. `transient = True` is the TYPED
    marker the flow hospital's classifier honours, so retryability does
    not hang on message wording."""

    transient = True


class WrongNotaryError(NotaryException):
    """Notary-pinning violation verdict: the transaction's inputs are
    governed by a different notary than the one asked to commit them, or
    the named notary cannot be resolved on this network. FINAL by
    construction — retrying cannot change which notary a state is pinned
    to — so there is deliberately no `transient` attr and the message
    wording must never match the hospital's unavailable/timed-out
    predicate: the hospital wards it fatal instead of re-admitting a
    flow that can only fail the same way again. `pinned_notary` carries
    the governing notary of the offending input (None for the
    unresolvable-notary case) so callers can re-route or instigate a
    notary change."""

    def __init__(self, error, pinned_notary: Optional[Party] = None):
        super().__init__(error)
        self.pinned_notary = pinned_notary


class UniquenessException(Exception):
    def __init__(self, conflict: Conflict):
        super().__init__(f"input state conflict: {conflict}")
        self.conflict = conflict


# ---------------------------------------------------------------------------
# Uniqueness providers
# ---------------------------------------------------------------------------

#: the uniqueness log's one durability barrier (store "uniqueness_log"):
#: fired by NotaryServiceFlow.commit_input_states before the commit-log
#: write — a crash here must lose the whole commit, never tear it
faultpoints.register_crash_point("notary.commit", "uniqueness_log")


class UniquenessProvider:
    def commit(self, states: List[StateRef], tx_id, requesting_party: Party):
        """Consume `states` for `tx_id` or raise UniquenessException.

        May return a list of notary signatures over the tx id when the
        commit protocol itself produces them (the BFT provider returns
        the f+1 replica signatures); None otherwise."""
        raise NotImplementedError

    # Providers that can fold MANY transactions' input sets into one
    # consensus round / one DB transaction additionally implement
    #   commit_many(requests: [(states, tx_id, party)]) -> [per-tx result]
    # where each result is None (committed) or a Conflict (rejected).
    # Semantics are sequential: a request earlier in the batch that
    # claims a ref makes a later conflicting request fail, exactly as if
    # the commits had run one at a time. CoalescingUniquenessProvider
    # fronts such providers on the notary hot path.


class PersistentUniquenessProvider(UniquenessProvider):
    """Single-node commit log in the node DB. All-or-nothing batch commit
    with conflict reporting (reference PersistentUniquenessProvider).

    `table` namespaces the commit log so a partitioned notary can run one
    provider per shard over ONE database (sharded_notary.py)."""

    def __init__(self, db: NodeDatabase, table: str = "uniqueness"):
        self._map = KVStore(db, table)
        self._db = db

    @staticmethod
    def _key(ref: StateRef) -> bytes:
        return ref.txhash.bytes + ref.index.to_bytes(4, "big")

    def probe_commits(self, keys) -> Dict[bytes, object]:
        """{key: consuming tx id} for already-spent keys — the committed-
        state read the sharded provider's cross-shard prepare runs."""
        return {
            k: deserialize(blob)["tx_id"]
            for k, blob in self._map.get_many(keys).items()
        }

    def consumed_keys(self) -> List[Tuple[bytes, str]]:
        """Full commit-log dump as (state key, consuming tx hex) pairs —
        recovery's cross-shard double-spend check (node/recovery.py
        verify_consumption) scans EVERY shard's log with this."""
        out: List[Tuple[bytes, str]] = []
        for k, blob in self._map.items():
            tx_id = deserialize(blob)["tx_id"]
            tx_hex = (
                tx_id.bytes.hex() if hasattr(tx_id, "bytes") else str(tx_id)
            )
            out.append((bytes(k), tx_hex))
        return out

    def commit(self, states: List[StateRef], tx_id, requesting_party: Party) -> None:
        result = self.commit_many([(states, tx_id, requesting_party)])[0]
        if result is not None:
            raise UniquenessException(result)

    def commit_many(self, requests: Sequence[Tuple]) -> List[Optional[Conflict]]:
        """One DB transaction for the whole batch: the merged StateRef set
        is fetched in one pass, conflicts are resolved per-tx against the
        map plus earlier requests in the same batch, and all accepted
        rows land via one executemany."""
        out: List[Optional[Conflict]] = []
        with self._db.transaction():
            merged = {
                self._key(ref)
                for states, _, _ in requests
                for ref in states
            }
            existing = self._map.get_many(merged)
            staged: Dict[bytes, object] = {}  # key -> tx claimed this batch
            writes: List[Tuple[bytes, bytes]] = []
            for states, tx_id, party in requests:
                conflicts: Dict[str, object] = {}
                for ref in states:
                    key = self._key(ref)
                    prior = staged.get(key)
                    if prior is not None:
                        if prior != tx_id:
                            conflicts[repr(ref)] = prior
                        continue
                    blob = existing.get(key)
                    if blob is not None:
                        consuming = deserialize(blob)
                        if consuming["tx_id"] != tx_id:
                            conflicts[repr(ref)] = consuming["tx_id"]
                if conflicts:
                    out.append(Conflict(tx_id, conflicts))
                    continue
                blob = serialize({"tx_id": tx_id, "by": party.name})
                for ref in states:
                    key = self._key(ref)
                    staged[key] = tx_id
                    writes.append((key, blob))
                out.append(None)
            if writes:
                self._map.put_many(writes)
        return out


class RaftUniquenessProvider(UniquenessProvider):
    """Replicated commit log over the framework's own Raft (reference
    `RaftUniquenessProvider.kt:71-156` which delegates to Copycat).

    The state machine is a persisted map StateRef-key -> consuming tx; a
    `putall` command checks-and-inserts the whole input set atomically and
    deterministically on every replica.  Only the leader accepts commits;
    notary cluster clients fail over between members
    (send_and_receive_with_retry, reference FlowLogic.kt:98-110).
    """

    def __init__(self, raft_node, db: NodeDatabase,
                 forwarding_retry: bool = False):
        self.raft = raft_node
        # real-time clusters (OS processes) forward follower commits to
        # the leader and retry across elections; virtual-time test buses
        # keep the fail-fast behavior and drive retries themselves
        self.forwarding_retry = forwarding_retry
        self._map = KVStore(db, "raft_uniqueness")
        # Log compaction (reference DistributedImmutableMap's snapshottable
        # state machine): the Raft log's applied prefix folds into a dump
        # of the uniqueness map.
        if getattr(raft_node, "snapshot_fn", None) is None:
            raft_node.snapshot_fn = self.snapshot
        if getattr(raft_node, "restore_fn", None) is None:
            raft_node.restore_fn = self.restore

    def snapshot(self) -> bytes:
        return serialize([[bytes(k), bytes(v)] for k, v in self._map.items()])

    def restore(self, data: bytes) -> None:
        for k, _ in list(self._map.items()):
            self._map.delete(k)
        for k, v in deserialize(data):
            self._map.put(bytes(k), bytes(v))

    def is_consumed(self, ref: StateRef) -> bool:
        """Whether this REPLICA's applied log knows `ref` as spent —
        a replication observability hook (cluster tests, dryrun)."""
        return self._map.get(PersistentUniquenessProvider._key(ref)) is not None

    def probe_commits(self, keys) -> Dict[bytes, object]:
        """{key: consuming tx id} from this replica's APPLIED log — the
        committed-state read behind a cross-shard prepare. Submit the
        probe against the shard leader (the sharded provider routes
        commits there anyway) for a linearizable-enough read."""
        return {
            k: deserialize(blob)["tx_id"]
            for k, blob in self._map.get_many(keys).items()
        }

    def apply(self, command: dict):
        """State-machine apply (runs on every replica, in log order)."""
        kind = command.get("kind")
        if kind == "putall":
            # single-tx command; kept for logs persisted before the
            # batched protocol (replayed verbatim after a restart)
            return self._apply_entries([command["entries"]])[0]
        if kind != "putall_multi":
            return None
        return {"results": self._apply_entries(command["txs"])}

    def _apply_entries(self, txs: Sequence[dict]) -> List[dict]:
        """Apply each tx's entry set in order; per-tx all-or-nothing.
        A tx later in the batch that collides with an EARLIER accepted
        tx sees that tx's rows already in the map, so merged batches
        keep exact sequential-commit semantics. One DB transaction for
        the whole command keeps a 10k-row burst off sqlite's
        per-statement commit path."""
        results = []
        with self._map.db.transaction():
            for entries in txs:
                conflicts = {}
                for key_hex, consuming_blob in entries.items():
                    existing = self._map.get(bytes.fromhex(key_hex))
                    if existing is not None:
                        mine = deserialize(consuming_blob)["tx_id"]
                        theirs = deserialize(existing)["tx_id"]
                        if mine != theirs:
                            conflicts[key_hex] = theirs
                if not conflicts:
                    self._map.put_many(
                        (bytes.fromhex(k), blob)
                        for k, blob in entries.items()
                    )
                results.append({"conflicts": conflicts})
        return results

    def _submit(self, command: dict) -> dict:
        from concurrent.futures import TimeoutError as _FuturesTimeout

        from .raft import NotLeaderError

        if not self.forwarding_retry:
            return self.raft.submit(command).result(timeout=30)
        # Any member accepts the commit: leaders apply locally,
        # followers forward (raft.submit_anywhere); NotLeaderError
        # during elections retries until the cluster converges
        # (reference CopycatClient). putall is idempotent for the
        # same tx_id, so a retried commit cannot double-spend itself.
        deadline = time.monotonic() + 30
        while True:
            fut = self.raft.submit_anywhere(command)
            try:
                return fut.result(timeout=5)
            except NotLeaderError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.2)
            except (TimeoutError, _FuturesTimeout):
                # distinct classes on 3.10; aliases from 3.11 on
                if time.monotonic() > deadline:
                    raise

    def commit(self, states: List[StateRef], tx_id, requesting_party: Party) -> None:
        result = self.commit_many([(states, tx_id, requesting_party)])[0]
        if result is not None:
            raise UniquenessException(result)

    def commit_many(self, requests: Sequence[Tuple]) -> List[Optional[Conflict]]:
        """ONE Raft log entry for the whole batch: a 10k-tx uniqueness
        burst costs O(batches) consensus rounds instead of O(tx). Per-tx
        verdicts come back positionally and demultiplex to Conflicts."""
        txs = []
        for states, tx_id, party in requests:
            blob = serialize({"tx_id": tx_id, "by": party.name})
            txs.append({
                PersistentUniquenessProvider._key(ref).hex(): blob
                for ref in states
            })
        result = self._submit({"kind": "putall_multi", "txs": txs})
        out: List[Optional[Conflict]] = []
        for (states, tx_id, _), verdict in zip(requests, result["results"]):
            conflicts = verdict["conflicts"]
            if not conflicts:
                out.append(None)
                continue
            by_key = {
                PersistentUniquenessProvider._key(ref).hex(): ref
                for ref in states
            }
            out.append(Conflict(
                tx_id,
                {
                    repr(by_key[k]): v
                    for k, v in conflicts.items()
                    if k in by_key
                },
            ))
        return out


class BFTUniquenessProvider(UniquenessProvider):
    """Byzantine-fault-tolerant commit log over the framework's own PBFT
    (reference `BFTSMaRt.kt` Client/Replica wrapping the BFT-SMaRt library;
    see corda_tpu.node.bft for the replica protocol).  The provider is the
    client side: it submits the putall and accepts the verdict once f+1
    replicas agree.

    With a BLS vote committee (BFTReplica vote_scheme "bls"), the
    replicas behind this provider certify each block's prepare quorum
    with ONE aggregate signature check instead of 2f+1 per-vote
    verifies; `vote_stats()` surfaces the measured split so the
    committee-consensus loadtest and bench stage can report
    aggregate-vs-naive verification work (docs/bls-aggregation.md)."""

    def __init__(self, bft_client, replicas=None):
        self.client = bft_client
        # in-process replicas, when the caller hosts them (MockNetwork
        # clusters, loadtests); real deployments read per-node metrics
        self._replicas = list(replicas or [])

    def vote_stats(self) -> dict:
        """{vote_scheme, agg_checks, vote_verifies} summed over the
        replicas this process hosts (zeros when they live elsewhere).
        vote_scheme is "mixed" when hosted replicas disagree — a split
        committee is a degraded deployment and must never masquerade as
        a healthy "bls" one to the loadtest SLOs."""
        out = {"vote_scheme": None, "agg_checks": 0, "vote_verifies": 0}
        schemes = {r.vote_scheme for r in self._replicas}
        if schemes:
            out["vote_scheme"] = (
                schemes.pop() if len(schemes) == 1 else "mixed"
            )
        for r in self._replicas:
            out["agg_checks"] += r.agg_checks
            out["vote_verifies"] += r.vote_verifies
        return out

    def commit(self, states: List[StateRef], tx_id, requesting_party: Party) -> None:
        entries = {
            PersistentUniquenessProvider._key(ref).hex():
                serialize({"tx_id": tx_id, "by": requesting_party.name}).hex()
            for ref in states
        }
        fut = self.client.submit({
            "kind": "putall", "entries": entries,
            "tx_id": tx_id.bytes.hex(),
        })
        result = fut.result(timeout=30)
        if result["conflicts"]:
            by_key = {
                PersistentUniquenessProvider._key(ref).hex(): ref
                for ref in states
            }
            raise UniquenessException(Conflict(
                tx_id,
                {
                    repr(by_key[k]): deserialize(bytes.fromhex(v))["tx_id"]
                    for k, v in result["conflicts"].items()
                    if k in by_key
                },
            ))
        # the f+1 replica signatures over the tx id, returned per-request
        # so concurrent notarisations of the same tx cannot cross wires
        return list(result.get("tx_sigs") or []) or None

    @staticmethod
    def make_replica_apply(db: NodeDatabase, sign_tx_fn=None):
        """The deterministic state-machine applied on every BFT replica.

        sign_tx_fn(tx_id_bytes) -> DigitalSignatureWithKey: when given, a
        conflict-free commit reply carries this replica's signature over
        the transaction id (reference BFTNonValidatingNotaryService:
        per-replica signatures returned to the client, which aggregates
        f+1 of them into the notary response)."""
        umap = KVStore(db, "bft_uniqueness")

        def apply(command: dict):
            if command.get("kind") != "putall":
                return None
            conflicts = {}
            for key_hex, blob_hex in command["entries"].items():
                existing = umap.get(bytes.fromhex(key_hex))
                if existing is not None and existing != bytes.fromhex(blob_hex):
                    conflicts[key_hex] = existing.hex()
            if not conflicts:
                for key_hex, blob_hex in command["entries"].items():
                    umap.put(bytes.fromhex(key_hex), bytes.fromhex(blob_hex))
            result = {"conflicts": conflicts}
            if not conflicts and sign_tx_fn is not None:
                tx_id = command.get("tx_id")
                if tx_id is not None:
                    result["tx_sig"] = sign_tx_fn(bytes.fromhex(tx_id))
            return result

        return apply

    @staticmethod
    def make_replica_state(db: NodeDatabase, sign_tx_fn=None):
        """(apply_fn, snapshot_fn, restore_fn, meta_store) over ONE durable
        db — everything a BFTReplica needs to survive restarts and serve
        catch-up state transfer (reference DefaultRecoverable's
        getSnapshot/installSnapshot, `BFTSMaRt.kt:150-276`)."""
        apply = BFTUniquenessProvider.make_replica_apply(db, sign_tx_fn)
        umap = KVStore(db, "bft_uniqueness")  # same store apply writes
        meta = KVStore(db, "bft_replica_meta")

        def snapshot() -> bytes:
            # SORTED: the f+1 state-transfer agreement compares digests
            # of this dump across replicas; sqlite row order without an
            # ORDER BY is unspecified, so byte-determinism must be
            # imposed here or honest replicas could never agree
            return serialize(sorted(
                [bytes(k), bytes(v)] for k, v in umap.items()
            ))

        def restore(data: bytes) -> None:
            # atomic: a crash mid-restore must never leave the uniqueness
            # map partially cleared (holes there would answer 'no
            # conflict' for already-spent states — silent Byzantine)
            with db.transaction():
                for k, _ in list(umap.items()):
                    umap.delete(k)
                for k, v in deserialize(data):
                    umap.put(bytes(k), bytes(v))

        return apply, snapshot, restore, meta


# ---------------------------------------------------------------------------
# Commit coalescing (group commit)
# ---------------------------------------------------------------------------

class CoalescingUniquenessProvider(UniquenessProvider):
    """Group-commit front for providers that implement `commit_many`.

    Concurrent `commit` calls (the notary's flow-blocking executor runs
    one per in-flight notarise flow) coalesce into ONE consensus round /
    ONE DB transaction: the first caller in becomes the drainer and
    keeps folding whatever arrives while a round is in flight; everyone
    else waits on a per-request future. Uncontended commits drain
    immediately as a batch of 1, so the layer adds no linger latency —
    batching emerges exactly when there is load to batch (the
    committee-consensus lesson from PAPERS.md: once verification is
    batched, the coordination path must batch too).

    Seam telemetry: `batches`, `commits`, `largest_batch`,
    `commit_wall_s` feed bench.py's `uniq_commit_batch_mean` stage
    timing."""

    def __init__(self, delegate, max_batch: Optional[int] = None,
                 max_queue: Optional[int] = None):
        if max_batch is None:
            max_batch = int(
                os.environ.get("CORDA_TPU_UNIQ_COALESCE_MAX", 512)
            )
        if max_queue is None:
            max_queue = int(
                os.environ.get("CORDA_TPU_NOTARY_QUEUE_MAX", 4096)
            )
        self.delegate = delegate
        self.max_batch = max_batch
        # overload protection: the notary's request queue is THIS pending
        # list — bounding it keeps a commit storm from queueing without
        # limit behind a slow consensus round. Overflow rejects with a
        # retryable "unavailable" NotaryException (the flow hospital
        # classifies it transient, so admitted flows retry with backoff
        # + jitter instead of dying). 0 = unbounded.
        self.max_queue = max_queue
        self._lock = lockorder.make_lock("CoalescingUniquenessProvider._lock")
        # (states, tx_id, party, trace ctx, Future) — the ctx is what lets
        # one group commit emit a fan-in span linking every waiting flow
        self._pending: List[Tuple] = []
        self._draining = False
        # seam telemetry
        self.batches = 0
        self.commits = 0
        self.largest_batch = 0
        self.commit_wall_s = 0.0
        self.sheds = 0  # commits rejected at the queue cap

    @property
    def mean_batch(self) -> float:
        return self.commits / self.batches if self.batches else 0.0

    @staticmethod
    def _batch_span(ctxs):
        """Fan-in span for one group-commit round: links every waiting
        flow's trace (untraced rounds emit no span)."""
        return tracing.get_tracer().fan_in_span("notary.commit_batch", ctxs)

    def commit(self, states: List[StateRef], tx_id, requesting_party: Party):
        fut: Optional[Future] = None
        ctx = tracing.current_context()  # the committing flow's trace
        shed = False
        with self._lock:
            if self._draining:
                if self.max_queue and len(self._pending) >= self.max_queue:
                    self.sheds += 1
                    shed = True
                else:
                    fut = Future()
                    self._pending.append(
                        (list(states), tx_id, requesting_party, ctx, fut)
                    )
            else:
                self._draining = True
        if shed:
            # retryable by design: the text matches the hospital's
            # notary-unavailable transient classifier, so an admitted
            # flow retries from its checkpoint (with jittered backoff)
            # instead of failing — the queue bound sheds WAITING, not work
            eventlog.emit(
                "warning", "notary", "commit shed: request queue full",
                queue_max=self.max_queue, tx_id=tx_id.bytes.hex()[:16],
            )
            raise NotaryUnavailableError(
                f"notary unavailable: request queue full ({self.max_queue});"
                " retry later"
            )
        if fut is not None:
            # a round is in flight: the drainer commits for us.
            # generous bound: the delegate's own consensus deadline
            # (30 s/round) plus queued rounds ahead of this one
            result = fut.result(timeout=120)
        else:
            # uncontended leader fast path: commit directly (no Future,
            # no handoff — a lone commit costs what the delegate costs),
            # then serve anything that queued behind us
            try:
                sp = self._batch_span((ctx,))
                t0 = time.perf_counter()
                try:
                    result = self.delegate.commit_many(
                        [(list(states), tx_id, requesting_party)]
                    )[0]
                except BaseException as exc:
                    sp.finish(error=exc)
                    raise
                sp.finish()
                self.commit_wall_s += time.perf_counter() - t0
                self.batches += 1
                self.commits += 1
                self.largest_batch = max(self.largest_batch, 1)
            finally:
                self._drain()
        if isinstance(result, Conflict):
            raise UniquenessException(result)
        return result

    def _drain(self) -> None:
        """Serve queued requests in max_batch rounds; caller must hold
        the drainer role (self._draining True). Releases it on exit.

        Shard-aware delegates (`shard_of`, e.g. ShardedUniquenessProvider)
        get the batch pre-grouped by shard and one commit_many PER SHARD,
        dispatched concurrently: the whole point of partitioned
        uniqueness is that shards are independent consensus groups, so a
        mixed coalesced batch must cost max-over-shards wall time, not
        sum — and never serialise one round per REQUEST. The per-round
        budget scales to max_batch PER SHARD for the same reason."""
        sharded = getattr(self.delegate, "shard_of", None) is not None
        n_shards = getattr(self.delegate, "n_shards", 1) if sharded else 1
        per_round = self.max_batch * max(1, n_shards)
        while True:
            with self._lock:
                batch = self._pending[:per_round]
                self._pending = self._pending[per_round:]
                if not batch:
                    self._draining = False
                    return
            sp = self._batch_span([c for _, _, _, c, _ in batch])
            t0 = time.perf_counter()
            try:
                requests = [(s, t, p) for s, t, p, _, _ in batch]
                if sharded and len(batch) > 1:
                    results = self._commit_many_by_shard(requests)
                else:
                    results = self.delegate.commit_many(requests)
            except BaseException as exc:
                # fail this round's waiters; later arrivals get a fresh
                # consensus attempt instead of inheriting the error
                sp.finish(error=exc)
                for *_, fut in batch:
                    fut.set_exception(exc)
                continue
            sp.finish()
            self.commit_wall_s += time.perf_counter() - t0
            self.batches += 1
            self.commits += len(batch)
            self.largest_batch = max(self.largest_batch, len(batch))
            # fan-in event mirroring the fan-in span: visible under every
            # waiting flow's trace in /logs?trace=<id>
            eventlog.emit(
                "info", "notary", "group commit",
                trace_ids={
                    c.trace_id for _, _, _, c, _ in batch if c is not None
                },
                batch=len(batch),
                wall_ms=round((time.perf_counter() - t0) * 1000, 3),
            )
            for (*_, fut), result in zip(batch, results):
                if isinstance(result, BaseException):
                    # a failed chunk's slots carry their error (other
                    # chunks in the round may have committed durably)
                    fut.set_exception(result)
                else:
                    fut.set_result(result)

    def _commit_many_by_shard(self, requests):
        """Partition one drained batch by the sharded delegate's routing
        (cross-shard requests form their own group — they run the
        two-phase protocol and must not ride a single-shard round) and
        commit the groups CONCURRENTLY, demultiplexing positionally."""
        groups: Dict[object, List[int]] = {}
        for i, (states, _tx, _p) in enumerate(requests):
            shards = self.delegate.shards_of(states)
            key = shards[0] if len(shards) == 1 else "cross"
            groups.setdefault(key, []).append(i)
        if len(groups) == 1:
            return self._commit_chunked(requests)
        results: List = [None] * len(requests)

        def run(indices: List[int]) -> None:
            # the drain budget is max_batch PER SHARD: under skewed
            # routing one group can hold most of the round, so chunk it
            # back to max_batch per delegate round — one hot issuer must
            # not inflate a single consensus round n_shards-fold
            for j in range(0, len(indices), self.max_batch):
                chunk = indices[j:j + self.max_batch]
                try:
                    for i, res in zip(chunk, self.delegate.commit_many(
                        [requests[i] for i in chunk]
                    )):
                        results[i] = res
                except BaseException as exc:
                    # a delegate round is all-or-nothing per CALL (one
                    # transaction / consensus round): only this chunk's
                    # waiters inherit the error. Raising for the whole
                    # drained batch would hand other groups' waiters an
                    # error for commits that already landed DURABLY —
                    # a flow treating that as final would abandon a tx
                    # whose inputs are permanently consumed.
                    for i in chunk:
                        results[i] = exc

        threads = [
            threading.Thread(
                target=run, args=(indices,), daemon=True,
                name=f"uniq-shard-{key}",
            )
            for key, indices in groups.items()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return results

    def _commit_chunked(self, requests) -> List:
        """Delegate rounds of at most max_batch (a skewed drain that
        landed on one shard still honours the per-round bound). A chunk
        that raises poisons only ITS slots — earlier chunks' durable
        commits keep their results (see _commit_many_by_shard)."""
        if len(requests) <= self.max_batch:
            return self.delegate.commit_many(requests)
        out: List = []
        for j in range(0, len(requests), self.max_batch):
            chunk = requests[j:j + self.max_batch]
            try:
                out.extend(self.delegate.commit_many(chunk))
            except BaseException as exc:
                out.extend([exc] * len(chunk))
        return out

    def __getattr__(self, name):
        # observability passthrough (is_consumed, member_providers, _map…)
        return getattr(self.delegate, name)


def default_uniqueness_provider(db: NodeDatabase,
                                shards: Optional[int] = None) -> UniquenessProvider:
    """The notary's default commit log: partitioned across `shards`
    independent per-shard providers when sharding is configured
    (node.conf `shards`, `MockNetwork.create_node(shards=)`, or
    `CORDA_TPU_SHARDS` — docs/sharding.md), else exactly the unsharded
    PersistentUniquenessProvider of every round before this one.
    shards None/0/1 keeps the default path byte-identical."""
    if shards is None:
        shards = int(os.environ.get("CORDA_TPU_SHARDS", "0") or 0)
    if shards and int(shards) > 1:
        from .sharded_notary import ShardedUniquenessProvider

        if db.path != ":memory:":
            # file-backed node: one sqlite file per shard so commits
            # parallelise across OS workers (per-database write locks),
            # coordination state in the shared node db
            return ShardedUniquenessProvider.over_directory(
                db, os.path.join(os.path.dirname(db.path), "shards"),
                int(shards),
            )
        return ShardedUniquenessProvider.over_database(db, int(shards))
    return PersistentUniquenessProvider(db)


def maybe_coalesced(provider: UniquenessProvider) -> UniquenessProvider:
    """Front `provider` with the group-commit layer when it supports
    batched commits (CORDA_TPU_NOTARY_COALESCE=0 disables)."""
    if (
        hasattr(provider, "commit_many")
        and not isinstance(provider, CoalescingUniquenessProvider)
        and os.environ.get("CORDA_TPU_NOTARY_COALESCE", "1") != "0"
    ):
        return CoalescingUniquenessProvider(provider)
    return provider


# ---------------------------------------------------------------------------
# Notary services
# ---------------------------------------------------------------------------

class NotaryService:
    """Base notary (reference TrustedAuthorityNotaryService)."""

    validating = False

    def __init__(self, services, identity: Party,
                 uniqueness_provider: Optional[UniquenessProvider] = None):
        self.services = services
        self.identity = identity
        self.uniqueness_provider = maybe_coalesced(
            uniqueness_provider or default_uniqueness_provider(services.db)
        )

    def validate_time_window(self, time_window: Optional[TimeWindow]) -> None:
        if time_window is None:
            return
        now = int(self.services.clock() * 1_000_000_000)
        if not time_window.contains(now):
            raise NotaryException("time-window invalid")

    def commit_input_states(self, inputs: List[StateRef], tx_id):
        """Commit; returns the commit protocol's notary signatures when it
        produced them (BFT: f+1 replica signatures), else None."""
        audit = getattr(self.services, "audit_service", None)
        if faultpoints.hook is not None:
            action = faultpoints.fire(
                "notary.commit", tx_id=tx_id.bytes.hex(),
                notary=self.identity.name,
            )
            if action == "unavailable":
                raise NotaryException("notary unavailable (injected fault)")
            if action == "crash":
                # the durability barrier: the uniqueness write below has
                # not happened yet — a crash here must lose the commit
                # cleanly, never half-record it
                raise faultpoints.InjectedCrashError(
                    "injected crash at notary.commit"
                )
            if isinstance(action, tuple) and action[:1] == ("delay",):
                time.sleep(float(action[1]))
        try:
            # child span of the serving notary flow (whose context is
            # current — inline on the pump or re-activated by the
            # blocking executor); the coalescer's group-commit span
            # links onto it
            with tracing.get_tracer().span(
                "notary.commit",
                tx_id=tx_id.bytes.hex()[:16], inputs=len(inputs),
            ):
                sigs = self.uniqueness_provider.commit(
                    inputs, tx_id, self.identity
                )
        except UniquenessException as e:
            if audit is not None:
                audit.record_event(
                    self.identity.name, "notary.conflict",
                    tx_id=tx_id.bytes.hex(), inputs=len(inputs),
                )
            eventlog.emit(
                "warning", "notary", "double-spend conflict",
                tx_id=tx_id.bytes.hex()[:16], inputs=len(inputs),
                node=self.identity.name,
            )
            raise NotaryException(e.conflict)
        if audit is not None:
            audit.record_event(
                self.identity.name, "notary.commit",
                tx_id=tx_id.bytes.hex(), inputs=len(inputs),
            )
        # flight recorder: the serving flow's trace context is current
        # here, so /logs?trace=<id> joins the commit against its trace
        eventlog.emit(
            "info", "notary", "transaction committed",
            tx_id=tx_id.bytes.hex()[:16], inputs=len(inputs),
            node=self.identity.name,
        )
        return sigs

    def sign(self, tx_id) -> object:
        return self.services.key_management_service.sign(
            tx_id.bytes, self.identity.owning_key
        )


class SimpleNotaryService(NotaryService):
    """Non-validating single-node notary (reference SimpleNotaryService)."""
    validating = False


class ValidatingNotaryService(NotaryService):
    """Fully validates transactions before committing: resolves the chain,
    checks signatures (batched) and runs contracts via the node's
    TransactionVerifierService (reference ValidatingNotaryService/Flow —
    the batch-scale verification path)."""
    validating = True


# ---------------------------------------------------------------------------
# Flows
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class NotarisationPayload:
    """What the client sends: full stx to validating notaries, tear-off to
    non-validating ones (reference NotaryFlow.Client:66-74).

    `dependencies` piggybacks the sender's locally-stored dependency
    chain (bounded) so a validating notary resolves WITHOUT opening
    fetch dialogues back to the client — the hop-count tax of pull-based
    resolution was ~half the per-transaction message count (round-3
    system profile). The notary verifies every pushed transaction
    exactly as it verifies fetched ones; anything missing still pulls."""
    signed_transaction: Optional[SignedTransaction]
    filtered_transaction: Optional[FilteredTransaction]
    dependencies: Tuple = ()


@dataclass(frozen=True)
class NotarisationResponse:
    signatures: Tuple  # DigitalSignatureWithKey over the tx id


register_adapter(
    NotarisationPayload, "NotarisationPayload",
    lambda p: {"stx": p.signed_transaction, "ftx": p.filtered_transaction,
               "deps": list(p.dependencies)},
    lambda d: NotarisationPayload(
        d["stx"], d["ftx"], tuple(d.get("deps") or ())
    ),
)
register_adapter(
    NotarisationResponse, "NotarisationResponse",
    lambda r: {"sigs": list(r.signatures)},
    lambda d: NotarisationResponse(tuple(d["sigs"])),
)


def notary_tearoff_filter(obj: object) -> bool:
    """What a non-validating notary may see: inputs (StateRef), the time
    window, and the notary identity (Party).  Outputs, commands and
    attachments stay pruned — that privacy is the point of the tear-off
    (reference NotaryFlow.Client, NotaryFlow.kt:66-74)."""
    return isinstance(obj, (StateRef, TimeWindow, Party))


@initiating_flow
class NotaryClientFlow(FlowLogic):
    """Client side (reference NotaryFlow.Client, NotaryFlow.kt:33-95)."""

    def __init__(self, stx: SignedTransaction, notary_validating: Optional[bool] = None,
                 notary: Optional[Party] = None):
        self.stx = stx
        # None -> ask the network map (single-notary networks); explicit for
        # multi-notary setups.
        self.notary_validating = notary_validating
        # target override: None routes to stx.notary (every pre-existing
        # call site). The cross-domain notary-change ASSUME leg passes
        # the NEW notary here — the wire tx's `notary` field must keep
        # naming the OLD notary (it is what the consume leg validates).
        self.notary = notary

    def call(self):
        from ..core.transactions.notary_change import (
            NotaryChangeWireTransaction,
        )

        stx = self.stx
        notary = self.notary if self.notary is not None else stx.notary
        if notary is None:
            raise FlowException("transaction has no notary set")
        is_notary_change = isinstance(stx.tx, NotaryChangeWireTransaction)
        self._check_notary_pinning(stx, notary, is_notary_change)
        if is_notary_change:
            # The instigator holds the input states; full pre-notarisation
            # check (signers resolved from input participants).
            stx.check_signatures_are_valid()
            try:
                stx.tx.check_inputs_and_signatures(
                    stx.sigs, self.service_hub.load_state, exclude_notary=True
                )
            except ValueError as exc:
                raise FlowException(str(exc))
        elif stx.inputs:
            # All non-notary signatures must already be present and valid.
            stx.verify_signatures_except(notary.owning_key)
        validating = self.notary_validating
        if validating is None:
            validating = self.service_hub.network_map_cache.is_validating_notary(
                notary
            )
        if validating or is_notary_change:
            # Tear-offs don't apply to notary-change transactions
            # (reference NotaryChangeTransactions.kt: filtering n/a).
            # Piggyback the local dependency chain so the validating
            # notary resolves without fetch dialogues back to us.
            from ..core.flows.library import collect_dependencies

            payload = NotarisationPayload(
                stx, None,
                collect_dependencies(stx, self.service_hub)
                if not is_notary_change else (),
            )
        else:
            # Reveal only what a non-validating notary needs: inputs
            # (StateRef), the time window, and the notary identity (Party).
            # Outputs/commands/attachments stay pruned to hashes — the
            # privacy point of the tear-off (reference NotaryFlow.Client).
            # check_all_inputs_revealed + the GROUP_SIZES leaf give the
            # notary completeness without a full reveal.
            wtx = stx.tx
            ftx = wtx.build_filtered_transaction(notary_tearoff_filter)
            payload = NotarisationPayload(None, ftx)
        try:
            response = yield self.send_and_receive_with_retry(
                notary, payload, NotarisationResponse
            )
        except NotaryException as exc:
            self._reconcile_conflict(exc, stx)
            raise
        sigs = list(response.signatures)
        if not sigs:
            raise NotaryException("notary returned no signatures")
        for sig in sigs:
            # every signer must belong to the notary identity (leaf of a
            # composite cluster key, or the key itself)
            leaf_keys = getattr(
                notary.owning_key, "keys", frozenset({notary.owning_key})
            )
            if sig.by not in leaf_keys and not notary.owning_key.is_fulfilled_by(
                {sig.by}
            ):
                raise NotaryException(
                    f"signature from {sig.by} is not the notary's"
                )
            if not sig.is_valid(stx.id.bytes):
                raise NotaryException("invalid notary signature")
        # COLLECTIVE fulfillment: a composite cluster identity (reference
        # distributed notary service keys) may need several distinct
        # members' signatures to reach its threshold (BFT: f+1)
        if not notary.owning_key.is_fulfilled_by({s.by for s in sigs}):
            raise NotaryException(
                "notary signatures do not fulfil the cluster identity"
            )
        return sigs

    def _check_notary_pinning(self, stx, notary: Party,
                              is_notary_change: bool) -> None:
        """Per-state notary pinning, enforced before anything crosses the
        wire (multi-domain federation: the data model's `notary` field is
        load-bearing). Two violations, both typed WrongNotaryError so the
        hospital wards them fatal instead of retrying a routing decision
        that cannot change:

          * the target notary is not resolvable as a notary on this
            node's (domain-scoped) network map;
          * an input state we hold is pinned to a different notary than
            the one asked to commit it (mixed-notary input set).

        A notary-change tx is the sanctioned exception: its inputs are
        pinned to the OLD notary while the assume leg targets the NEW
        one, so both of the wire tx's notaries are legitimate."""
        cache = getattr(self.service_hub, "network_map_cache", None)
        if cache is not None:
            known = {
                n.owning_key.encoded for n in cache.notary_identities
            }
            if known and notary.owning_key.encoded not in known:
                raise WrongNotaryError(
                    f"{notary.name} does not resolve to a notary on this "
                    "network map"
                )
        load_state = getattr(self.service_hub, "load_state", None)
        if load_state is None:
            return
        allowed = {notary.owning_key.encoded}
        if is_notary_change:
            allowed.add(stx.tx.notary.owning_key.encoded)
            allowed.add(stx.tx.new_notary.owning_key.encoded)
        for ref in stx.tx.inputs:
            try:
                ts = load_state(ref)
            # inputs we don't hold locally: the notary's own server-side
            # check rules on those
            except Exception:  # lint: allow(swallow)
                continue
            pinned = getattr(ts, "notary", None)
            if pinned is None:
                continue
            if pinned.owning_key.encoded not in allowed:
                raise WrongNotaryError(
                    f"input {ref} is pinned to notary {pinned.name}; "
                    f"it cannot be committed by {notary.name}",
                    pinned_notary=pinned,
                )

    def _reconcile_conflict(self, exc: NotaryException, stx) -> None:
        """A conflict verdict is AUTHORITATIVE evidence our inputs are
        spent by a transaction we may not hold (a notary crash between
        commit and reply fails the spender without the vault ever
        recording the spend — the remote soak's notary-kill wedge).
        Flip exactly OUR transaction's conflicted inputs consumed so
        coin selection stops picking provably-dead states; states the
        conflict names that are not our inputs (another party's) are
        left alone."""
        pairs = conflict_consumed_refs(exc)
        if not pairs:
            return
        our_inputs = set(stx.tx.inputs)
        refs = [
            ref for ref, consumer in pairs
            if ref in our_inputs and consumer != stx.id
        ]
        vault = getattr(self.service_hub, "vault_service", None)
        if not refs or vault is None:
            return
        flipped = vault.mark_notary_consumed(refs)
        if flipped:
            eventlog.emit(
                "warning", "notary",
                "vault reconciled notary-conflict spends",
                refs=[repr(r) for r in flipped],
            )


@initiated_by(NotaryClientFlow)
class NotaryServiceFlow(FlowLogic):
    """Server side template (reference NotaryFlow.Service:106-129)."""

    def __init__(self, counterparty: Party):
        self.counterparty = counterparty

    def call(self):
        service: NotaryService = getattr(self.service_hub, "notary_service", None)
        if service is None:
            raise FlowException("this node is not a notary")
        payload = yield self.receive(self.counterparty, NotarisationPayload)
        tx_id, inputs, time_window = yield from self._receive_and_verify(
            service, payload
        )
        service.validate_time_window(time_window)
        # off-pump: a cluster commit can block on consensus (leader
        # election, member outage) and must not starve the messaging
        # pump that delivers the consensus traffic itself
        commit_sigs = yield self.await_blocking(
            lambda: service.commit_input_states(inputs, tx_id)
        )
        # the commit protocol's own signatures (BFT: f+1 replicas) win;
        # otherwise the serving identity signs
        sigs = tuple(commit_sigs) if commit_sigs else (service.sign(tx_id),)
        yield self.send(self.counterparty, NotarisationResponse(sigs))

    def _receive_and_verify(self, service: NotaryService, payload):
        from ..core.transactions.notary_change import (
            NotaryChangeWireTransaction,
        )

        stx = payload.signed_transaction
        if stx is not None and isinstance(stx.tx, NotaryChangeWireTransaction):
            return (yield from self._verify_notary_change(stx, service))
        if service.validating:
            stx = payload.signed_transaction
            if stx is None:
                raise NotaryException(
                    "validating notary requires the full transaction"
                )
            notary_key = stx.notary.owning_key if stx.notary else None
            # Signature hot loop -> the node's CROSS-transaction batcher
            # (verifier service SignatureBatcher): concurrent notarise
            # flows accumulate into one device-worthy flush instead of
            # each paying its own dispatch. The flow parks off-pump while
            # the batch resolves, so other flows keep feeding the batch.
            svc = getattr(
                self.service_hub, "transaction_verifier_service", None
            )
            if (
                svc is not None and stx.sigs
                and os.environ.get("CORDA_TPU_NOTARY_BATCHED", "1") != "0"
            ):
                futs = svc.verify_signatures(stx.signature_check_items())
                # deterministic single-pump networks (MockNetwork) run
                # the await INLINE: nothing else can feed the batch while
                # we block, so waiting out the linger is pure latency
                inline = (
                    not self.state_machine.smm.dispatches_blocking_off_pump
                )

                def _collect():
                    if inline:
                        svc.flush_signatures()
                    return [
                        i for i, f in enumerate(futs) if not f.result(120)
                    ]

                bad = yield self.await_blocking(_collect)
                if bad:
                    raise NotaryException(
                        f"invalid signature(s) at positions {bad} on {stx.id}"
                    )
                stx.check_required_keys_except(notary_key)
            else:
                stx.verify_signatures_except(notary_key)
            resolved = yield from self.sub_flow(
                ResolveTransactionsFlow(
                    stx, self.counterparty,
                    pool=getattr(payload, "dependencies", ()),
                )
            )
            missing_atts = [
                h for h in stx.tx.attachments
                if not self.service_hub.attachments.has_attachment(h)
            ]
            if missing_atts:
                yield from self.sub_flow(
                    FetchAttachmentsFlow(tuple(missing_atts), self.counterparty)
                )
            try:
                stx.verify(self.service_hub, check_sufficient_signatures=False)
            except Exception as exc:
                raise NotaryException(f"transaction invalid: {exc}")
            wtx = stx.tx
            return stx.id, list(wtx.inputs), wtx.time_window
        ftx = payload.filtered_transaction
        if ftx is None:
            raise NotaryException("non-validating notary requires a tear-off")
        ftx.verify()  # Merkle proof against the root = tx id
        # Completeness: a tear-off hiding inputs must not obtain a signature
        # (it would leave the hidden inputs spendable again).
        ftx.check_all_inputs_revealed()
        return ftx.id, list(ftx.inputs), ftx.time_window

    def _verify_notary_change(self, stx, service):
        """Notary-change txs skip contract verification. A VALIDATING
        notary resolves the back-chain and checks every participant
        signed; a NON-validating notary must NOT pull the chain — that
        would expose full historic transaction contents, the exact leak
        the tear-off model exists to prevent — so it checks only
        cryptographic signature validity and commits.
        """
        wtx = stx.tx
        # This service must BE the old notary (the CONSUME leg) or the new
        # notary (the cross-domain ASSUME leg) — anything else is a rogue
        # client having an unrelated notary commit inputs it does not
        # govern (ledger fork).
        me = self.service_hub.my_info
        my_keys = {me.owning_key.encoded, service.identity.owning_key.encoded}
        if wtx.notary.owning_key.encoded in my_keys:
            pass  # consume leg: we are the old notary the inputs pin
        elif wtx.new_notary.owning_key.encoded in my_keys:
            # ASSUME leg of the two-phase cross-domain notary change: we
            # (the NEW notary) durably record the migrated inputs in OUR
            # commit log, so a later double-spend probe of the old refs
            # in THIS domain conflicts instead of silently succeeding.
            # Gate on evidence the old notary already consumed — its
            # cluster identity must fulfil a signature over this tx —
            # or a client could assume-before-consume and tear the
            # exactly-one-owner invariant the protocol exists for.
            if not wtx.notary.owning_key.is_fulfilled_by(
                {s.by for s in stx.sigs}
            ):
                raise NotaryException(
                    f"notary-change assume for {wtx.new_notary.name} lacks "
                    f"the old notary's ({wtx.notary.name}) commit signature"
                )
        else:
            raise NotaryException(
                f"notary change names {wtx.notary.name}, not this notary"
            )
        if not service.validating:
            try:
                stx.check_signatures_are_valid()
            except Exception as exc:
                raise NotaryException(f"notary change invalid: {exc}")
            return stx.id, list(wtx.inputs), None
        yield from self.sub_flow(
            ResolveTransactionsFlow(
                [ref.txhash for ref in wtx.inputs], self.counterparty
            )
        )
        try:
            stx.check_signatures_are_valid()
            wtx.check_inputs_and_signatures(
                stx.sigs, self.service_hub.load_state, exclude_notary=True
            )
        except NotaryException:
            raise
        except Exception as exc:
            raise NotaryException(f"notary change invalid: {exc}")
        return stx.id, list(wtx.inputs), None


# Imported lazily to avoid a cycle at module load; these flows live with
# the other core library flows.
from ..core.flows.library import (  # noqa: E402
    FetchAttachmentsFlow,
    ResolveTransactionsFlow,
)

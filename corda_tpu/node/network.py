"""Node-to-node transports.

`InMemoryMessagingNetwork` is the deterministic multi-node-in-one-process
transport (reference `test-utils/.../InMemoryMessagingNetwork.kt:47-144`):
messages queue globally and are delivered only when pumped, so MockNetwork
tests are fully deterministic; an optional latency/drop injector reorders
the world for failure testing.  `BrokerMessagingService` adapts the durable
broker (corda_tpu.messaging) to the same interface for single-node +
verifier topologies.

Interface (NodeMessagingClient equivalent, reference `Messaging.kt`):
    send(peer: Party, topic: str, payload: bytes)
    add_handler(topic, fn(sender: Party, payload: bytes))
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Deque, Dict, List, Optional, Tuple

from ..core.identity import Party
from ..messaging.broker import BrokerError
from ..utils import eventlog, lockorder, tracing


@dataclass(frozen=True)
class _InFlight:
    sender: Party
    recipient: str  # party name
    topic: str
    payload: bytes
    due_at: float = 0.0  # clock seconds; 0 = deliverable immediately
    # trace context captured at send time (tracing spine): delivered
    # handlers run with this as the current context, so a responder
    # flow's spans chain onto the sender's trace
    traceparent: Optional[str] = None
    # broker-header twin (the session route hint rides here): only read
    # by the OPT-IN flow-lane dispatch — the default in-memory delivery
    # ignores headers exactly as before
    headers: Optional[dict] = None


class InMemoryMessagingNetwork:
    """Deterministic pumped network of named endpoints."""

    def __init__(self):
        self._queue: Deque[_InFlight] = deque()
        self._endpoints: Dict[str, "InMemoryMessaging"] = {}
        self._lock = lockorder.make_lock("InMemoryMessagingNetwork._lock")
        self.sent_count = 0
        self.delivered_count = 0
        # Hook: fn(msg) -> bool keep (False drops the message); used for
        # fault injection in tests.
        self.filter: Optional[Callable[[_InFlight], bool]] = None
        # Hook: fn(msg) called on every delivery (simulation visualisers).
        self.observer: Optional[Callable[[_InFlight], None]] = None
        # Latency injection (reference InMemoryMessagingNetwork
        # LatencyCalculator, `InMemoryMessagingNetwork.kt:139-144`): with
        # both set, a message becomes deliverable at clock()+latency(s, r).
        self.latency: Optional[Callable[[Party, str], float]] = None
        self.clock: Optional[Callable[[], float]] = None
        # Distributed-service addressing (reference: Artemis distributes
        # service-queue messages across cluster members; clients address
        # ONE service identity and any live member serves it): service
        # name -> member endpoint names, delivered round-robin with dead
        # members skipped — which IS the failover sendAndReceiveWithRetry
        # relies on (FlowLogic.kt:98-110).
        self._service_members: Dict[str, List[str]] = {}
        self._service_rr: Dict[str, int] = {}
        # Overload protection: optional per-recipient inbound caps.
        # recipient -> (max_depth, policy); "reject" raises QueueFullError
        # at the sender (backpressure), "drop_oldest" sheds that
        # recipient's oldest undelivered message into `dead_letters`.
        self._caps: Dict[str, Tuple[int, str]] = {}
        self.shed_counts: Dict[str, int] = {}
        self.dead_letters: Deque[_InFlight] = deque(maxlen=256)
        # OPT-IN multi-lane continuation dispatch (docs/perf-system.md
        # round 20): None = today's fully deterministic inline delivery.
        # MockNetwork(flow_lanes=N) arms it for tests that want the laned
        # concurrency shape on the in-memory transport; run() then
        # barriers on lane quiescence so run_network keeps its contract.
        self.lane_executor = None

    def enable_flow_lanes(self, n_lanes: int) -> None:
        """Arm laned delivery of session messages (hinted via the
        x-session-route header) on N lane threads. Test-only opt-in —
        the default in-memory transport stays inline/deterministic."""
        from .flowlanes import FlowLaneExecutor

        if n_lanes and self.lane_executor is None:
            self.lane_executor = FlowLaneExecutor(n_lanes, name="inmem")

    def create_endpoint(self, me: Party) -> "InMemoryMessaging":
        ep = InMemoryMessaging(self, me)
        with self._lock:
            self._endpoints[me.name] = ep
        return ep

    def remove_endpoint(self, name: str) -> None:
        with self._lock:
            self._endpoints.pop(name, None)

    def set_recipient_cap(self, recipient: str, max_depth: Optional[int],
                          policy: str = "reject") -> None:
        """Bound one endpoint's undelivered inbound backlog (the in-memory
        twin of a broker queue depth cap). None/0 removes the bound."""
        if policy not in ("reject", "drop_oldest"):
            raise ValueError(f"unknown shed policy {policy!r}")
        with self._lock:
            if not max_depth:
                self._caps.pop(recipient, None)
            else:
                self._caps[recipient] = (int(max_depth), policy)

    def _enqueue(self, msg: _InFlight) -> None:
        from ..messaging.broker import QueueFullError

        if self.latency is not None and self.clock is not None:
            delay = self.latency(msg.sender, msg.recipient)
            if delay > 0:
                msg = _InFlight(
                    msg.sender, msg.recipient, msg.topic, msg.payload,
                    due_at=self.clock() + delay,
                    traceparent=msg.traceparent,
                    headers=msg.headers,
                )
        with self._lock:
            cap = self._caps.get(msg.recipient)
            if cap is not None:
                # depth is recomputed on demand: disruptions mutate
                # _queue directly, so a counter would drift
                max_depth, policy = cap
                depth = sum(
                    1 for m in self._queue if m.recipient == msg.recipient
                )
                if depth >= max_depth:
                    self.shed_counts[msg.recipient] = (
                        self.shed_counts.get(msg.recipient, 0) + 1
                    )
                    if policy == "reject":
                        raise QueueFullError(
                            f"inbound queue for {msg.recipient} is full "
                            f"({depth}/{max_depth}); send rejected"
                        )
                    for i, m in enumerate(self._queue):
                        if m.recipient == msg.recipient:
                            self.dead_letters.append(m)
                            del self._queue[i]
                            break
            self._queue.append(msg)
            self.sent_count += 1

    def register_service_endpoint(self, service_name: str, member_name: str) -> None:
        with self._lock:
            members = self._service_members.setdefault(service_name, [])
            if member_name not in members:
                members.append(member_name)

    def _resolve_recipient(self, name: str) -> Optional["InMemoryMessaging"]:
        """Direct endpoint, or a live member of a service address."""
        ep = self._endpoints.get(name)
        if ep is not None:
            return ep
        members = self._service_members.get(name)
        if not members:
            return None
        start = self._service_rr.get(name, 0)
        for i in range(len(members)):
            member = members[(start + i) % len(members)]
            ep = self._endpoints.get(member)
            if ep is not None:
                self._service_rr[name] = (start + i + 1) % len(members)
                return ep
        return None

    def queue_depth(self, recipient: Optional[str] = None) -> int:
        """Undelivered messages queued network-wide, or for ONE recipient
        (a node's inbound backlog — the per-node backpressure gauge)."""
        with self._lock:
            if recipient is None:
                return len(self._queue)
            return sum(1 for m in self._queue if m.recipient == recipient)

    def next_due(self) -> Optional[float]:
        """Earliest due_at among undeliverable queued messages (simulation
        drivers advance their TestClock to this when the network idles)."""
        with self._lock:
            future = [m.due_at for m in self._queue if m.due_at > 0]
        return min(future) if future else None

    def pump(self) -> bool:
        """Deliver exactly one deliverable queued message. Returns False
        when idle (messages delayed past the clock don't count as work)."""
        with self._lock:
            if not self._queue:
                return False
            now = self.clock() if self.clock is not None else None
            msg = None
            for i, m in enumerate(self._queue):
                if m.due_at == 0.0 or now is None or m.due_at <= now:
                    msg = m
                    del self._queue[i]
                    break
            if msg is None:
                return False  # everything queued is delayed into the future
            if self.filter is not None and not self.filter(msg):
                return True  # dropped by the injector; work was done
            ep = self._resolve_recipient(msg.recipient)
        if ep is not None:
            ep._deliver(msg.sender, msg.topic, msg.payload,
                        traceparent=msg.traceparent, headers=msg.headers)
            if self.observer is not None:
                self.observer(msg)
        with self._lock:
            self.delivered_count += 1
        return True

    def run(self, max_messages: int = 100_000) -> int:
        """Pump until quiescent (reference runNetwork). Returns deliveries.
        With opt-in flow lanes armed, "quiescent" additionally means every
        lane drained and idle: laned continuations may send new messages,
        so the pump/lane barrier loops until BOTH are empty."""
        n = 0
        while True:
            while self.pump():
                n += 1
                if n > max_messages:
                    raise RuntimeError(
                        "network did not quiesce (message storm?)"
                    )
            if self.lane_executor is None:
                return n
            if not self.lane_executor.quiesce():
                # a wedged continuation must fail the run like the
                # message-storm guard does, not spin here forever
                raise RuntimeError(
                    "flow lanes did not quiesce (wedged continuation?): "
                    f"{self.lane_executor.stats()}"
                )
            if self.queue_depth() == 0 and self.lane_executor.idle():
                return n


class InMemoryMessaging:
    """One node's endpoint on the in-memory network."""

    def __init__(self, network: InMemoryMessagingNetwork, me: Party):
        self.network = network
        self.me = me
        self._handlers: Dict[str, List[Callable]] = {}
        self.running = True

    def send(self, peer: Party, topic: str, payload: bytes,
             headers: Optional[dict] = None) -> None:
        # `headers` ride along for the OPT-IN lane dispatch (the session
        # route hint); the default inline delivery never reads them
        self.network._enqueue(
            _InFlight(self.me, peer.name, topic, payload,
                      traceparent=tracing.current_traceparent(),
                      headers=headers)
        )

    def add_handler(self, topic: str, fn: Callable[[Party, bytes], None]) -> None:
        self._handlers.setdefault(topic, []).append(fn)

    def queue_depth(self) -> int:
        """This endpoint's inbound backlog on the shared network queue."""
        return self.network.queue_depth(self.me.name)

    def _deliver(self, sender: Party, topic: str, payload: bytes,
                 traceparent: Optional[str] = None,
                 headers: Optional[dict] = None) -> None:
        if not self.running:
            return
        lanes = self.network.lane_executor
        if lanes is not None:
            # opt-in laned dispatch: hinted (session) messages run their
            # handlers on the lane owning the hint's flow id; everything
            # else stays inline on the pumping thread
            from .flowlanes import lane_key
            from .session import ROUTE_HINT_HEADER

            hint = (headers or {}).get(ROUTE_HINT_HEADER)
            if hint:
                try:
                    lanes.submit(
                        lane_key(hint),
                        lambda: self._dispatch(sender, topic, payload,
                                               traceparent),
                    )
                    return
                except RuntimeError:
                    pass  # lanes stopped mid-teardown: dispatch inline
        self._dispatch(sender, topic, payload, traceparent)

    def _dispatch(self, sender: Party, topic: str, payload: bytes,
                  traceparent: Optional[str] = None) -> None:
        ctx = tracing.SpanContext.from_traceparent(traceparent)
        if ctx is None:
            for fn in self._handlers.get(topic, []):
                fn(sender, payload)
            return
        # traced message: one delivery span per hop, active around the
        # handlers so responder flow spans chain under it
        tracer = tracing.get_tracer()
        sp = tracer.start_span(
            "p2p.deliver", parent=ctx, topic=topic, to=self.me.name,
        )
        with tracing.activate(sp.context):
            try:
                for fn in self._handlers.get(topic, []):
                    fn(sender, payload)
            finally:
                sp.finish()

    def stop(self) -> None:
        self.running = False
        self.network.remove_endpoint(self.me.name)


class BrokerMessagingService:
    """Same interface over the durable Broker: each node gets a queue
    `p2p.inbound.{name}`; a consumer thread dispatches to topic handlers.
    Used for single-process durable deployments and the verifier topology."""

    #: tells the SMM to run flow work on an executor: flow bodies may
    #: block (notary cluster commits) and must not wedge the pump thread
    ASYNC_FLOW_DISPATCH = True

    def __init__(self, broker, me: Party, bridges=None,
                 queue_suffix: str = ""):
        """`bridges`: optional BridgeManager — when it has a route for a
        peer, outbound messages go to its store-and-forward queue instead
        of a local inbound queue (cross-process P2P).

        `queue_suffix`: consume `p2p.inbound.<name><suffix>` instead of
        the bare inbound queue — the shard supervisor (node/shardhost.py)
        takes the bare queue for its router and hands this service the
        ".sup" leg; workers consume their ".w<k>" legs the same way."""
        from ..core.serialization.codec import deserialize, serialize

        self._serialize = serialize
        self._deserialize = deserialize
        self.broker = broker
        self.me = me
        self.bridges = bridges
        self.queue_name = f"p2p.inbound.{me.name}{queue_suffix}"
        # RemoteBroker (worker processes) has no journal attribute: the
        # owning broker process decides durability server-side
        broker.create_queue(
            self.queue_name,
            durable=getattr(broker, "_journal_dir", None) is not None,
        )
        self._bound_queue(self.queue_name)
        self._handlers: Dict[str, List[Callable]] = {}
        # Set by AbstractNode to the SMM registry: per-topic handler
        # timers (P2P.Handle.<topic>) locate where node wall-time goes —
        # the kernel->system profiling seam (round-2 VERDICT weak #3).
        self.metrics = None
        self._stop = threading.Event()
        # Multi-lane flow executor (docs/perf-system.md round 20):
        # session messages — identified header-only by the x-session-route
        # hint every session sender stamps — dispatch their handler chain
        # onto a lane thread keyed by flow id, so the pump's next
        # GIL-releasing native drain overlaps Python flow execution.
        # A laned message is acked only AFTER its handlers ran (the lane
        # reports completions back to the pump thread, which acks them on
        # its next cycle): the at-least-once contract of the inline path
        # is unchanged — a crash mid-continuation leaves the message
        # unacked and the broker redelivers. CORDA_TPU_FLOW_LANES=0
        # restores today's fully-inline dispatch byte-identically.
        from .flowlanes import FlowLaneExecutor, default_lanes

        n_lanes = default_lanes()
        self._lanes = (
            FlowLaneExecutor(n_lanes, name=me.name) if n_lanes > 0 else None
        )
        self._consumer = broker.create_consumer(self.queue_name)
        self._extra_threads: List[threading.Thread] = []
        self._extra_consumers: List = []
        from ..utils.profiling import maybe_profiled

        self._thread = threading.Thread(
            target=maybe_profiled(self._consume, "p2p"),
            name=f"p2p-{me.name}", daemon=True,
        )
        # NOT started here: the pump must only run once the node has
        # installed its flow handlers (AbstractNode.start), otherwise a
        # message arriving in the startup window is dispatched into a void
        # and acked away — observed as a lost broadcast when a node
        # restarts while peers' bridges are retrying. Inbound messages
        # wait safely in the (durable) queue until start().

    #: default inbound-queue depth cap (overload protection): a 5x burst
    #: that outruns the pump parks in a BOUNDED queue and overflow
    #: rejects the sender (bridges retry; local senders see
    #: QueueFullError) instead of growing RSS without bound.
    #: CORDA_TPU_P2P_QUEUE_MAX=0 removes the bound.
    P2P_QUEUE_MAX = 10_000

    def _bound_queue(self, queue: str) -> None:
        max_depth = int(
            os.environ.get("CORDA_TPU_P2P_QUEUE_MAX", self.P2P_QUEUE_MAX)
        )
        # ingest queues use reject-new: P2P session traffic must never be
        # silently dropped mid-conversation (the sender's bridge holds it
        # durably and retries); RemoteBroker transports have no bound API
        # — the owning broker process bounds server-side
        if max_depth > 0 and hasattr(self.broker, "set_queue_bound"):
            self.broker.set_queue_bound(queue, max_depth, "reject")

    def start(self) -> None:
        if not self._thread.is_alive():
            self._thread.start()
        for t in self._extra_threads:
            if not t.is_alive():
                t.start()

    def also_serve(self, service_name: str) -> None:
        """Consume a SECOND inbound queue addressed to a service identity
        (e.g. a notary cluster's composite Party): peers' bridges deliver
        to p2p.inbound.<cluster name> on this member's broker, and those
        messages dispatch through the same topic handlers. Call before
        start()."""
        queue = f"p2p.inbound.{service_name}"
        self.broker.create_queue(
            queue,
            durable=getattr(self.broker, "_journal_dir", None) is not None,
        )
        self._bound_queue(queue)
        consumer = self.broker.create_consumer(queue)
        self._extra_consumers.append(consumer)
        thread = threading.Thread(
            target=lambda: self._consume_from(consumer),
            name=f"p2p-svc-{service_name}", daemon=True,
        )
        self._extra_threads.append(thread)
        if self._thread.is_alive():  # started already: bring it up now
            thread.start()

    def send(self, peer: Party, topic: str, payload: bytes,
             headers: Optional[dict] = None) -> None:
        extra = headers
        headers = {"topic": topic, "sender": self.me.name,
                   "sender_key": self.me.owning_key.encoded.hex()}
        if extra:
            headers.update(extra)
        traceparent = tracing.current_traceparent()
        if traceparent is not None:
            headers[tracing.TRACEPARENT_HEADER] = traceparent
        if (
            self.bridges is not None
            and peer.name != self.me.name
            and self.bridges.route_for(peer.name) is not None
        ):
            # Remote peer: durable outbound queue + bridge forwarder
            # (ArtemisMessagingServer.deployBridge semantics).
            self.broker.send(
                self.bridges.outbound_queue(peer.name), payload, headers
            )
            return
        self.broker.send(f"p2p.inbound.{peer.name}", payload, headers)

    def add_handler(self, topic: str, fn: Callable[[Party, bytes], None]) -> None:
        self._handlers.setdefault(topic, []).append(fn)

    def queue_depth(self) -> int:
        """Messages waiting in this node's inbound broker queue(s) —
        pump-thread backpressure in one number (a depth that climbs while
        consumers are live means the handlers can't keep up)."""
        depth = self.broker.message_count(self.queue_name)
        for c in self._extra_consumers:
            q = getattr(c, "_queue", None)
            if q is not None:
                depth += self.broker.message_count(q.name)
        return depth

    def _consume(self) -> None:
        self._consume_from(self._consumer)

    #: max messages drained into one lock acquisition by the pump
    PUMP_BATCH = 32

    def _handle_msg(self, msg, payload=None) -> None:
        """Dispatch ONE broker message through the topic handlers —
        runs inline on the pump (default) or on a flow lane (hinted
        session messages when CORDA_TPU_FLOW_LANES > 0). `payload`
        overrides msg.payload for laned dispatch, whose bytes were
        snapshotted at handoff (the zero-copy drain arena only lives
        until the pump's next cycle)."""
        from ..core.crypto.keys import SchemePublicKey

        topic = msg.headers.get("topic", "")
        sender = Party(
            msg.headers.get("sender", "?"),
            SchemePublicKey(
                "EDDSA_ED25519_SHA512",
                bytes.fromhex(msg.headers.get("sender_key", "")),
            )
            if msg.headers.get("sender_key")
            else None,
        )
        body = msg.payload if payload is None else payload
        metrics = self.metrics
        t0 = time.perf_counter() if metrics is not None else 0.0
        ctx = tracing.SpanContext.from_traceparent(
            msg.headers.get(tracing.TRACEPARENT_HEADER)
        )
        sp = (
            tracing.get_tracer().start_span(
                "p2p.deliver", parent=ctx, topic=topic,
                to=self.me.name,
            )
            if ctx is not None else tracing.NOOP_SPAN
        )
        with tracing.activate(sp.context):
            for fn in self._handlers.get(topic, []):
                try:
                    fn(sender, body)
                except Exception as exc:
                    # handler errors must not kill the pump, but
                    # a silently-dropped delivery is exactly the
                    # evidence a flow hang investigation needs
                    eventlog.emit(
                        "error", "p2p",
                        f"handler error on {topic}",
                        error=f"{type(exc).__name__}: {exc}",
                        sender=str(sender),
                    )
            sp.finish()
        if metrics is not None:
            metrics.timer(f"P2P.Handle.{topic}").update(
                time.perf_counter() - t0
            )

    @staticmethod
    def _drain_completions(consumer, lane_done, in_lanes) -> None:
        """Ack every lane-completed message (pump thread only: consumers
        are single-threaded objects — RemoteConsumer shares one socket —
        so lanes report completions here instead of acking directly)."""
        done = []
        while True:
            try:
                done.append(lane_done.popleft())
            except IndexError:
                break
        if not done:
            return
        in_lanes[0] -= len(done)
        try:
            if hasattr(consumer, "ack_many"):
                consumer.ack_many(done)
            else:  # RemoteConsumer: per-message one-way acks
                for m in done:
                    consumer.ack(m)
        except BrokerError as exc:
            # consumer closed mid-shutdown: the broker requeued these
            # unacked — redelivery + receiver dedup absorb the overlap
            eventlog.emit(
                "info", "p2p", "lane completions acked after close",
                error=str(exc), count=len(done),
            )

    def _consume_from(self, consumer) -> None:
        from .flowlanes import lane_key
        from .session import ROUTE_HINT_HEADER

        # local consumers batch under one broker-lock acquisition; remote
        # consumers (RemoteConsumer) pipeline on the wire already and
        # keep the one-at-a-time surface
        batched = hasattr(consumer, "receive_many")
        lanes = self._lanes
        lane_done: Deque = deque()  # lane threads append; pump pops
        in_lanes = [0]  # dispatched-not-yet-acked, pump-thread-local
        while not self._stop.is_set():
            if lanes is not None:
                self._drain_completions(consumer, lane_done, in_lanes)
            if batched:
                batch = consumer.receive_many(self.PUMP_BATCH, timeout=0.2)
            else:
                msg = consumer.receive(timeout=0.2)
                batch = [msg] if msg is not None else []
            if not batch:
                continue
            inline_done = []
            for msg in batch:
                hint = (
                    msg.headers.get(ROUTE_HINT_HEADER)
                    if lanes is not None else None
                )
                if hint:
                    # snapshot: a zero-copy arena view must not escape
                    # this drain cycle (PR 11 arena lifetime rules)
                    payload = (
                        msg.payload if type(msg.payload) is bytes
                        else bytes(msg.payload)
                    )

                    def task(msg=msg, payload=payload):
                        try:
                            self._handle_msg(msg, payload)
                        finally:
                            lane_done.append(msg)

                    try:
                        lanes.submit(lane_key(hint), task)
                        in_lanes[0] += 1
                        continue
                    except RuntimeError:
                        pass  # lanes stopped: dispatch inline below
                self._handle_msg(msg)
                inline_done.append(msg)
            try:
                if inline_done and batched:
                    consumer.ack_many(inline_done)
                else:
                    for m in inline_done:
                        consumer.ack(m)
            except BrokerError as exc:
                if not self._stop.is_set():
                    raise
                # shutdown race: stop() closed the consumer between the
                # receive and this ack — close() already requeued the
                # batch, redelivery + dedup absorb it
                eventlog.emit(
                    "info", "p2p", "ack raced shutdown close",
                    error=str(exc), count=len(inline_done),
                )
        # stopping: in-flight laned continuations get a bounded window to
        # complete so their messages ack; whatever stays unacked is
        # requeued by consumer.close() and redelivered (at-least-once)
        if lanes is not None:
            deadline = time.monotonic() + 5.0
            while in_lanes[0] > 0 and time.monotonic() < deadline:
                self._drain_completions(consumer, lane_done, in_lanes)
                if in_lanes[0] > 0:
                    time.sleep(0.01)
            self._drain_completions(consumer, lane_done, in_lanes)

    def stop(self) -> None:
        self._stop.set()
        if self._lanes is not None:
            # drain first: in-flight continuations complete and their
            # messages ack through the pump's exit path; anything the
            # timeout abandons stays unacked and redelivers after the
            # consumer close below requeues it
            self._lanes.stop(drain=True, timeout=10)
            if self._thread.ident is not None:
                self._thread.join(timeout=6)
            for t in self._extra_threads:
                if t.ident is not None:
                    t.join(timeout=6)
        self._consumer.close()
        for c in self._extra_consumers:
            c.close()
        if self._thread.ident is not None:  # pump may never have started
            self._thread.join(timeout=2)
        for t in self._extra_threads:
            if t.ident is not None:
                t.join(timeout=2)

"""Node health and readiness aggregation (the /healthz + /readyz model).

Kubernetes-style split: **liveness** (`/healthz`) answers "is the process
sound enough to keep sending traffic to" — 200 whenever the node is
serving and every registered component check passes, 503 with a JSON
cause while starting or draining; **readiness** (`/readyz`) answers "may
traffic start" — 503 until the node's start sequence completed AND every
component marked `readiness=True` passes (broker reachable, verifier
backend initialized, notary/raft leader known, thread pools not
saturated).

Checks are zero-arg callables returning a detail dict (truthy `ok` key
optional — a plain dict means healthy); raising marks the component
unhealthy with the exception as the cause. Check bodies run on the ops
server's request threads: they must be cheap reads (queue lengths,
flags), never blocking probes.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple
from ..utils import lockorder

#: lifecycle states, in order
STARTING, SERVING, DRAINING, STOPPED = (
    "starting", "serving", "draining", "stopped",
)


class SustainedBreach:
    """Debounce for degradation checks: a condition only counts as
    degraded after it has held continuously for `for_s` seconds.

    One queue-depth spike at the instant a probe lands must not flip
    /readyz (the load balancer would yank a healthy node); a backlog
    that STAYS saturated across the window is real degradation. Recovery
    clears immediately — the hysteresis lives in the overload state
    machine, not here."""

    def __init__(self, for_s: float, clock: Callable[[], float] = time.time):
        self.for_s = float(for_s)
        self._clock = clock
        self._since: Optional[float] = None

    def observe(self, breached: bool) -> bool:
        """Feed one reading; returns True once the breach is sustained."""
        if not breached:
            self._since = None
            return False
        now = self._clock()
        if self._since is None:
            self._since = now
        return (now - self._since) >= self.for_s

    @property
    def breached_for_s(self) -> float:
        """How long the current breach has held (0 when clear)."""
        return 0.0 if self._since is None else self._clock() - self._since


class HealthTracker:
    """Per-node lifecycle state + named component checks."""

    def __init__(self) -> None:
        self._lock = lockorder.make_lock("HealthTracker._lock")
        self._state = STARTING
        self._state_since = time.time()
        #: name -> (check fn, counts toward readiness, counts toward liveness)
        self._checks: Dict[
            str, Tuple[Callable[[], Optional[dict]], bool, bool]
        ] = {}

    # -- lifecycle ----------------------------------------------------------

    @property
    def state(self) -> str:
        return self._state

    def set_state(self, state: str) -> None:
        with self._lock:
            if state != self._state:
                self._state = state
                self._state_since = time.time()

    def mark_serving(self) -> None:
        self.set_state(SERVING)

    def mark_draining(self) -> None:
        self.set_state(DRAINING)

    def mark_stopped(self) -> None:
        self.set_state(STOPPED)

    # -- checks -------------------------------------------------------------

    def register(self, name: str, check: Callable[[], Optional[dict]],
                 readiness: bool = True, liveness: bool = True) -> None:
        """Idempotent by name: a restarted service re-registering its
        check replaces the stale closure (same rule as gauge
        re-registration in MetricRegistry).

        `readiness`: failing flips /readyz to 503. `liveness`: failing
        flips /healthz to 503. A check with liveness=False is an
        OVERLOAD-class signal: the node stops ADMITTING (/readyz 503,
        the load balancer's cue) while /healthz stays 200 with the
        component detail — shedding load is the process working, not the
        process sick, and a liveness-triggered restart would throw away
        exactly the in-flight work the shed protects."""
        with self._lock:
            self._checks[name] = (check, readiness, liveness)

    def _run_checks(self, readiness_only: bool) -> Tuple[bool, Dict]:
        """Runs every relevant check. In readiness mode only
        readiness-scoped checks run and all of them aggregate; in
        liveness mode ALL checks run for detail, but only liveness-scoped
        ones aggregate into the ok verdict."""
        with self._lock:
            checks = sorted(self._checks.items())
        all_ok = True
        details: Dict[str, dict] = {}
        for name, (fn, for_readiness, for_liveness) in checks:
            if readiness_only and not for_readiness:
                continue
            try:
                detail = fn() or {}
                ok = bool(detail.pop("ok", True))
            except Exception as exc:  # a broken check IS an unhealthy component
                detail, ok = {"error": f"{type(exc).__name__}: {exc}"}, False
            details[name] = {"ok": ok, **detail}
            if readiness_only or for_liveness:
                all_ok = all_ok and ok
        return all_ok, details

    # -- the two probe views ------------------------------------------------

    def _base(self) -> Dict:
        with self._lock:
            return {
                "state": self._state,
                "state_age_s": round(time.time() - self._state_since, 3),
            }

    def healthz(self) -> Tuple[int, Dict]:
        """(http status, body): 200 only while SERVING with all
        component checks passing; starting/draining/stopped are 503 with
        the lifecycle state as the cause."""
        body = self._base()
        ok, details = self._run_checks(readiness_only=False)
        body["checks"] = details
        if self._state != SERVING:
            body["status"] = "unavailable"
            body["cause"] = f"node is {self._state}"
            return 503, body
        if not ok:
            failing = sorted(n for n, d in details.items() if not d["ok"])
            body["status"] = "unhealthy"
            body["cause"] = "failing checks: " + ", ".join(failing)
            return 503, body
        body["status"] = "ok"
        return 200, body

    def readyz(self) -> Tuple[int, Dict]:
        """(http status, body): 200 once serving and every readiness
        check passes — the gate a load balancer / driver polls before
        routing traffic."""
        body = self._base()
        ok, details = self._run_checks(readiness_only=True)
        body["checks"] = details
        if self._state != SERVING or not ok:
            not_ready: List[str] = sorted(
                n for n, d in details.items() if not d["ok"]
            )
            body["status"] = "not-ready"
            body["cause"] = (
                f"node is {self._state}" if self._state != SERVING
                else "failing checks: " + ", ".join(not_ready)
            )
            return 503, body
        body["status"] = "ready"
        return 200, body

"""Node health and readiness aggregation (the /healthz + /readyz model).

Kubernetes-style split: **liveness** (`/healthz`) answers "is the process
sound enough to keep sending traffic to" — 200 whenever the node is
serving and every registered component check passes, 503 with a JSON
cause while starting or draining; **readiness** (`/readyz`) answers "may
traffic start" — 503 until the node's start sequence completed AND every
component marked `readiness=True` passes (broker reachable, verifier
backend initialized, notary/raft leader known, thread pools not
saturated).

Checks are zero-arg callables returning a detail dict (truthy `ok` key
optional — a plain dict means healthy); raising marks the component
unhealthy with the exception as the cause. Check bodies run on the ops
server's request threads: they must be cheap reads (queue lengths,
flags), never blocking probes.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

#: lifecycle states, in order
STARTING, SERVING, DRAINING, STOPPED = (
    "starting", "serving", "draining", "stopped",
)


class HealthTracker:
    """Per-node lifecycle state + named component checks."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._state = STARTING
        self._state_since = time.time()
        #: name -> (check fn, counts toward readiness)
        self._checks: Dict[str, Tuple[Callable[[], Optional[dict]], bool]] = {}

    # -- lifecycle ----------------------------------------------------------

    @property
    def state(self) -> str:
        return self._state

    def set_state(self, state: str) -> None:
        with self._lock:
            if state != self._state:
                self._state = state
                self._state_since = time.time()

    def mark_serving(self) -> None:
        self.set_state(SERVING)

    def mark_draining(self) -> None:
        self.set_state(DRAINING)

    def mark_stopped(self) -> None:
        self.set_state(STOPPED)

    # -- checks -------------------------------------------------------------

    def register(self, name: str, check: Callable[[], Optional[dict]],
                 readiness: bool = True) -> None:
        """Idempotent by name: a restarted service re-registering its
        check replaces the stale closure (same rule as gauge
        re-registration in MetricRegistry)."""
        with self._lock:
            self._checks[name] = (check, readiness)

    def _run_checks(self, readiness_only: bool) -> Tuple[bool, Dict]:
        with self._lock:
            checks = sorted(self._checks.items())
        all_ok = True
        details: Dict[str, dict] = {}
        for name, (fn, for_readiness) in checks:
            if readiness_only and not for_readiness:
                continue
            try:
                detail = fn() or {}
                ok = bool(detail.pop("ok", True))
            except Exception as exc:  # a broken check IS an unhealthy component
                detail, ok = {"error": f"{type(exc).__name__}: {exc}"}, False
            details[name] = {"ok": ok, **detail}
            all_ok = all_ok and ok
        return all_ok, details

    # -- the two probe views ------------------------------------------------

    def _base(self) -> Dict:
        with self._lock:
            return {
                "state": self._state,
                "state_age_s": round(time.time() - self._state_since, 3),
            }

    def healthz(self) -> Tuple[int, Dict]:
        """(http status, body): 200 only while SERVING with all
        component checks passing; starting/draining/stopped are 503 with
        the lifecycle state as the cause."""
        body = self._base()
        ok, details = self._run_checks(readiness_only=False)
        body["checks"] = details
        if self._state != SERVING:
            body["status"] = "unavailable"
            body["cause"] = f"node is {self._state}"
            return 503, body
        if not ok:
            failing = sorted(n for n, d in details.items() if not d["ok"])
            body["status"] = "unhealthy"
            body["cause"] = "failing checks: " + ", ".join(failing)
            return 503, body
        body["status"] = "ok"
        return 200, body

    def readyz(self) -> Tuple[int, Dict]:
        """(http status, body): 200 once serving and every readiness
        check passes — the gate a load balancer / driver polls before
        routing traffic."""
        body = self._base()
        ok, details = self._run_checks(readiness_only=True)
        body["checks"] = details
        if self._state != SERVING or not ok:
            not_ready: List[str] = sorted(
                n for n, d in details.items() if not d["ok"]
            )
            body["status"] = "not-ready"
            body["cause"] = (
                f"node is {self._state}" if self._state != SERVING
                else "failing checks: " + ", ".join(not_ready)
            )
            return 503, body
        body["status"] = "ready"
        return 200, body

"""Distributed-service cluster identity (reference
`node/.../utilities/ServiceIdentityGenerator.kt` + the composite service
keys Raft/BFT notary clusters advertise).

A notary cluster presents ONE identity to clients: a `CompositeKey` over
the members' keys with a threshold (Raft: 1 — any leader's signature
settles it; BFT: f+1 — enough distinct replicas must co-sign). Clients
address the cluster Party and validate the returned signature set
*collectively* against the composite key.
"""
from __future__ import annotations

import json
import os
from typing import Optional, Sequence

from ..core.crypto.composite import CompositeKey
from ..core.crypto.keys import PublicKey
from ..core.identity import Party


def generate_service_identity(
    service_name: str,
    member_keys: Sequence[PublicKey],
    threshold: Optional[int] = None,
) -> Party:
    """Composite cluster Party over the members' keys.

    threshold defaults to 1 (CFT semantics: any current leader's signature
    is authoritative, reference RaftUniquenessProvider clusters); BFT
    clusters pass f+1.
    """
    if not member_keys:
        raise ValueError("a cluster needs at least one member")
    threshold = 1 if threshold is None else threshold
    if not (1 <= threshold <= len(member_keys)):
        raise ValueError(
            f"threshold {threshold} invalid for {len(member_keys)} members"
        )
    builder = CompositeKey.Builder()
    for key in member_keys:
        builder.add_key(key, weight=1)
    return Party(service_name, builder.build(threshold))


def write_service_identity(party: Party, out_dir: str) -> str:
    """Persist the cluster identity for distribution to members/clients
    (reference ServiceIdentityGenerator writes cluster keys to disk)."""
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "service-identity.json")
    with open(path, "w") as fh:
        json.dump(
            {
                "name": party.name,
                "composite_key": party.owning_key.encoded.hex(),
            },
            fh,
        )
    return path


def load_service_identity(path: str) -> Party:
    from ..core.crypto.composite import decode_composite_key

    with open(path) as fh:
        data = json.load(fh)
    return Party(
        data["name"], decode_composite_key(bytes.fromhex(data["composite_key"]))
    )

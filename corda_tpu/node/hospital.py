"""Flow hospital: automatic checkpoint-replay retry for transient flow
failures, plus a bounded dead-letter ward for fatal ones.

Reference inspiration: the staff/diagnosis model of Corda's
`StaffedFlowHospital` (flows that error are "admitted", diagnosed, and
either scheduled for a retry from their last checkpoint or kept for the
operator), rebuilt on this repo's deterministic-replay checkpoints:

  * A flow failing with a TRANSIENT error (verifier deadline exhaustion,
    an explicit `TransientFlowError`, a notary reporting itself
    unavailable) is re-admitted automatically: after a capped
    exponential backoff its checkpoint is replayed into a fresh
    FlowStateMachine that reuses the SAME flow id and — crucially — the
    SAME result Future the original caller holds, so an RPC client
    blocked on `flow_result` simply sees the retry succeed.
  * A flow failing FATALLY (contract violation, any FlowException, an
    unclassified bug) keeps today's behavior — the caller's future gets
    the exception immediately — and additionally lands in the ward with
    its checkpoint blob captured, visible via `node_hospital()` and
    `GET /hospital`, retryable via `retry_flow()` and dischargeable via
    `kill_flow()`. Kills are never retried or warded.

The transient set is deliberately NARROW by default: retrying an error
that is actually deterministic turns one failure into max_retries
failures plus latency, and retrying session errors can leave a flow
parked on a peer that will never answer. Deployments widen it via
`FlowHospital.transient_predicates`.

Knobs: CORDA_TPU_HOSPITAL=0 disables auto-retry (the ward still
records), CORDA_TPU_HOSPITAL_MAX_RETRIES (default 3),
CORDA_TPU_HOSPITAL_BACKOFF_S (base, default 0.1),
CORDA_TPU_HOSPITAL_BACKOFF_CAP_S (default 5), CORDA_TPU_HOSPITAL_WARD_MAX
(default 256).
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional

from ..core.flows.api import (
    FlowException,
    FlowKilledException,
    encode_flow_exception,
)
from ..utils import eventlog, lockorder, timerwheel
from ..verifier.failover import backoff_delay
from ..verifier.service import VerificationTimeoutError


class TransientFlowError(Exception):
    """Marker: a failure the raiser KNOWS is worth a checkpoint-replay
    retry (an infrastructure hiccup, not a logic error). Flow bodies and
    service seams raise it (or a subclass) to opt into hospital
    re-admission."""


def _notary_unavailable(exc: BaseException) -> bool:
    """NotaryException whose error text reports an infrastructure outage
    (not a conflict / validation verdict, which must stay final)."""
    from .notary import NotaryException

    if not isinstance(exc, NotaryException):
        return False
    text = str(getattr(exc, "error", "") or exc).lower()
    return "unavailable" in text or "timed out" in text


class FlowHospital:
    """Per-node failure triage attached to one StateMachineManager."""

    def __init__(self, smm, enabled: Optional[bool] = None,
                 max_retries: Optional[int] = None,
                 backoff_s: Optional[float] = None,
                 backoff_cap_s: Optional[float] = None,
                 ward_max: Optional[int] = None,
                 rng=None):
        env = os.environ
        # jitter source for the retry backoff: a SHARED outage (notary
        # unavailable across hundreds of flows at once) admits the whole
        # herd in the same instant, and un-jittered exponential backoff
        # would re-release it in the same instant too — re-creating the
        # overload the retry was meant to ride out. backoff_delay scales
        # each delay by [0.5, 1.0) from this rng (seedable for tests).
        import random as _random

        self.rng = rng if rng is not None else _random.Random()
        self.smm = smm
        self.enabled = (
            enabled if enabled is not None
            else env.get("CORDA_TPU_HOSPITAL", "1") != "0"
        )
        self.max_retries = (
            max_retries if max_retries is not None
            else int(env.get("CORDA_TPU_HOSPITAL_MAX_RETRIES", 3))
        )
        self.backoff_s = (
            backoff_s if backoff_s is not None
            else float(env.get("CORDA_TPU_HOSPITAL_BACKOFF_S", 0.1))
        )
        self.backoff_cap_s = (
            backoff_cap_s if backoff_cap_s is not None
            else float(env.get("CORDA_TPU_HOSPITAL_BACKOFF_CAP_S", 5.0))
        )
        self.ward_max = (
            ward_max if ward_max is not None
            else int(env.get("CORDA_TPU_HOSPITAL_WARD_MAX", 256))
        )
        #: extra classifiers: any predicate saying True makes an error
        #: transient (checked before the default fatal verdict)
        self.transient_predicates: List[Callable[[BaseException], bool]] = [
            _notary_unavailable,
        ]
        self._lock = lockorder.make_rlock("FlowHospital._lock")
        self._closed = False
        #: flow_id -> recovery record for flows awaiting / mid re-admission
        self._recovering: Dict[str, dict] = {}
        #: flow_id -> ward record (bounded, insertion-ordered for eviction)
        self._ward: "OrderedDict[str, dict]" = OrderedDict()
        self._executor = None  # lazy single-thread readmission executor
        m = smm.metrics
        self.retries = m.counter("Hospital.Retries")
        self.recovered = m.counter("Hospital.Recovered")
        self.warded = m.counter("Hospital.Warded")
        m.gauge("Hospital.Recovering", lambda: len(self._recovering))
        m.gauge("Hospital.WardSize", lambda: len(self._ward))

    # -- classification ------------------------------------------------------

    def classify(self, exc: BaseException) -> str:
        """'transient' (retry from checkpoint) or 'fatal' (ward)."""
        if isinstance(exc, FlowKilledException):
            return "fatal"  # a kill is a decision, not a failure
        if isinstance(exc, (TransientFlowError, VerificationTimeoutError)):
            return "transient"
        if getattr(exc, "transient", False):
            # typed opt-in (NotaryUnavailableError and friends): the
            # raiser KNOWS this is an infrastructure verdict, so
            # retryability does not hang on message wording
            return "transient"
        for pred in self.transient_predicates:
            try:
                if pred(exc):
                    return "transient"
            except Exception:
                pass
        return "fatal"

    # -- admission (called from FlowStateMachine._fail) ----------------------

    def consider(self, fsm, exc: BaseException) -> Optional[float]:
        """Admission decision for a failing flow: a backoff delay when
        the hospital will re-admit it (the fail path then STOPS — the
        caller's future stays pending), or None to let it fail."""
        if not self.enabled or self._closed:
            # after close() (node stopping) a late transient failure must
            # fail normally — re-admitting would strand the caller's
            # future and replay the flow against torn-down services
            return None
        if self.classify(exc) != "transient":
            return None
        with self._lock:
            rec = self._recovering.get(fsm.flow_id)
            attempts = rec["attempts"] if rec else 0
            if attempts >= self.max_retries:
                # exhausted: release the record; the fail path wards it
                self._recovering.pop(fsm.flow_id, None)
                return None
            attempts += 1
            delay = backoff_delay(
                attempts, base_s=self.backoff_s, cap_s=self.backoff_cap_s,
                rng=self.rng,
            )
            self._recovering[fsm.flow_id] = {
                "flow_id": fsm.flow_id,
                "flow_name": fsm.flow.flow_name(),
                "attempts": attempts,
                "error": f"{type(exc).__name__}: {exc}",
                "future": fsm.result,
                "old_fsm": fsm,
                "is_responder": fsm.is_responder,
                "next_retry_at": time.time() + delay,
                "timer": None,
                "killed": False,
            }
            self._recovering[fsm.flow_id]["timer"] = timerwheel.call_later(
                delay, lambda: self._on_retry_timer(fsm.flow_id)
            )
        self.retries.inc()
        eventlog.emit(
            "warning", "hospital", "flow admitted for retry",
            flow=fsm.flow.flow_name(), flow_id=fsm.flow_id,
            attempt=attempts, backoff_s=round(delay, 3),
            error=f"{type(exc).__name__}: {exc}",
        )
        return delay

    def record_fatal(self, fsm, exc: BaseException) -> None:
        """Ward a fatally-failing flow (called BEFORE the checkpoint is
        dropped so the blob can be captured for retry_flow)."""
        if isinstance(exc, FlowKilledException):
            # kills are never warded, but a killed RETRY ATTEMPT must
            # still drop its recovery record — otherwise discharge()
            # later reports the kill as "flow recovered"
            with self._lock:
                self._recovering.pop(fsm.flow_id, None)
            return
        blob = None
        try:
            blob = self.smm.checkpoint_storage.get(fsm.flow_id)
        except Exception:
            pass
        with self._lock:
            self._recovering.pop(fsm.flow_id, None)
            self._ward[fsm.flow_id] = {
                "flow_id": fsm.flow_id,
                "flow_name": fsm.flow.flow_name(),
                "error": f"{type(exc).__name__}: {exc}",
                "error_type": type(exc).__name__,
                "ts": time.time(),
                "is_responder": fsm.is_responder,
                "checkpoint": blob,
                "flow_cls": type(fsm.flow),
                "args": fsm.args,
                "kwargs": dict(fsm.kwargs),
                "retries_spent": 0,
            }
            while len(self._ward) > self.ward_max:
                self._ward.popitem(last=False)  # evict oldest
        self.warded.inc()
        eventlog.emit(
            "warning", "hospital", "flow dead-lettered to ward",
            flow=fsm.flow.flow_name(), flow_id=fsm.flow_id,
            error=f"{type(exc).__name__}: {exc}",
        )

    # -- readmission ---------------------------------------------------------

    def _executor_submit(self, fn) -> None:
        """Readmissions replay flow bodies (arbitrary user code + crypto)
        — too heavy for the timer wheel's shared 2-thread callback pool,
        so they run on the hospital's own single worker."""
        with self._lock:
            if self._closed:
                return  # never recreate the executor close() tore down
            if self._executor is None:
                from concurrent.futures import ThreadPoolExecutor

                self._executor = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="flow-hospital"
                )
            executor = self._executor
        try:
            executor.submit(fn)
        except RuntimeError:
            pass  # node stopping

    def _on_retry_timer(self, flow_id: str) -> None:
        self._executor_submit(lambda: self._readmit(flow_id))

    def _readmit(self, flow_id: str) -> None:
        with self._lock:
            rec = self._recovering.get(flow_id)
            if rec is None or rec["killed"]:
                return
        eventlog.emit(
            "info", "hospital", "replaying flow from checkpoint",
            flow=rec["flow_name"], flow_id=flow_id, attempt=rec["attempts"],
        )
        try:
            blob = self.smm.checkpoint_storage.get(flow_id)
            with self._lock:
                # re-check after the storage read: a kill (or close) that
                # landed since the first check popped the record, removed
                # the checkpoint, and already failed the caller future —
                # re-running the flow now would execute a killed flow's
                # side effects
                if self._recovering.get(flow_id) is not rec or rec["killed"]:
                    return
            if blob is not None:
                self.smm._restore(
                    flow_id, blob, result_future=rec["future"],
                    merge_inbox_from=rec.get("old_fsm"),
                )
            else:
                # failed before its first checkpoint: re-run from scratch
                # with the original constructor args — but ONLY when no
                # sessions were opened (a fresh machine has no session
                # state and the peer's routes/dedup still point at the
                # old ids: re-running would hang or spawn duplicate
                # responders; failing loudly into the ward is safer)
                old = rec["old_fsm"]
                if old.sessions:
                    raise RuntimeError(
                        "flow opened sessions before its first "
                        "checkpoint; not fresh-retryable"
                    )
                self.smm._start_fresh_retry(
                    flow_id, type(old.flow), old.args, old.kwargs,
                    old.is_responder, rec["future"],
                )
        except BaseException as exc:
            # the RETRY ITSELF failed to launch — final: ward + fail
            fut = rec["future"]
            with self._lock:
                self._recovering.pop(flow_id, None)
            old = rec["old_fsm"]
            self.record_fatal(old, exc)
            self.smm.checkpoint_storage.remove(flow_id)
            if not fut.done():
                fut.set_exception(exc)

    def discharge(self, flow_id: str) -> None:
        """A re-admitted flow finished (either way): drop its record."""
        with self._lock:
            rec = self._recovering.pop(flow_id, None)
        if rec is not None:
            self.recovered.inc()
            eventlog.emit(
                "info", "hospital", "flow recovered",
                flow=rec["flow_name"], flow_id=flow_id,
                attempts=rec["attempts"],
            )

    def recovering_attempts(self, flow_id: str) -> int:
        with self._lock:
            rec = self._recovering.get(flow_id)
            return rec["attempts"] if rec else 0

    # -- operator surface (RPC node_hospital / retry_flow / kill_flow) -------

    def kill(self, flow_id: str) -> bool:
        """Kill a flow the hospital holds: cancels a scheduled retry
        (failing the preserved caller future with FlowKilledException)
        or discharges a ward record. False when unknown here."""
        with self._lock:
            rec = self._recovering.pop(flow_id, None)
            if rec is not None:
                rec["killed"] = True
                if rec["timer"] is not None:
                    rec["timer"].cancel()
            warded = self._ward.pop(flow_id, None) is not None
        if rec is not None:
            try:
                self.smm.checkpoint_storage.remove(flow_id)
            except Exception:
                pass
            exc = FlowKilledException(f"flow {flow_id} killed via RPC")
            # honour kill_flow's contract even for hospital-held flows:
            # peers get a SessionEnd (sessions were deliberately left
            # open for the retry; without this the counterparty responder
            # parks forever)
            old = rec.get("old_fsm")
            if old is not None:
                try:
                    old._end_sessions(encode_flow_exception(exc))
                except Exception:
                    pass  # messaging may already be down
            fut: Future = rec["future"]
            if not fut.done():
                fut.set_exception(exc)
            # every other terminal path runs _flow_finished: the finished
            # notification, audit record, and Flows.Finished meter must
            # not silently skip RPC-killed recovering flows
            if old is not None:
                try:
                    self.smm._flow_finished(old)
                except Exception:
                    pass
            return True
        return warded

    def retry_from_ward(self, flow_id: str) -> bool:
        """Re-run a warded flow NOW from its captured checkpoint (or from
        scratch when it never checkpointed). The re-run gets a fresh
        result future reachable via `flow_result(flow_id)`; a re-FAILURE
        of the flow simply re-wards it. Returns False when the id is not
        in the ward OR the relaunch itself failed (the record stays
        warded). Runs synchronously on the caller's thread."""
        with self._lock:
            rec = self._ward.pop(flow_id, None)
        if rec is None:
            return False
        eventlog.emit(
            "info", "hospital", "operator retry from ward",
            flow=rec["flow_name"], flow_id=flow_id,
        )
        try:
            if rec["checkpoint"] is not None:
                self.smm._restore(flow_id, rec["checkpoint"])
            else:
                self.smm._start_fresh_retry(
                    flow_id, rec["flow_cls"], rec["args"], rec["kwargs"],
                    rec["is_responder"], Future(),
                )
        except BaseException as exc:
            eventlog.emit(
                "warning", "hospital", "ward retry failed to launch",
                flow_id=flow_id, error=f"{type(exc).__name__}: {exc}",
            )
            with self._lock:
                self._ward[flow_id] = rec  # put it back
            return False  # never report a relaunch that did not happen
        return True

    def snapshot(self) -> dict:
        """The operator view: who is recovering, who is dead-lettered."""
        with self._lock:
            recovering = [
                {
                    k: rec[k]
                    for k in ("flow_id", "flow_name", "attempts", "error",
                              "next_retry_at")
                }
                for rec in self._recovering.values()
            ]
            ward = [
                {
                    k: rec[k]
                    for k in ("flow_id", "flow_name", "error", "error_type",
                              "ts", "is_responder")
                }
                for rec in self._ward.values()
            ]
        return {
            "enabled": self.enabled,
            "max_retries": self.max_retries,
            "recovering": recovering,
            "ward": ward,
            "ward_max": self.ward_max,
            "retries": self.retries.value,
            "recovered": self.recovered.value,
            "warded": self.warded.value,
        }

    def close(self) -> None:
        with self._lock:
            self._closed = True
            pending = list(self._recovering.values())
            self._recovering.clear()
            for rec in pending:
                rec["killed"] = True
                if rec["timer"] is not None:
                    rec["timer"].cancel()
            executor, self._executor = self._executor, None
        # Callers blocked on a recovering flow's result must fail fast,
        # not hang past shutdown (the checkpoint survives — a restarted
        # node restores and re-runs the flow).
        for rec in pending:
            fut: Future = rec["future"]
            if not fut.done():
                fut.set_exception(
                    FlowException(
                        "node stopped before flow "
                        f"{rec['flow_id']} finished recovery"
                    )
                )
        if executor is not None:
            executor.shutdown(wait=False)

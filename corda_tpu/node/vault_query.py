"""Vault query engine: composable criteria, paging, sorting, tracking.

Reference parity: `node/src/main/kotlin/net/corda/node/services/vault/
HibernateQueryCriteriaParser.kt` (criteria -> JPA predicates) and the
`CordaRPCOps.kt:151-259` vault query surface (queryBy/trackBy with
QueryCriteria + PageSpecification + Sort).  The reference compiles a
criteria tree to Hibernate; here the same tree compiles to one SQL WHERE
clause over the vault_states table — a single embedded store instead of
four ORMs, per the TPU-build design.

Criteria compose with `.and_(...)` / `.or_(...)` (reference
QueryCriteria.and/or).  Results come back as a `Page` with the total
count, mirroring the reference's Vault.Page (totalStatesAvailable).
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from ..core.contracts.structures import StateAndRef, StateRef
from ..core.serialization.codec import register_adapter

DEFAULT_PAGE_SIZE = 200
MAX_PAGE_SIZE = 10_000

UNCONSUMED = "UNCONSUMED"
CONSUMED = "CONSUMED"
ALL = "ALL"


class VaultQueryError(Exception):
    pass


@dataclass(frozen=True)
class PageSpecification:
    """1-based page number (reference PageSpecification)."""

    page_number: int = 1
    page_size: int = DEFAULT_PAGE_SIZE

    def __post_init__(self):
        if self.page_number < 1:
            raise VaultQueryError("page_number is 1-based")
        if not 0 < self.page_size <= MAX_PAGE_SIZE:
            raise VaultQueryError(f"page_size must be in 1..{MAX_PAGE_SIZE}")


@dataclass(frozen=True)
class Sort:
    """Sort by a vault column (reference Sort/SortAttribute)."""

    column: str = "recorded_at"   # recorded_at | contract_name | state_ref
    descending: bool = False

    _COLUMNS = {
        "recorded_at": "recorded_at",
        "contract_name": "contract_name",
        "state_ref": "tx_id, output_index",
    }

    def sql(self) -> str:
        col = self._COLUMNS.get(self.column)
        if col is None:
            raise VaultQueryError(f"unknown sort column {self.column!r}")
        direction = "DESC" if self.descending else "ASC"
        return ", ".join(f"{c.strip()} {direction}" for c in col.split(","))


class QueryCriteria:
    """Base: compiles to (sql_fragment, params). Compose with and_/or_."""

    def compile(self) -> Tuple[str, list]:
        raise NotImplementedError

    def and_(self, other: "QueryCriteria") -> "QueryCriteria":
        return _Compound("AND", self, other)

    def or_(self, other: "QueryCriteria") -> "QueryCriteria":
        return _Compound("OR", self, other)


@dataclass(frozen=True)
class _Compound(QueryCriteria):
    op: str
    left: QueryCriteria
    right: QueryCriteria

    def compile(self):
        lsql, lparams = self.left.compile()
        rsql, rparams = self.right.compile()
        return f"({lsql} {self.op} {rsql})", lparams + rparams


@dataclass(frozen=True)
class VaultQueryCriteria(QueryCriteria):
    """The general criteria (reference QueryCriteria.VaultQueryCriteria):
    status, contract names, specific refs, notary, participants, record
    time window, soft-lock filter."""

    status: str = UNCONSUMED
    contract_names: Tuple[str, ...] = ()
    state_refs: Tuple[StateRef, ...] = ()
    notary_names: Tuple[str, ...] = ()
    participant_keys: Tuple[bytes, ...] = ()   # encoded public keys
    recorded_after: Optional[float] = None
    recorded_before: Optional[float] = None
    include_soft_locked: bool = True

    def compile(self):
        clauses, params = [], []
        if self.status == UNCONSUMED:
            clauses.append("consumed = 0")
        elif self.status == CONSUMED:
            clauses.append("consumed = 1")
        elif self.status != ALL:
            raise VaultQueryError(f"unknown status {self.status!r}")
        if self.contract_names:
            marks = ",".join("?" * len(self.contract_names))
            clauses.append(f"contract_name IN ({marks})")
            params.extend(self.contract_names)
        if self.state_refs:
            ref_clause = " OR ".join(
                "(tx_id = ? AND output_index = ?)" for _ in self.state_refs
            )
            clauses.append(f"({ref_clause})")
            for ref in self.state_refs:
                params.extend([ref.txhash.bytes, ref.index])
        if self.notary_names:
            marks = ",".join("?" * len(self.notary_names))
            clauses.append(f"notary_name IN ({marks})")
            params.extend(self.notary_names)
        if self.participant_keys:
            marks = ",".join("?" * len(self.participant_keys))
            clauses.append(
                "EXISTS (SELECT 1 FROM vault_participants p WHERE"
                " p.tx_id = vault_states.tx_id"
                " AND p.output_index = vault_states.output_index"
                f" AND p.key_hex IN ({marks}))"
            )
            params.extend(k.hex() for k in self.participant_keys)
        if self.recorded_after is not None:
            clauses.append("recorded_at >= ?")
            params.append(self.recorded_after)
        if self.recorded_before is not None:
            clauses.append("recorded_at <= ?")
            params.append(self.recorded_before)
        if not self.include_soft_locked:
            clauses.append("lock_id IS NULL")
        return (" AND ".join(clauses) or "1=1"), params


def _status_clause(status: str) -> Tuple[str, list]:
    if status == UNCONSUMED:
        return "consumed = 0", []
    if status == CONSUMED:
        return "consumed = 1", []
    if status == ALL:
        return "1=1", []
    raise VaultQueryError(f"unknown status {status!r}")


def _attr_exists(name: str, op: str, value, numeric: bool) -> Tuple[str, list]:
    """EXISTS subquery over vault_attributes for one attribute predicate."""
    if op not in ("=", "<", "<=", ">", ">=", "LIKE"):
        raise VaultQueryError(f"unsupported attribute operator {op!r}")
    column = "value_num" if numeric else "value_text"
    return (
        "EXISTS (SELECT 1 FROM vault_attributes a WHERE"
        " a.tx_id = vault_states.tx_id"
        " AND a.output_index = vault_states.output_index"
        f" AND a.name = ? AND a.{column} {op} ?)",
        # ints stay ints: the column has NUMERIC affinity so 64-bit token
        # quantities compare exactly (no 2^53 float rounding)
        [name, value if numeric else str(value)],
    )


def _attr_in(name: str, values) -> Tuple[str, list]:
    marks = ",".join("?" * len(values))
    return (
        "EXISTS (SELECT 1 FROM vault_attributes a WHERE"
        " a.tx_id = vault_states.tx_id"
        " AND a.output_index = vault_states.output_index"
        f" AND a.name = ? AND a.value_text IN ({marks}))",
        [name] + [str(v) for v in values],
    )


@dataclass(frozen=True)
class LinearStateQueryCriteria(QueryCriteria):
    """LinearState family (reference QueryCriteria.LinearStateQueryCriteria
    -> HibernateQueryCriteriaParser VaultLinearStates columns): select by
    linear id (UniqueIdentifier or its string form) and/or external id."""

    linear_ids: Tuple = ()
    external_ids: Tuple[str, ...] = ()
    status: str = UNCONSUMED

    def compile(self):
        clauses, params = [], []
        sql, p = _status_clause(self.status)
        clauses.append(sql)
        params.extend(p)
        if self.linear_ids:
            sql, p = _attr_in("linear_id", [str(l) for l in self.linear_ids])
            clauses.append(sql)
            params.extend(p)
        if self.external_ids:
            sql, p = _attr_in("external_id", list(self.external_ids))
            clauses.append(sql)
            params.extend(p)
        return " AND ".join(clauses), params


@dataclass(frozen=True)
class FungibleAssetQueryCriteria(QueryCriteria):
    """FungibleAsset family (reference
    QueryCriteria.FungibleAssetQueryCriteria -> CashSchemaV1 columns):
    owner keys, quantity comparison, issuer party names/refs, product."""

    owner_keys: Tuple[bytes, ...] = ()     # encoded public keys
    quantity: Optional[Tuple[str, int]] = None  # (op, value), op in = < <= > >=
    issuer_names: Tuple[str, ...] = ()
    issuer_refs: Tuple[bytes, ...] = ()
    products: Tuple[str, ...] = ()
    status: str = UNCONSUMED

    def compile(self):
        clauses, params = [], []
        sql, p = _status_clause(self.status)
        clauses.append(sql)
        params.extend(p)
        if self.owner_keys:
            sql, p = _attr_in("owner_key", [k.hex() for k in self.owner_keys])
            clauses.append(sql)
            params.extend(p)
        if self.quantity is not None:
            op, value = self.quantity
            sql, p = _attr_exists("quantity", op, value, numeric=True)
            clauses.append(sql)
            params.extend(p)
        if self.issuer_names:
            sql, p = _attr_in("issuer_name", list(self.issuer_names))
            clauses.append(sql)
            params.extend(p)
        if self.issuer_refs:
            sql, p = _attr_in("issuer_ref", [r.hex() for r in self.issuer_refs])
            clauses.append(sql)
            params.extend(p)
        if self.products:
            sql, p = _attr_in("product", list(self.products))
            clauses.append(sql)
            params.extend(p)
        return " AND ".join(clauses), params


@dataclass(frozen=True)
class CustomAttributeCriteria(QueryCriteria):
    """Custom per-contract schema criterion (reference
    QueryCriteria.VaultCustomQueryCriteria over a MappedSchema column):
    matches an attribute a state exposed via `vault_attributes()` —
    `CustomAttributeCriteria("maturity", "<=", 1700000000.0)`."""

    name: str = ""
    op: str = "="
    value: object = None
    numeric: bool = False
    status: str = UNCONSUMED

    def compile(self):
        clauses, params = [], []
        sql, p = _status_clause(self.status)
        clauses.append(sql)
        params.extend(p)
        sql, p = _attr_exists(self.name, self.op, self.value, self.numeric)
        clauses.append(sql)
        params.extend(p)
        return " AND ".join(clauses), params


@dataclass(frozen=True)
class Page:
    """One page of results (reference Vault.Page)."""

    states: Tuple[StateAndRef, ...]
    total_states_available: int
    page_number: int
    page_size: int


register_adapter(
    PageSpecification, "PageSpecification",
    lambda p: {"n": p.page_number, "size": p.page_size},
    lambda d: PageSpecification(d["n"], d["size"]),
)
register_adapter(
    Sort, "VaultSort",
    lambda s: {"col": s.column, "desc": s.descending},
    lambda d: Sort(d["col"], d["desc"]),
)
register_adapter(
    VaultQueryCriteria, "VaultQueryCriteria",
    lambda c: {
        "status": c.status, "contracts": list(c.contract_names),
        "refs": list(c.state_refs), "notaries": list(c.notary_names),
        "participants": list(c.participant_keys),
        "after": c.recorded_after, "before": c.recorded_before,
        "locked": c.include_soft_locked,
    },
    lambda d: VaultQueryCriteria(
        d["status"], tuple(d["contracts"]), tuple(d["refs"]),
        tuple(d["notaries"]), tuple(d["participants"]),
        d["after"], d["before"], d["locked"],
    ),
)
register_adapter(
    _Compound, "VaultCompoundCriteria",
    lambda c: {"op": c.op, "l": c.left, "r": c.right},
    lambda d: _Compound(d["op"], d["l"], d["r"]),
)
register_adapter(
    LinearStateQueryCriteria, "LinearStateQueryCriteria",
    lambda c: {
        "linear_ids": [str(l) for l in c.linear_ids],
        "external_ids": list(c.external_ids), "status": c.status,
    },
    lambda d: LinearStateQueryCriteria(
        tuple(d["linear_ids"]), tuple(d["external_ids"]), d["status"],
    ),
)
register_adapter(
    FungibleAssetQueryCriteria, "FungibleAssetQueryCriteria",
    lambda c: {
        "owners": list(c.owner_keys),
        "quantity": list(c.quantity) if c.quantity else None,
        "issuers": list(c.issuer_names), "refs": list(c.issuer_refs),
        "products": list(c.products), "status": c.status,
    },
    lambda d: FungibleAssetQueryCriteria(
        tuple(d["owners"]),
        tuple(d["quantity"]) if d["quantity"] else None,
        tuple(d["issuers"]), tuple(d["refs"]), tuple(d["products"]),
        d["status"],
    ),
)
register_adapter(
    CustomAttributeCriteria, "CustomAttributeCriteria",
    lambda c: {
        "name": c.name, "op": c.op, "value": c.value,
        "numeric": c.numeric, "status": c.status,
    },
    lambda d: CustomAttributeCriteria(
        d["name"], d["op"], d["value"], d["numeric"], d["status"],
    ),
)
register_adapter(
    Page, "VaultPage",
    lambda p: {
        "states": list(p.states), "total": p.total_states_available,
        "n": p.page_number, "size": p.page_size,
    },
    lambda d: Page(tuple(d["states"]), d["total"], d["n"], d["size"]),
)

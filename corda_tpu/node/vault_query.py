"""Vault query engine: composable criteria, paging, sorting, tracking.

Reference parity: `node/src/main/kotlin/net/corda/node/services/vault/
HibernateQueryCriteriaParser.kt` (criteria -> JPA predicates) and the
`CordaRPCOps.kt:151-259` vault query surface (queryBy/trackBy with
QueryCriteria + PageSpecification + Sort).  The reference compiles a
criteria tree to Hibernate; here the same tree compiles to one SQL WHERE
clause over the vault_states table — a single embedded store instead of
four ORMs, per the TPU-build design.

Criteria compose with `.and_(...)` / `.or_(...)` (reference
QueryCriteria.and/or).  Results come back as a `Page` with the total
count, mirroring the reference's Vault.Page (totalStatesAvailable).
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from ..core.contracts.structures import StateAndRef, StateRef
from ..core.serialization.codec import register_adapter

DEFAULT_PAGE_SIZE = 200
MAX_PAGE_SIZE = 10_000

UNCONSUMED = "UNCONSUMED"
CONSUMED = "CONSUMED"
ALL = "ALL"


class VaultQueryError(Exception):
    pass


@dataclass(frozen=True)
class PageSpecification:
    """1-based page number (reference PageSpecification)."""

    page_number: int = 1
    page_size: int = DEFAULT_PAGE_SIZE

    def __post_init__(self):
        if self.page_number < 1:
            raise VaultQueryError("page_number is 1-based")
        if not 0 < self.page_size <= MAX_PAGE_SIZE:
            raise VaultQueryError(f"page_size must be in 1..{MAX_PAGE_SIZE}")


@dataclass(frozen=True)
class Sort:
    """Sort by a vault column (reference Sort/SortAttribute)."""

    column: str = "recorded_at"   # recorded_at | contract_name | state_ref
    descending: bool = False

    _COLUMNS = {
        "recorded_at": "recorded_at",
        "contract_name": "contract_name",
        "state_ref": "tx_id, output_index",
    }

    def sql(self) -> str:
        col = self._COLUMNS.get(self.column)
        if col is None:
            raise VaultQueryError(f"unknown sort column {self.column!r}")
        direction = "DESC" if self.descending else "ASC"
        return ", ".join(f"{c.strip()} {direction}" for c in col.split(","))


class QueryCriteria:
    """Base: compiles to (sql_fragment, params). Compose with and_/or_."""

    def compile(self) -> Tuple[str, list]:
        raise NotImplementedError

    def and_(self, other: "QueryCriteria") -> "QueryCriteria":
        return _Compound("AND", self, other)

    def or_(self, other: "QueryCriteria") -> "QueryCriteria":
        return _Compound("OR", self, other)


@dataclass(frozen=True)
class _Compound(QueryCriteria):
    op: str
    left: QueryCriteria
    right: QueryCriteria

    def compile(self):
        lsql, lparams = self.left.compile()
        rsql, rparams = self.right.compile()
        return f"({lsql} {self.op} {rsql})", lparams + rparams


@dataclass(frozen=True)
class VaultQueryCriteria(QueryCriteria):
    """The general criteria (reference QueryCriteria.VaultQueryCriteria):
    status, contract names, specific refs, notary, participants, record
    time window, soft-lock filter."""

    status: str = UNCONSUMED
    contract_names: Tuple[str, ...] = ()
    state_refs: Tuple[StateRef, ...] = ()
    notary_names: Tuple[str, ...] = ()
    participant_keys: Tuple[bytes, ...] = ()   # encoded public keys
    recorded_after: Optional[float] = None
    recorded_before: Optional[float] = None
    include_soft_locked: bool = True

    def compile(self):
        clauses, params = [], []
        if self.status == UNCONSUMED:
            clauses.append("consumed = 0")
        elif self.status == CONSUMED:
            clauses.append("consumed = 1")
        elif self.status != ALL:
            raise VaultQueryError(f"unknown status {self.status!r}")
        if self.contract_names:
            marks = ",".join("?" * len(self.contract_names))
            clauses.append(f"contract_name IN ({marks})")
            params.extend(self.contract_names)
        if self.state_refs:
            ref_clause = " OR ".join(
                "(tx_id = ? AND output_index = ?)" for _ in self.state_refs
            )
            clauses.append(f"({ref_clause})")
            for ref in self.state_refs:
                params.extend([ref.txhash.bytes, ref.index])
        if self.notary_names:
            marks = ",".join("?" * len(self.notary_names))
            clauses.append(f"notary_name IN ({marks})")
            params.extend(self.notary_names)
        if self.participant_keys:
            marks = ",".join("?" * len(self.participant_keys))
            clauses.append(
                "EXISTS (SELECT 1 FROM vault_participants p WHERE"
                " p.tx_id = vault_states.tx_id"
                " AND p.output_index = vault_states.output_index"
                f" AND p.key_hex IN ({marks}))"
            )
            params.extend(k.hex() for k in self.participant_keys)
        if self.recorded_after is not None:
            clauses.append("recorded_at >= ?")
            params.append(self.recorded_after)
        if self.recorded_before is not None:
            clauses.append("recorded_at <= ?")
            params.append(self.recorded_before)
        if not self.include_soft_locked:
            clauses.append("lock_id IS NULL")
        return (" AND ".join(clauses) or "1=1"), params


@dataclass(frozen=True)
class Page:
    """One page of results (reference Vault.Page)."""

    states: Tuple[StateAndRef, ...]
    total_states_available: int
    page_number: int
    page_size: int


register_adapter(
    PageSpecification, "PageSpecification",
    lambda p: {"n": p.page_number, "size": p.page_size},
    lambda d: PageSpecification(d["n"], d["size"]),
)
register_adapter(
    Sort, "VaultSort",
    lambda s: {"col": s.column, "desc": s.descending},
    lambda d: Sort(d["col"], d["desc"]),
)
register_adapter(
    VaultQueryCriteria, "VaultQueryCriteria",
    lambda c: {
        "status": c.status, "contracts": list(c.contract_names),
        "refs": list(c.state_refs), "notaries": list(c.notary_names),
        "participants": list(c.participant_keys),
        "after": c.recorded_after, "before": c.recorded_before,
        "locked": c.include_soft_locked,
    },
    lambda d: VaultQueryCriteria(
        d["status"], tuple(d["contracts"]), tuple(d["refs"]),
        tuple(d["notaries"]), tuple(d["participants"]),
        d["after"], d["before"], d["locked"],
    ),
)
register_adapter(
    _Compound, "VaultCompoundCriteria",
    lambda c: {"op": c.op, "l": c.left, "r": c.right},
    lambda d: _Compound(d["op"], d["l"], d["r"]),
)
register_adapter(
    Page, "VaultPage",
    lambda p: {
        "states": list(p.states), "total": p.total_states_available,
        "n": p.page_number, "size": p.page_size,
    },
    lambda d: Page(tuple(d["states"]), d["total"], d["n"], d["size"]),
)

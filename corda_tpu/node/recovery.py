"""Crash-recovery invariants + per-record CRC framing (the durability
contract, docs/robustness.md §7).

Two jobs:

* **Record framing.** Checkpoint blobs and broker-journal record bodies
  are wrapped in a ``magic | u32 len | u32 crc32 | payload`` frame on
  write. A loader that hits a corrupt or truncated record QUARANTINES
  it (eventlog ``recovery`` record + the ``Recovery.QuarantinedRecords``
  counter) and keeps going, instead of wedging startup on the one torn
  row a power cut left behind. Legacy unframed blobs pass through
  unchanged (``unframe`` detects the magic), so old stores keep
  working.

* **`verify_node_state`** — the ONE invariant checker every crash-point
  run in tools/crashmc.py asserts after recovery: no lost acked
  message, no duplicated flow result, no half-consumed state ref, every
  journaled 2PC round fully re-driven or fully released, checkpoint
  store parseable. Each `verify_*` helper returns a list of problem
  strings (empty = clean) so the checker composes per-store and a
  failure names its store.
"""
from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..utils import eventlog, metrics

#: frame magic for CRC-framed records. Chosen to be impossible as the
#: first 4 bytes of this repo's serialization codec output AND of a
#: legacy raw journal body (which starts with a hex message id).
FRAME_MAGIC = b"\xc5\xcfR1"

_FRAME_HDR = struct.Struct(">II")  # payload length, crc32(payload)

#: process-wide: how many corrupt records loaders skipped-and-kept-going
#: past instead of raising mid-restore (exposed as
#: Recovery.QuarantinedRecords via node_metrics wiring or read directly)
quarantined_records = metrics.Counter()

#: the metric name the counter rides under when a registry exports it
QUARANTINE_METRIC = "Recovery.QuarantinedRecords"


class CorruptRecordError(ValueError):
    """A CRC-framed record failed its checksum or length check."""


def frame(payload: bytes) -> bytes:
    """Wrap `payload` in the per-record CRC32 + length frame."""
    return FRAME_MAGIC + _FRAME_HDR.pack(
        len(payload), zlib.crc32(payload) & 0xFFFFFFFF
    ) + payload


def unframe(blob: bytes) -> bytes:
    """Verify-and-strip the frame; legacy (unframed) blobs pass through
    unchanged. Raises CorruptRecordError on truncation or CRC mismatch —
    callers quarantine via `quarantine_record` instead of crashing."""
    if not blob.startswith(FRAME_MAGIC):
        return blob
    hdr_end = len(FRAME_MAGIC) + _FRAME_HDR.size
    if len(blob) < hdr_end:
        raise CorruptRecordError("frame header truncated")
    length, crc = _FRAME_HDR.unpack_from(blob, len(FRAME_MAGIC))
    payload = blob[hdr_end:]
    if len(payload) != length:
        raise CorruptRecordError(
            f"frame length mismatch: header says {length}, "
            f"got {len(payload)} bytes"
        )
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise CorruptRecordError("frame crc32 mismatch (torn write)")
    return payload


def quarantine_record(store: str, ident: str, reason: str) -> None:
    """Count + announce one skipped corrupt record. The eventlog record
    (component "recovery") is the operator's evidence that data was set
    aside, not silently destroyed."""
    quarantined_records.inc()
    eventlog.emit(
        "warning", "recovery",
        "corrupt record quarantined instead of wedging startup",
        store=store, ident=ident, reason=reason,
    )


# -- invariant checkers -------------------------------------------------------

@dataclass
class RecoveryReport:
    """verify_node_state's verdict: empty problems = the recovery
    invariants held."""
    problems: List[str] = field(default_factory=list)
    quarantined: int = 0

    @property
    def ok(self) -> bool:
        return not self.problems

    def extend(self, label: str, probs: List[str]) -> None:
        self.problems.extend(f"{label}: {p}" for p in probs)


def verify_broker_journal(
    journal_dir: str,
    sent: Optional[Set[str]] = None,
    acked: Optional[Set[str]] = None,
    durable_sent: Optional[Set[str]] = None,
) -> List[str]:
    """Replay every queue journal under `journal_dir` and check:
    journals parse (torn tails truncate, corrupt records quarantine —
    never raise); recovered pending ids are unique per queue and ⊆
    `sent` (no fabricated message); no ACKED message redelivery is
    REQUIRED (pending ∩ acked is allowed — ack-flush batching means a
    crash legally forgets recent acks and dedup absorbs the replay);
    and every id in `durable_sent` (enqueues known fsync-durable) that
    was never acked IS recovered — the no-lost-message half."""
    import os

    from ..messaging.broker import _Journal

    problems: List[str] = []
    recovered: Set[str] = set()
    if not os.path.isdir(journal_dir):
        return [f"journal dir missing: {journal_dir}"]
    for fn in sorted(os.listdir(journal_dir)):
        if not fn.endswith(".journal"):
            continue
        path = os.path.join(journal_dir, fn)
        try:
            pending = _Journal.replay(path)
        except Exception as exc:
            problems.append(f"{fn}: replay raised {type(exc).__name__}: "
                            f"{exc} (must truncate/quarantine, not wedge)")
            continue
        ids = [m.message_id for m in pending]
        if len(ids) != len(set(ids)):
            problems.append(f"{fn}: duplicate pending message ids")
        recovered.update(ids)
    if sent is not None:
        ghosts = recovered - sent
        if ghosts:
            problems.append(
                f"recovered messages never sent: {sorted(ghosts)[:3]}"
            )
    if durable_sent is not None:
        lost = durable_sent - (acked or set()) - recovered
        if lost:
            problems.append(
                f"durably-enqueued unacked messages lost: "
                f"{sorted(lost)[:3]} (+{max(0, len(lost) - 3)} more)"
            )
    return problems


def verify_checkpoints(checkpoint_storage) -> List[str]:
    """The checkpoint store must be PARSEABLE end to end: every surviving
    blob unframes and deserializes. Corrupt rows were already quarantined
    by the storage layer (all_checkpoints never raises on them)."""
    from ..core.serialization.codec import deserialize

    problems: List[str] = []
    try:
        rows = checkpoint_storage.all_checkpoints()
    except Exception as exc:
        return [f"all_checkpoints raised {type(exc).__name__}: {exc} "
                f"(corrupt records must quarantine, not wedge startup)"]
    seen: Set[str] = set()
    for flow_id, blob in rows:
        if flow_id in seen:
            problems.append(f"duplicate checkpoint for flow {flow_id}")
        seen.add(flow_id)
        try:
            state = deserialize(blob)
        except Exception as exc:
            problems.append(
                f"checkpoint {flow_id} not deserializable after "
                f"recovery: {type(exc).__name__}: {exc}"
            )
            continue
        if not isinstance(state, dict) or "flow_name" not in state:
            problems.append(f"checkpoint {flow_id} missing flow_name")
    return problems


def verify_vault(db) -> List[str]:
    """No half-consumed state ref: vault ingest (notify_all) is one
    sqlite transaction per batch, so for every transaction the node
    recorded, either its outputs are present AND its inputs consumed,
    or neither — a tx with consumed inputs but missing outputs (or the
    reverse) is a torn ingest. Also: no state both consumed and still
    soft-locked (a consumed row must not pin a lock forever)."""
    from ..core.serialization.codec import deserialize

    problems: List[str] = []
    vault_rows = db.query(
        "SELECT tx_id, output_index, consumed, lock_id FROM vault_states"
    )
    by_ref: Dict[Tuple[bytes, int], Tuple[int, Optional[str]]] = {
        (bytes(r[0]), r[1]): (r[2], r[3]) for r in vault_rows
    }
    for (txid, idx), (consumed, lock_id) in by_ref.items():
        if consumed and lock_id:
            problems.append(
                f"state {txid.hex()[:16]}:{idx} consumed but still "
                f"soft-locked by {lock_id}"
            )
    try:
        tx_rows = db.query("SELECT tx_id, blob FROM transactions")
    # lint: allow(swallow) — node without a tx store (bare vault rigs)
    except Exception:
        return problems
    for txid_raw, blob in tx_rows:
        try:
            stx = deserialize(blob)
            wtx = stx.tx
        # lint: allow(swallow) — undeserializable row is not this
        except Exception:
            continue  # checker's store; verify_checkpoints owns blobs
        inputs_here = [
            (ref.txhash.bytes, ref.index) for ref in wtx.inputs
            if (ref.txhash.bytes, ref.index) in by_ref
        ]
        outputs_here = [
            i for i in range(len(wtx.outputs))
            if (wtx.id.bytes, i) in by_ref
        ]
        consumed_flags = [by_ref[k][0] for k in inputs_here]
        if outputs_here and consumed_flags and not all(consumed_flags):
            problems.append(
                f"tx {wtx.id.bytes.hex()[:16]} half-ingested: outputs "
                f"recorded but {consumed_flags.count(0)} of "
                f"{len(consumed_flags)} inputs unconsumed"
            )
    return problems


def verify_sharded_journal(provider) -> List[str]:
    """After `provider.recover()`: every journaled round is fully
    re-driven or fully released — no 'committing' round may remain (the
    decision was durable; recovery must drive it to completion), and no
    reservation may outlive its round's journal entry."""
    problems: List[str] = []
    rounds = provider.journal.items()
    for round_id, rec in rounds:
        if rec.get("phase") == "committing":
            problems.append(
                f"round {round_id[:16]} still journaled 'committing' "
                f"after recovery (must be re-driven to completion)"
            )
    live_rounds = {round_id for round_id, _ in rounds}
    for s, store in enumerate(getattr(provider, "_stores", [])):
        try:
            held = store.held_tx_ids()
        except AttributeError:
            continue
        for tx_hex in held:
            if tx_hex not in live_rounds:
                problems.append(
                    f"shard s{s}: reservation for {tx_hex[:16]} outlives "
                    f"its journal entry (leaked lock)"
                )
    return problems


def verify_consumption(providers, expected: Dict[bytes, str]) -> List[str]:
    """Cross-store double-spend check for a recovery scenario: each key
    in `expected` (state key -> consuming tx hex) must be consumed by
    EXACTLY that tx in exactly one provider — and a re-commit probe of a
    DIFFERENT tx against the same key must conflict, which callers do
    via the provider API. Here: no key consumed twice under different
    txs across `providers`."""
    problems: List[str] = []
    owners: Dict[bytes, Set[str]] = {}
    for p in providers:
        for key, tx_hex in p.consumed_keys():
            owners.setdefault(key, set()).add(tx_hex)
    for key, txs in owners.items():
        if len(txs) > 1:
            problems.append(
                f"state key {key.hex()[:16]} consumed by {len(txs)} "
                f"different txs: {sorted(t[:16] for t in txs)}"
            )
    for key, tx_hex in expected.items():
        got = owners.get(key, set())
        if got and got != {tx_hex}:
            problems.append(
                f"state key {key.hex()[:16]} consumed by "
                f"{sorted(got)[0][:16]}, expected {tx_hex[:16]}"
            )
    return problems


def verify_notary_change(journal) -> List[str]:
    """Notary-change journal entries after recovery must be gone (the
    recovery flow re-drives each to completion and removes it) — any
    survivor means a change is neither re-driven nor released."""
    return [
        f"notary-change {tx_hex[:16]} parked at phase "
        f"{rec.get('phase')!r} after recovery"
        for tx_hex, rec in journal.items()
    ]


def verify_flow_results(results: Dict[str, List]) -> List[str]:
    """No duplicated flow result: a flow id observed completing more
    than once (e.g. replayed checkpoint AND live run both delivering)
    is a duplicated side effect."""
    return [
        f"flow {fid} delivered {len(rs)} results (exactly-once violated)"
        for fid, rs in results.items() if len(rs) > 1
    ]


def verify_node_state(
    node=None,
    *,
    journal_dir: Optional[str] = None,
    checkpoint_storage=None,
    db=None,
    sharded_provider=None,
    notary_change_journal=None,
    flow_results: Optional[Dict[str, List]] = None,
    sent: Optional[Set[str]] = None,
    acked: Optional[Set[str]] = None,
    durable_sent: Optional[Set[str]] = None,
) -> RecoveryReport:
    """THE recovery invariant checker (ISSUE 20): run every per-store
    verifier that applies to what the caller hands in. Pass a live
    `node` (AbstractNode duck type) to derive the stores, or pass the
    stores individually (the crashmc scenarios build them bare)."""
    report = RecoveryReport(quarantined=quarantined_records.value)
    if node is not None:
        checkpoint_storage = checkpoint_storage or getattr(
            node, "checkpoint_storage", None)
        db = db or getattr(node, "db", None)
        broker = getattr(node, "broker", None)
        if journal_dir is None and broker is not None:
            journal_dir = getattr(broker, "journal_dir", None)
    if journal_dir is not None:
        report.extend("broker_journal", verify_broker_journal(
            journal_dir, sent=sent, acked=acked,
            durable_sent=durable_sent,
        ))
    if checkpoint_storage is not None:
        report.extend("checkpoints", verify_checkpoints(checkpoint_storage))
    if db is not None:
        report.extend("vault", verify_vault(db))
    if sharded_provider is not None:
        report.extend("sharded_2pc",
                      verify_sharded_journal(sharded_provider))
    if notary_change_journal is not None:
        report.extend("notary_change",
                      verify_notary_change(notary_change_journal))
    if flow_results is not None:
        report.extend("flows", verify_flow_results(flow_results))
    return report

"""Network registration: CSR submission to a doorman (reference
`node/.../utilities/registration/NetworkRegistrationHelper.kt:1-150` —
the node generates a certificate signing request, POSTs it to the
network's doorman over HTTP, polls until the signed certificate chain
comes back, and installs it in its certificate store).

Includes a `DoormanServer` (the registration-service half: reference's
doorman is a separate product; a functioning stdlib-HTTP one here makes
the protocol testable end-to-end) with optional manual approval.
"""
from __future__ import annotations

import base64
import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib import request as _urlreq

try:
    from cryptography import x509
    from cryptography.hazmat.primitives import serialization
except ImportError:  # pragma: no cover - registration needs real X.509
    from ..core.crypto.pki import serialization, x509  # lazy-failing stubs

from ..core.crypto import pki
from ..utils import lockorder


class RegistrationError(Exception):
    pass


# --- client side (the node's helper) -----------------------------------------

class NetworkRegistrationHelper:
    """Generate CSR -> POST /certificate -> poll GET /certificate/{id}
    until APPROVED -> validate the returned chain -> write it into the
    node's certificate store.

    Trust: pass `expected_root` (the pre-provisioned network trust root,
    as a certificate or its SHA-256 DER fingerprint hex) so a MITM or
    rogue doorman cannot hand the node an attacker-controlled identity —
    the reference validates the doorman's response against the local
    network truststore the same way (NetworkRegistrationHelper.kt).
    Without it the first response is trusted (trust-on-first-use) and a
    warning is logged. In production `doorman_url` should be HTTPS; the
    chain validation here is what protects enrolment when it is not."""

    def __init__(self, doorman_url: str, legal_name: str, cert_dir: str,
                 expected_root=None):
        self.doorman_url = doorman_url.rstrip("/")
        self.legal_name = legal_name
        self.cert_dir = cert_dir
        self.expected_root = expected_root

    def register(self, timeout: float = 60, poll_interval: float = 0.2):
        csr, key = pki.create_csr(self.legal_name)
        pem = csr.public_bytes(serialization.Encoding.PEM)
        req = _urlreq.Request(
            f"{self.doorman_url}/certificate",
            data=pem,
            method="POST",
            headers={"Content-Type": "application/x-pem-file"},
        )
        with _urlreq.urlopen(req, timeout=10) as resp:
            request_id = json.loads(resp.read())["request_id"]

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with _urlreq.urlopen(
                f"{self.doorman_url}/certificate/{request_id}", timeout=10
            ) as resp:
                body = json.loads(resp.read())
            if body["status"] == "APPROVED":
                chain = [
                    x509.load_pem_x509_certificate(
                        base64.b64decode(pem_b64)
                    )
                    for pem_b64 in body["certificates"]
                ]
                self._validate(chain, csr)
                self._install(chain, key)
                return chain
            if body["status"] == "REJECTED":
                raise RegistrationError(
                    f"doorman rejected registration: {body.get('reason')}"
                )
            time.sleep(poll_interval)
        raise RegistrationError(f"registration not approved in {timeout}s")

    def _validate(self, chain, csr) -> None:
        """Reject a chain that (a) does not fit the leaf/intermediate/root
        alias scheme, (b) does not bind the CSR's key, or (c) does not
        verify up to the expected trust root."""
        if len(chain) != 3:
            raise RegistrationError(
                f"doorman returned {len(chain)} certificates; expected "
                "exactly [identity, intermediate, root]"
            )
        leaf, intermediate, root = chain
        leaf_spki = leaf.public_key().public_bytes(
            serialization.Encoding.DER,
            serialization.PublicFormat.SubjectPublicKeyInfo,
        )
        csr_spki = csr.public_key().public_bytes(
            serialization.Encoding.DER,
            serialization.PublicFormat.SubjectPublicKeyInfo,
        )
        if leaf_spki != csr_spki:
            raise RegistrationError(
                "returned identity certificate does not bind the key this "
                "node generated for its CSR"
            )
        if not pki.verify_chain(leaf, [intermediate], root):
            raise RegistrationError(
                "returned certificate chain fails path validation"
            )
        if self.expected_root is None:
            import logging

            logging.getLogger(__name__).warning(
                "no expected_root configured: trusting the doorman's root "
                "on first use — pin the network trust root in production"
            )
            return
        root_der = root.public_bytes(serialization.Encoding.DER)
        if isinstance(self.expected_root, str):
            import hashlib

            got = hashlib.sha256(root_der).hexdigest()
            want = self.expected_root.lower().replace(":", "")
            if got != want:
                raise RegistrationError(
                    f"doorman root fingerprint {got} does not match the "
                    f"pinned trust root {want}"
                )
        else:
            want_der = self.expected_root.public_bytes(
                serialization.Encoding.DER
            )
            if root_der != want_der:
                raise RegistrationError(
                    "doorman root certificate does not match the pinned "
                    "network trust root"
                )

    def _install(self, chain, key) -> None:
        """Persist leaf + chain + key as the node's identity material
        (reference: keystore writes at the end of registration)."""
        leaf, intermediate, root = chain  # length checked in _validate
        pki.write_cert_store(
            self.cert_dir,
            identity=pki.CertAndKey(cert=leaf, key=key),
            intermediate=pki.CertAndKey(cert=intermediate, key=None),
            root=pki.CertAndKey(cert=root, key=None),
        )


# --- server side (a working doorman) -----------------------------------------

class DoormanServer:
    """Registration service: issues node CA certs under a root/intermediate
    it controls. auto_approve=False holds requests for .approve(id)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 auto_approve: bool = True):
        self.root = pki.create_self_signed_ca("Doorman Root CA")
        self.intermediate = pki.create_intermediate_ca(self.root)
        self.auto_approve = auto_approve
        self._requests: Dict[str, dict] = {}
        self._lock = lockorder.make_lock("DoormanServer._lock")
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _json(self, code: int, value) -> None:
                body = json.dumps(value).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                if self.path != "/certificate":
                    self._json(404, {"error": "no route"})
                    return
                length = int(self.headers.get("Content-Length", 0))
                pem = self.rfile.read(length)
                try:
                    request_id = outer._submit(pem)
                except Exception as exc:
                    self._json(400, {"error": str(exc)})
                    return
                self._json(200, {"request_id": request_id})

            def do_GET(self):
                prefix = "/certificate/"
                if not self.path.startswith(prefix):
                    self._json(404, {"error": "no route"})
                    return
                entry = outer._requests.get(self.path[len(prefix):])
                if entry is None:
                    self._json(404, {"error": "unknown request"})
                    return
                self._json(200, outer._status_body(entry))

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="doorman", daemon=True
        )
        self._thread.start()

    # -- protocol ------------------------------------------------------------

    def _submit(self, pem: bytes) -> str:
        csr = x509.load_pem_x509_csr(pem)
        if not csr.is_signature_valid:
            raise RegistrationError("CSR signature invalid")
        request_id = str(uuid.uuid4())
        with self._lock:
            self._requests[request_id] = {"csr": csr, "status": "PENDING",
                                          "certs": None, "reason": None}
        if self.auto_approve:
            self.approve(request_id)
        return request_id

    def approve(self, request_id: str) -> None:
        with self._lock:
            entry = self._requests[request_id]
            cert = pki.sign_csr(self.intermediate, entry["csr"], is_ca=True)
            entry["certs"] = [cert, self.intermediate.cert, self.root.cert]
            entry["status"] = "APPROVED"

    def reject(self, request_id: str, reason: str = "rejected") -> None:
        with self._lock:
            entry = self._requests[request_id]
            entry["status"] = "REJECTED"
            entry["reason"] = reason

    def _status_body(self, entry: dict) -> dict:
        body = {"status": entry["status"], "reason": entry["reason"]}
        if entry["certs"]:
            body["certificates"] = [
                base64.b64encode(
                    c.public_bytes(serialization.Encoding.PEM)
                ).decode()
                for c in entry["certs"]
            ]
        return body

    def pending(self):
        with self._lock:
            return [
                rid for rid, e in self._requests.items()
                if e["status"] == "PENDING"
            ]

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)

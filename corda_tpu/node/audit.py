"""Audit service (reference `node/.../services/api/AuditService.kt:125-133`
— the reference defines the interface and installs a no-op
`DummyAuditService`; here the in-memory implementation is real, bounded,
and wired to flow lifecycle + notary commits).
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


@dataclass(frozen=True)
class AuditEvent:
    timestamp: float
    principal: str        # node legal name or flow id
    event_type: str       # e.g. "flow.started", "notary.commit"
    context: Dict = field(default_factory=dict)


class AuditService:
    """Interface: implementations must be non-blocking and never raise."""

    def record(self, event: AuditEvent) -> None:  # pragma: no cover
        raise NotImplementedError

    def record_event(self, principal: str, event_type: str, **context) -> None:
        self.record(
            AuditEvent(time.time(), principal, event_type, dict(context))
        )


class DummyAuditService(AuditService):
    """Drops everything (the reference default)."""

    def record(self, event: AuditEvent) -> None:
        pass


class MemoryAuditService(AuditService):
    """Bounded in-memory trail with filtered reads."""

    def __init__(self, capacity: int = 10_000):
        self._events: deque = deque(maxlen=capacity)
        self._observers: List[Callable[[AuditEvent], None]] = []

    def record(self, event: AuditEvent) -> None:
        self._events.append(event)
        for obs in list(self._observers):
            try:
                obs(event)
            except Exception:
                pass  # audit fan-out must never break the caller

    def subscribe(self, observer: Callable[[AuditEvent], None]) -> None:
        self._observers.append(observer)

    def events(
        self,
        event_type: Optional[str] = None,
        principal: Optional[str] = None,
    ) -> List[AuditEvent]:
        return [
            e for e in self._events
            if (event_type is None or e.event_type == event_type)
            and (principal is None or e.principal == principal)
        ]

    def __len__(self) -> int:
        return len(self._events)

"""Network map: the directory-node protocol + per-node client + P2P bridges.

Reference parity:
  * `node/src/main/kotlin/net/corda/node/services/network/
    NetworkMapService.kt:65-71` — REGISTER / FETCH / QUERY / SUBSCRIBE /
    PUSH topics served by a designated directory node, with **signed**
    `NodeRegistration`s (serial-numbered ADD/REMOVE, expiry);
  * `InMemoryNetworkMapCache` — the client-side cache each node keeps
    (corda_tpu.node.services.NetworkMapCache);
  * `ArtemisMessagingServer.kt:299-412` — store-and-forward **bridges**
    deployed from network-map changes: outbound messages queue durably on
    the local broker and a bridge forwards them to the peer's broker,
    retrying while the peer is down.

Topology here: the map service runs in a node process and serves over
that node's TCP broker (`netmap.requests` queue).  Other nodes connect
with a RemoteBroker, REGISTER a signed entry carrying their own broker
address, FETCH the current map, and SUBSCRIBE for pushes.  The
registration signature is checked against the party key inside the entry
(a malicious node cannot forge someone else's mapping).
"""
from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..core.crypto import crypto
from ..core.identity import Party
from ..utils import atomicfile, lockorder
from ..core.serialization.codec import (
    deserialize,
    register_adapter,
    serialize,
)

NETWORK_MAP_QUEUE = "netmap.requests"

ADD = "ADD"
REMOVE = "REMOVE"


@dataclass(frozen=True)
class NodeRegistration:
    """One signed directory entry (reference NodeRegistration)."""

    party: Party
    broker_address: str      # HOST:PORT of the node's broker
    advertised_services: tuple
    serial: int              # monotonically increasing per party
    expires_at: float        # unix seconds
    reg_type: str = ADD      # ADD | REMOVE

    def signable_bytes(self) -> bytes:
        return serialize(
            {
                "party": self.party,
                "addr": self.broker_address,
                "services": tuple(self.advertised_services),
                "serial": self.serial,
                "expires": self.expires_at,
                "type": self.reg_type,
            }
        )


@dataclass(frozen=True)
class SignedRegistration:
    registration: NodeRegistration
    signature: bytes

    def verify(self) -> bool:
        try:
            key = self.registration.party.owning_key
            data = self.registration.signable_bytes()
            from ..core.crypto.composite import (
                CompositeKey,
                CompositeSignaturesWithKeys,
            )

            if isinstance(key, CompositeKey):
                # A cluster member registers the shared composite identity
                # alone, and no single member can meet an f+1 threshold
                # (BFT clusters) — directory registration instead requires
                # at least one VALID signature by a constituent leaf key
                # (any member can vouch for / fail over the entry, the
                # trust model the reference gets from members registering
                # their own NodeInfo carrying the service identity).
                sigs = CompositeSignaturesWithKeys.deserialize(
                    self.signature
                )
                leaves = key.keys
                return bool(sigs.sigs) and all(
                    pub in leaves and crypto.is_valid(pub, sig, data)
                    for pub, sig in sigs.sigs
                )
            return crypto.is_valid(key, self.signature, data)
        except Exception:
            return False


register_adapter(
    NodeRegistration, "NodeRegistration",
    lambda r: {
        "party": r.party, "addr": r.broker_address,
        "services": tuple(r.advertised_services), "serial": r.serial,
        "expires": r.expires_at, "type": r.reg_type,
    },
    lambda d: NodeRegistration(
        d["party"], d["addr"], tuple(d["services"]), d["serial"],
        d["expires"], d["type"],
    ),
)
register_adapter(
    SignedRegistration, "SignedRegistration",
    lambda r: {"reg": r.registration, "sig": r.signature},
    lambda d: SignedRegistration(d["reg"], d["sig"]),
)


def sign_registration(reg: NodeRegistration, private_key) -> SignedRegistration:
    return SignedRegistration(reg, crypto.do_sign(private_key, reg.signable_bytes()))


def _entry_visible(domain: Optional[str], services) -> bool:
    """Is a map entry advertising `services` visible from `domain`'s
    scoped view?  `domain=None` means an UNSCOPED requester (no "domain"
    field in its fetch/subscribe — every pre-federation client), which
    sees the full map: the kill switch that keeps single-domain networks
    byte-identical.  A scoped requester sees its own domain, domainless
    entries, and advertised cross-domain gateways."""
    if domain is None:
        return True
    from .services import NetworkMapCache as _cache

    svc = tuple(services)
    entry_domain = _cache.domain_of_services(svc)
    return (
        entry_domain is None
        or entry_domain == domain
        or _cache.GATEWAY_SERVICE in svc
    )


class NetworkMapService:
    """The directory service (runs in the map node's process, serves over
    its broker).  Thread-per-service pull loop, mirroring the verifier
    worker's shape."""

    def __init__(self, broker, persist_path: Optional[str] = None):
        """persist_path: optional file the registration set survives
        restarts in (the reference's map is a persisted service; an
        in-memory map that forgets every peer when the directory node
        restarts breaks routing for any node that registered before —
        observed as a Raft term-war livelock when the map host is also a
        cluster member that gets killed and relaunched)."""
        self._broker = broker
        broker.create_queue(NETWORK_MAP_QUEUE)
        self._entries: Dict[str, SignedRegistration] = {}
        #: unsigned server-side liveness: when each entry's registrant
        #: last re-attempted registration (incl. "unchanged" fast-path)
        self._last_seen: Dict[str, float] = {}
        self._subscribers: Dict[str, None] = {}
        self._lock = lockorder.make_lock("NetworkMapService._lock")
        self._persist_path = persist_path
        if persist_path and os.path.exists(persist_path):
            try:
                with open(persist_path, "rb") as fh:
                    for signed in deserialize(fh.read()):
                        if signed.verify():
                            self._entries[signed.registration.party.name] = signed
            except Exception:
                pass  # corrupt map file: start empty, re-registrations heal
        self._stop = threading.Event()
        self._consumer = broker.create_consumer(NETWORK_MAP_QUEUE)
        self._thread = threading.Thread(
            target=self._run, name="network-map", daemon=True
        )

    def _persist(self) -> None:
        """Crash-safe rewrite (tmp + rename). Caller holds the lock."""
        if not self._persist_path:
            return
        try:
            blob = serialize(list(self._entries.values()))
            atomicfile.write_atomic(self._persist_path, blob)
        except Exception:
            pass  # persistence is best-effort; the live map still serves

    def start(self) -> "NetworkMapService":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._consumer.close()

    # -- protocol ------------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            msg = self._consumer.receive(timeout=0.2)
            if msg is None:
                continue
            try:
                request = deserialize(msg.payload)
                self._handle(request)
            except Exception:
                pass  # malformed request must not kill the directory
            self._consumer.ack(msg)

    def _handle(self, request: dict) -> None:
        kind = request.get("kind")
        reply_to = request.get("reply_to")
        if kind == "register":
            signed: SignedRegistration = request["registration"]
            ok, reason = self._process_registration(signed)
            if reply_to:
                self._reply(reply_to, {"kind": "register-ack", "ok": ok,
                                       "error": reason,
                                       "req_id": request.get("req_id")})
            if ok and reason != "unchanged":
                self._push({"kind": "push", "registration": signed})
        elif kind == "fetch":
            now = time.time()
            domain = request.get("domain")  # absent = unscoped full map
            with self._lock:
                entries = [
                    s for s in self._entries.values()
                    if s.registration.reg_type == ADD
                    and s.registration.expires_at > now
                    and _entry_visible(
                        domain, s.registration.advertised_services
                    )
                ]
            if reply_to:
                self._reply(reply_to, {"kind": "fetch-reply", "entries": entries})
        elif kind == "subscribe":
            queue = request.get("queue")
            if queue:
                with self._lock:
                    # value = the subscriber's domain scope (None =
                    # unscoped: receives every push, pre-federation shape)
                    self._subscribers[queue] = request.get("domain")
                if reply_to:
                    self._reply(reply_to, {"kind": "subscribe-ack", "ok": True})
        elif kind == "query":
            name = request.get("name")
            with self._lock:
                signed = self._entries.get(name)
                last_seen = self._last_seen.get(name)
            if signed is not None and (
                signed.registration.reg_type == REMOVE
                or signed.registration.expires_at < time.time()
            ):
                signed = None
            if reply_to:
                self._reply(reply_to, {
                    "kind": "query-reply", "entry": signed,
                    # server-side liveness as an AGE (seconds since the
                    # registrant's last accepted attempt, incl.
                    # "unchanged" ones): an age survives cross-machine
                    # clock skew where an absolute timestamp would not
                    "last_seen_age": (
                        time.time() - last_seen
                        if last_seen is not None else None
                    ),
                    "req_id": request.get("req_id"),
                })

    def _process_registration(self, signed) -> tuple:
        if not isinstance(signed, SignedRegistration):
            return False, "not a SignedRegistration"
        reg = signed.registration
        if not signed.verify():
            return False, "bad signature"
        if reg.expires_at < time.time():
            return False, "expired"
        with self._lock:
            current = self._entries.get(reg.party.name)
            if current is not None and current.registration.serial >= reg.serial:
                return False, "stale serial"
            if current is not None:
                cr = current.registration
                now = time.time()
                # "far from expiry" must be judged against the entry's OWN
                # lifetime: the stored expiry has to outlast the client's
                # TTL/2 refresh cadence, or refreshes would stop extending
                # it and the entry would race its own expiry
                new_lifetime = reg.expires_at - now
                if (
                    cr.reg_type == reg.reg_type
                    and cr.broker_address == reg.broker_address
                    and tuple(cr.advertised_services)
                    == tuple(reg.advertised_services)
                    and cr.expires_at - now > 0.75 * new_lifetime
                ):
                    # fast shared-identity refreshes re-register every few
                    # seconds as a liveness signal; an operationally
                    # IDENTICAL entry far from expiry needs no rewrite of
                    # the persisted map and no push to every subscriber
                    self._last_seen[reg.party.name] = now
                    return True, "unchanged"
            # REMOVE entries are retained (not popped) so their serial
            # still orders against late ADDs; fetch/query filter them out.
            self._entries[reg.party.name] = signed
            self._last_seen[reg.party.name] = time.time()
            self._persist()
        return True, None

    def _reply(self, queue: str, payload: dict) -> None:
        try:
            self._broker.create_queue(queue)
            self._broker.send(queue, serialize(payload))
        except Exception:
            pass

    def _push(self, payload: dict) -> None:
        blob = serialize(payload)
        signed = payload.get("registration")
        services = (
            signed.registration.advertised_services
            if isinstance(signed, SignedRegistration) else ()
        )
        with self._lock:
            subscribers = list(self._subscribers.items())
        for queue, domain in subscribers:
            if not _entry_visible(domain, services):
                continue  # outside the subscriber's domain scope
            try:
                self._broker.send(queue, blob)
            except Exception:
                with self._lock:
                    self._subscribers.pop(queue, None)

    # -- introspection -------------------------------------------------------

    def entries(self) -> List[SignedRegistration]:
        with self._lock:
            return list(self._entries.values())


class NetworkMapClient:
    """Per-node client: register self, fetch the map, subscribe to pushes;
    feeds the node's NetworkMapCache + identity service and the bridge
    router (reference AbstractNode.registerWithNetworkMapIfConfigured,
    `AbstractNode.kt:584-621`)."""

    def __init__(self, map_broker, me: Party, my_address: str,
                 advertised_services, identity_private_key,
                 on_entry: Callable[[NodeRegistration], None],
                 on_remove: Optional[Callable[[NodeRegistration], None]] = None,
                 extra_identities=None,
                 extra_refresh_interval: float = 20.0):
        """extra_identities: [(party, advertised_services, signer)] also
        registered at this node's address — a notary CLUSTER member
        advertises the cluster's composite identity this way, signing the
        entry with its own leaf key wrapped as a threshold-satisfying
        composite signature (reference: ServiceIdentityGenerator-produced
        identities entering the network map).

        extra_refresh_interval: SHARED identities re-register on this fast
        cadence from EVERY member (vs the node's own TTL/2 refresh). The
        shared entry's route points at whichever member registered last;
        when that member dies, another live member's next re-registration
        replaces the route within one interval and the peers' bridges
        reconnect to it — cluster availability does not wait for the
        12-hour TTL refresh (reference parity: service addresses reach any
        live member)."""
        self._broker = map_broker
        self._me = me
        self._my_address = my_address
        self._advertised = tuple(advertised_services)
        # domain scope, derived from our own advertised tags: a node in a
        # domain asks the directory only for its own segment (+ gateways);
        # a domainless node sends NO domain field — the exact
        # pre-federation request bytes (kill switch). A GATEWAY asks
        # unscoped too: it anchors cross-domain protocol legs (the
        # notary-change ASSUME resolves its back-chain from a
        # foreign-domain client), so a scoped view would strand the
        # sessions it must serve.
        from .services import NetworkMapCache as _cache

        self._domain = (
            None if _cache.GATEWAY_SERVICE in self._advertised
            else _cache.domain_of_services(self._advertised)
        )
        self._key = identity_private_key
        self._extra_identities = list(extra_identities or [])
        self._on_entry = on_entry
        self._on_remove = on_remove
        self._serial = int(time.time() * 1000)
        self._req_counter = 0
        self._ttl = 24 * 3600.0  # registration lifetime (refreshed at TTL/2)
        self._reply_queue = f"netmap.reply.{me.name}"
        self._push_queue = f"netmap.push.{me.name}"
        map_broker.create_queue(self._reply_queue)
        map_broker.create_queue(self._push_queue)
        self._reply_consumer = map_broker.create_consumer(self._reply_queue)
        self._push_consumer = map_broker.create_consumer(self._push_queue)
        self._stop = threading.Event()
        self._extra_refresh_interval = float(extra_refresh_interval)
        # serializes reply-queue conversations across the refresh threads
        self._reg_lock = lockorder.make_lock("NetworkMapClient._reg_lock")
        self._push_thread = threading.Thread(
            target=self._consume_pushes, name=f"netmap-push-{me.name}",
            daemon=True,
        )

    # -- startup handshake ---------------------------------------------------

    def register_and_fetch(self, timeout: float = 15.0,
                           ttl: Optional[float] = None,
                           startup_window: float = 120.0) -> int:
        """REGISTER self + SUBSCRIBE + FETCH; apply entries; returns the
        number of peers learned. Raises on registration rejection. A
        background thread re-registers at TTL/2 so a long-running node
        never silently expires out of the directory.

        The first REGISTER retries for up to `startup_window` seconds on
        transient failures — the runnodes script (and any orchestrator)
        launches every node concurrently, so the directory node's broker,
        its `netmap.requests` queue, or its consumer may simply not exist
        yet. Permanent rejections (RuntimeError) still raise immediately."""
        from ..messaging import UnknownQueueError

        if ttl is not None:
            self._ttl = ttl
        deadline = time.monotonic() + startup_window
        while True:
            try:
                self._register(timeout, extras_force=True)
                break
            except (UnknownQueueError, ConnectionError, OSError,
                    TimeoutError):
                if time.monotonic() >= deadline:
                    raise
                time.sleep(1.0)
        self._refresh_thread = threading.Thread(
            target=self._refresh_loop, name=f"netmap-refresh-{self._me.name}",
            daemon=True,
        )
        self._refresh_thread.start()
        subscribe = {"kind": "subscribe", "queue": self._push_queue,
                     "reply_to": self._reply_queue}
        fetch = {"kind": "fetch", "reply_to": self._reply_queue}
        if self._domain is not None:
            subscribe["domain"] = self._domain
            fetch["domain"] = self._domain
        self._request(subscribe)
        self._await_reply("subscribe-ack", timeout)
        self._request(fetch)
        reply = self._await_reply("fetch-reply", timeout)
        count = 0
        for signed in reply.get("entries", []):
            if self._apply(signed):
                count += 1
        self._push_thread.start()
        # started only now: the fast loop shares the reply queue (under
        # _reg_lock) and must not race the unlocked startup fetch above
        if self._extra_identities and self._extra_refresh_interval > 0:
            self._extra_thread = threading.Thread(
                target=self._extra_refresh_loop,
                name=f"netmap-cluster-refresh-{self._me.name}", daemon=True,
            )
            self._extra_thread.start()
        return count

    def _next_req_id(self) -> str:
        self._req_counter += 1
        return f"{self._me.name}:{self._req_counter}"

    def _register(self, timeout: float, extras_force: bool = False) -> None:
        with self._reg_lock:
            self._serial += 1
            reg = NodeRegistration(
                self._me, self._my_address, self._advertised,
                serial=self._serial, expires_at=time.time() + self._ttl,
            )
            req_id = self._next_req_id()
            self._request(
                {"kind": "register",
                 "registration": sign_registration(reg, self._key),
                 "reply_to": self._reply_queue, "req_id": req_id},
            )
            ack = self._await_reply("register-ack", timeout, req_id=req_id)
            if not ack.get("ok"):
                raise RuntimeError(
                    f"network map rejected registration: {ack.get('error')}"
                )
        # The BOOT registration always stamps the shared entry (the
        # holder-liveness gate applies only to periodic refreshes). This
        # keeps the LAST-booted member as the initial route holder, which
        # matters when an earlier member co-hosts the network map: if the
        # gate left the route on the map host, one kill would take down
        # both the route AND the only service able to move it (observed
        # as a full-cluster notarisation stall). The TTL/2 refresh passes
        # extras_force=False so it cannot steal a live holder's route.
        self._register_extras(timeout, force=extras_force)

    def _query_entry(self, name: str, timeout: float):
        """(signed_entry | None, last_seen_age | None) for a map name."""
        with self._reg_lock:
            req_id = self._next_req_id()
            self._request({"kind": "query", "name": name,
                           "reply_to": self._reply_queue, "req_id": req_id})
            reply = self._await_reply("query-reply", timeout, req_id=req_id)
        return reply.get("entry"), reply.get("last_seen_age")

    def _register_extras(self, timeout: float, force: bool = False) -> None:
        for party, services, signer in self._extra_identities:
            if not force:
                # holder-liveness gate: when the shared entry's current
                # holder (another member) is actively refreshing, skip our
                # re-registration — otherwise N members would rotate the
                # route every interval, re-persisting and re-pushing the
                # map in steady state for no operational change. We take
                # over only when the holder's attempts stop (dead) or the
                # entry is ours to extend.
                try:
                    entry, age = self._query_entry(party.name, timeout)
                except Exception:
                    entry, age = None, None
                if (
                    entry is not None
                    and entry.registration.broker_address != self._my_address
                    and age is not None
                    and age < 2 * self._extra_refresh_interval
                ):
                    continue
            # SHARED key (e.g. a cluster identity all members register):
            # serials must order across PROCESSES, so each registration
            # takes a fresh wall-clock-ms serial — per-client counters
            # seeded at different times would pin the entry to whichever
            # member booted last and lock surviving members out of
            # re-registering after it dies (no failover).
            reg = NodeRegistration(
                party, self._my_address, tuple(services),
                serial=int(time.time() * 1000),
                expires_at=time.time() + self._ttl,
            )
            with self._reg_lock:
                req_id = self._next_req_id()
                self._request(
                    {"kind": "register",
                     "registration": SignedRegistration(
                         reg, signer(reg.signable_bytes())
                     ),
                     "reply_to": self._reply_queue, "req_id": req_id},
                )
                ack = self._await_reply("register-ack", timeout, req_id=req_id)
            if not ack.get("ok") and "stale serial" not in str(
                ack.get("error", "")
            ):
                raise RuntimeError(
                    f"network map rejected {party.name} registration: "
                    f"{ack.get('error')}"
                )
            # "stale serial" = another member registered the shared
            # identity in the same millisecond — benign; its entry serves

    def _refresh_loop(self) -> None:
        while not self._stop.wait(self._ttl / 2):
            try:
                self._register(timeout=15.0)
            except Exception:
                pass  # map temporarily unreachable; retry next period

    def _extra_refresh_loop(self) -> None:
        """Fast shared-identity refresh: keep the cluster route pointing
        at a LIVE member (see __init__'s extra_refresh_interval note)."""
        import logging

        while not self._stop.wait(self._extra_refresh_interval):
            try:
                self._register_extras(timeout=10.0)
            except RuntimeError as exc:
                # a PERMANENT rejection (bad signature etc.) silently
                # disables failover for this member — make it visible
                logging.getLogger(__name__).warning(
                    "shared-identity refresh rejected: %s", exc
                )
            except Exception:
                pass  # map temporarily unreachable; retry next period

    def _request(self, payload: dict) -> None:
        self._broker.send(NETWORK_MAP_QUEUE, serialize(payload))

    def _await_reply(self, kind: str, timeout: float,
                     req_id: Optional[str] = None) -> dict:
        """Wait for a matching reply; non-matching replies are discarded.

        `req_id` correlates register conversations: a register-ack whose
        req_id differs is a STALE ack from a conversation that timed out
        earlier — without the correlation, one timeout would permanently
        shift every later conversation onto the previous one's ack."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            msg = self._reply_consumer.receive(
                timeout=max(0.05, deadline - time.monotonic())
            )
            if msg is None:
                continue
            self._reply_consumer.ack(msg)
            reply = deserialize(msg.payload)
            if reply.get("kind") != kind:
                continue
            if req_id is not None and reply.get("req_id") != req_id:
                continue  # stale ack from a timed-out conversation
            return reply
        raise TimeoutError(f"no {kind} from network map")

    # -- push subscription ---------------------------------------------------

    def _consume_pushes(self) -> None:
        from ..messaging import QueueClosedError

        while not self._stop.is_set():
            try:
                msg = self._push_consumer.receive(timeout=0.2)
            except QueueClosedError:
                return  # map broker gone; subscription ends
            if msg is None:
                if getattr(self._push_consumer, "_closed", False):
                    return
                continue
            try:
                payload = deserialize(msg.payload)
                if payload.get("kind") == "push":
                    self._apply(payload["registration"])
            except Exception:
                pass
            self._push_consumer.ack(msg)

    def _apply(self, signed: SignedRegistration) -> bool:
        if not isinstance(signed, SignedRegistration) or not signed.verify():
            return False
        reg = signed.registration
        if reg.party.name == self._me.name:
            return False
        if reg.reg_type == REMOVE:
            if self._on_remove is not None:
                self._on_remove(reg)
            return False
        self._on_entry(reg)
        return True

    def stop(self) -> None:
        self._stop.set()
        self._reply_consumer.close()
        self._push_consumer.close()


class BridgeManager:
    """Store-and-forward bridges to peer brokers (ArtemisMessagingServer.
    deployBridge, `ArtemisMessagingServer.kt:299-412,377-400`).

    Outbound P2P messages for a remote peer are enqueued durably on the
    LOCAL broker (`p2p.outbound.<peer>`); one forwarder thread per peer
    drains that queue into the peer's broker over TCP, acking only after
    the remote send succeeds — so messages survive local restarts and peer
    downtime, with redelivery on reconnect."""

    def __init__(self, local_broker, remote_broker_factory=None):
        from ..messaging.net import RemoteBroker

        self._local = local_broker
        self._addresses: Dict[str, str] = {}
        self._threads: Dict[str, threading.Thread] = {}
        self._lock = lockorder.make_lock("BridgeManager._lock")
        self._stop = threading.Event()
        self._factory = remote_broker_factory or (
            lambda host, port: RemoteBroker(host, port)
        )

    def set_route(self, peer_name: str, broker_address: str) -> None:
        # Create the outbound queue BEFORE publishing the route: a sender
        # gated on route_for() must never race the forwarder thread into
        # an UnknownQueueError.
        self._local.create_queue(
            self.outbound_queue(peer_name),
            durable=getattr(self._local, "_journal_dir", None) is not None,
        )
        with self._lock:
            self._addresses[peer_name] = broker_address
            if peer_name not in self._threads:
                t = threading.Thread(
                    target=self._forward, args=(peer_name,),
                    name=f"bridge-{peer_name}", daemon=True,
                )
                self._threads[peer_name] = t
                t.start()

    def route_for(self, peer_name: str) -> Optional[str]:
        with self._lock:
            return self._addresses.get(peer_name)

    def outbound_queue(self, peer_name: str) -> str:
        return f"p2p.outbound.{peer_name}"

    #: max messages drained into one cross-process round trip; bounded so
    #: a burst cannot build an arbitrarily large frame
    BATCH = 64

    def _forward(self, peer_name: str) -> None:
        queue = self.outbound_queue(peer_name)  # created by set_route
        consumer = self._local.create_consumer(queue)
        remote = None
        while not self._stop.is_set():
            msg = consumer.receive(timeout=0.2)
            if msg is None:
                continue
            # Drain whatever else is queued (non-blocking) so the whole
            # batch crosses the process boundary in ONE round trip —
            # per-message round trips were the system-throughput ceiling
            # (~2-4 ms each under load; round-3 profile).
            batch = [msg]
            while len(batch) < self.BATCH:
                extra = consumer.receive(timeout=0)
                if extra is None:
                    break
                batch.append(extra)
            delivered = False
            while not delivered and not self._stop.is_set():
                try:
                    if remote is None:
                        with self._lock:
                            addr = self._addresses[peer_name]
                        host, port_s = addr.rsplit(":", 1)
                        remote = self._factory(host, int(port_s))
                    remote.send_many([
                        (f"p2p.inbound.{peer_name}", m.payload, m.headers)
                        for m in batch
                    ])
                    delivered = True
                except Exception as exc:
                    # Peer down: drop the connection, back off, retry —
                    # store-and-forward semantics.
                    import logging as _logging

                    _logging.getLogger(__name__).warning(
                        "bridge %s: delivery failed (%s: %s); retrying",
                        peer_name, type(exc).__name__, exc,
                    )
                    try:
                        if remote is not None:
                            remote.close()
                    except Exception:
                        pass
                    remote = None
                    self._stop.wait(0.5)
            if delivered:
                for m in batch:
                    consumer.ack(m)
        if remote is not None:
            try:
                remote.close()
            except Exception:
                pass
        consumer.close()

    def stop(self) -> None:
        self._stop.set()

"""Raft consensus for the distributed notary commit log.

Reference: `node/.../transactions/RaftUniquenessProvider.kt` delegates to
the Copycat library (CopycatServer + DistributedImmutableMap state machine,
`RaftUniquenessProvider.kt:71-156`).  The TPU build implements Raft itself
over the framework's messaging layer — leader election with randomized
timeouts, log replication via AppendEntries, quorum commit — applying
`PutAll` commands to a persisted uniqueness map (the DistributedImmutableMap
equivalent, `DistributedImmutableMap.kt:23-120`).

Determinism: the node is driven externally — `tick(now)` advances election/
heartbeat timers and `on_message` handles peer traffic — so tests step a
cluster through elections, partitions, and leader kills without real time.

Scope: leadership, replication, commit, and term safety are implemented;
log compaction/snapshotting is not (the uniqueness log is append-only and
bounded by ledger growth, matching the reference's usage pattern).
"""
from __future__ import annotations

import random
import threading
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..core.serialization.codec import deserialize, serialize
from .database import KVStore, NodeDatabase

RAFT_TOPIC = "platform.raft"

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


@dataclass
class LogEntry:
    term: int
    command: dict  # {"kind": "putall", "entries": {...}, "request_id": str}


class RaftNode:
    """One Raft replica.

    transport: send(peer_id: str, payload: bytes); incoming messages are fed
    to `on_message(sender_id, payload)` by the owner.
    apply_fn(command) -> result: applied exactly once per committed entry,
    in log order, on every replica.
    """

    # Timeouts in abstract "time units" — callers pass a consistent now().
    ELECTION_TIMEOUT = (10, 20)  # randomized range
    HEARTBEAT_INTERVAL = 3

    def __init__(
        self,
        node_id: str,
        peer_ids: List[str],
        transport: Callable[[str, bytes], None],
        apply_fn: Callable[[dict], object],
        db: Optional[NodeDatabase] = None,
        seed: Optional[int] = None,
    ):
        self.node_id = node_id
        self.peer_ids = [p for p in peer_ids if p != node_id]
        self.transport = transport
        self.apply_fn = apply_fn
        self._rand = random.Random(seed if seed is not None else node_id)
        self._lock = threading.RLock()
        # persistent state: meta (term/vote) + one KV row per log entry, so
        # heartbeats cost nothing and appends are O(1), not O(log).
        self._meta = KVStore(db, "raft_meta") if db is not None else None
        self._log_store = KVStore(db, "raft_log") if db is not None else None
        self.current_term = 0
        self.voted_for: Optional[str] = None
        self.log: List[LogEntry] = []
        if self._meta is not None:
            self._load_persistent()
        # volatile state
        self.role = FOLLOWER
        self.leader_id: Optional[str] = None
        self.commit_index = -1
        self.last_applied = -1
        self.next_index: Dict[str, int] = {}
        self.match_index: Dict[str, int] = {}
        self._votes: set = set()
        self._last_heard = 0.0
        self._last_heartbeat = 0.0
        self._election_deadline = 0.0
        self._now = 0.0
        # request_id -> future (leader only)
        self._pending: Dict[str, Future] = {}
        self._reset_election_deadline()

    # -- persistence ---------------------------------------------------------

    @staticmethod
    def _log_key(index: int) -> bytes:
        return index.to_bytes(8, "big")

    def _load_persistent(self) -> None:
        term = self._meta.get(b"term")
        if term is not None:
            self.current_term = deserialize(term)
        vote = self._meta.get(b"voted_for")
        if vote is not None:
            self.voted_for = deserialize(vote)
        rows = sorted(self._log_store.items(), key=lambda kv: kv[0])
        self.log = [
            LogEntry(*deserialize(v)) for _, v in rows
        ]

    def _persist_meta(self) -> None:
        if self._meta is None:
            return
        self._meta.put(b"term", serialize(self.current_term))
        self._meta.put(b"voted_for", serialize(self.voted_for))

    def _persist_log_from(self, start: int) -> None:
        """Write log rows [start:); callers handle truncation separately."""
        if self._log_store is None:
            return
        for i in range(start, len(self.log)):
            e = self.log[i]
            self._log_store.put(self._log_key(i), serialize([e.term, e.command]))

    def _persist_log_truncate(self, from_index: int) -> None:
        if self._log_store is None:
            return
        for k, _ in list(self._log_store.items()):
            if int.from_bytes(k, "big") >= from_index:
                self._log_store.delete(k)

    # -- public API ----------------------------------------------------------

    @property
    def is_leader(self) -> bool:
        return self.role == LEADER

    def submit(self, command: dict) -> Future:
        """Leader: append + replicate; resolves with apply result when the
        entry commits.  Non-leader: fails fast with the leader hint."""
        fut: Future = Future()
        with self._lock:
            if self.role != LEADER:
                fut.set_exception(NotLeaderError(self.leader_id))
                return fut
            request_id = command.get("request_id") or f"{self.node_id}:{len(self.log)}:{self.current_term}"
            command = dict(command, request_id=request_id)
            self.log.append(LogEntry(self.current_term, command))
            self._persist_log_from(len(self.log) - 1)
            self._pending[request_id] = fut
            # Single-node cluster commits immediately.
            self._advance_commit()
            for peer in self.peer_ids:
                self._send_append(peer)
        return fut

    def tick(self, now: float) -> None:
        """Advance timers: follower/candidate election timeout, leader
        heartbeats."""
        with self._lock:
            self._now = now
            if self.role == LEADER:
                if now - self._last_heartbeat >= self.HEARTBEAT_INTERVAL:
                    self._last_heartbeat = now
                    for peer in self.peer_ids:
                        self._send_append(peer)
            else:
                if now >= self._election_deadline:
                    self._start_election()

    def on_message(self, sender_id: str, payload: bytes) -> None:
        msg = deserialize(payload)
        with self._lock:
            kind = msg["kind"]
            if msg["term"] > self.current_term:
                self._become_follower(msg["term"])
            if kind == "request_vote":
                self._on_request_vote(sender_id, msg)
            elif kind == "vote":
                self._on_vote(sender_id, msg)
            elif kind == "append":
                self._on_append(sender_id, msg)
            elif kind == "append_reply":
                self._on_append_reply(sender_id, msg)

    # -- elections -----------------------------------------------------------

    def _reset_election_deadline(self) -> None:
        lo, hi = self.ELECTION_TIMEOUT
        self._election_deadline = self._now + self._rand.uniform(lo, hi)

    def _become_follower(self, term: int) -> None:
        self.current_term = term
        self.role = FOLLOWER
        self.voted_for = None
        self._votes.clear()
        self._fail_pending(NotLeaderError(None))
        self._persist_meta()
        self._reset_election_deadline()

    def _start_election(self) -> None:
        self.role = CANDIDATE
        self.current_term += 1
        self.voted_for = self.node_id
        self._votes = {self.node_id}
        self.leader_id = None
        self._persist_meta()
        self._reset_election_deadline()
        last_term = self.log[-1].term if self.log else -1
        for peer in self.peer_ids:
            self._send(peer, {
                "kind": "request_vote", "term": self.current_term,
                "last_log_index": len(self.log) - 1,
                "last_log_term": last_term,
            })
        self._maybe_win()

    def _on_request_vote(self, sender_id: str, msg: dict) -> None:
        grant = False
        if msg["term"] >= self.current_term and self.voted_for in (None, sender_id):
            my_last_term = self.log[-1].term if self.log else -1
            up_to_date = (
                msg["last_log_term"] > my_last_term
                or (
                    msg["last_log_term"] == my_last_term
                    and msg["last_log_index"] >= len(self.log) - 1
                )
            )
            if up_to_date:
                grant = True
                self.voted_for = sender_id
                self._persist_meta()
                self._reset_election_deadline()
        self._send(sender_id, {
            "kind": "vote", "term": self.current_term, "granted": grant,
        })

    def _on_vote(self, sender_id: str, msg: dict) -> None:
        if self.role != CANDIDATE or msg["term"] != self.current_term:
            return
        if msg["granted"]:
            self._votes.add(sender_id)
            self._maybe_win()

    def _maybe_win(self) -> None:
        quorum = (len(self.peer_ids) + 1) // 2 + 1
        if self.role == CANDIDATE and len(self._votes) >= quorum:
            self.role = LEADER
            self.leader_id = self.node_id
            self.next_index = {p: len(self.log) for p in self.peer_ids}
            self.match_index = {p: -1 for p in self.peer_ids}
            self._last_heartbeat = self._now
            for peer in self.peer_ids:
                self._send_append(peer)

    # -- replication ---------------------------------------------------------

    def _send_append(self, peer: str) -> None:
        ni = self.next_index.get(peer, len(self.log))
        prev_index = ni - 1
        prev_term = self.log[prev_index].term if prev_index >= 0 else -1
        entries = [[e.term, e.command] for e in self.log[ni:]]
        self._send(peer, {
            "kind": "append", "term": self.current_term,
            "prev_index": prev_index, "prev_term": prev_term,
            "entries": entries, "commit_index": self.commit_index,
        })

    def _on_append(self, sender_id: str, msg: dict) -> None:
        if msg["term"] < self.current_term:
            self._send(sender_id, {
                "kind": "append_reply", "term": self.current_term,
                "ok": False, "match_index": -1,
            })
            return
        self.role = FOLLOWER
        self.leader_id = sender_id
        self._reset_election_deadline()
        prev_index = msg["prev_index"]
        if prev_index >= 0 and (
            prev_index >= len(self.log)
            or self.log[prev_index].term != msg["prev_term"]
        ):
            self._send(sender_id, {
                "kind": "append_reply", "term": self.current_term,
                "ok": False, "match_index": -1,
            })
            return
        # Truncate conflicts, append new entries.
        idx = prev_index + 1
        first_change: Optional[int] = None
        truncated = False
        for term, command in msg["entries"]:
            if idx < len(self.log):
                if self.log[idx].term != term:
                    del self.log[idx:]
                    self.log.append(LogEntry(term, command))
                    truncated = True
                    if first_change is None:
                        first_change = idx
            else:
                self.log.append(LogEntry(term, command))
                if first_change is None:
                    first_change = idx
            idx += 1
        if first_change is not None:
            if truncated:
                self._persist_log_truncate(first_change)
            self._persist_log_from(first_change)
        if msg["commit_index"] > self.commit_index:
            self.commit_index = min(msg["commit_index"], len(self.log) - 1)
            self._apply_committed()
        # match up to what THIS append covered — not our whole log, which may
        # carry an uncommitted tail from a deposed leader beyond the new
        # leader's log (overstating would crash the leader's next send).
        self._send(sender_id, {
            "kind": "append_reply", "term": self.current_term,
            "ok": True, "match_index": prev_index + len(msg["entries"]),
        })

    def _on_append_reply(self, sender_id: str, msg: dict) -> None:
        if self.role != LEADER or msg["term"] != self.current_term:
            return
        if msg["ok"]:
            self.match_index[sender_id] = msg["match_index"]
            self.next_index[sender_id] = msg["match_index"] + 1
            self._advance_commit()
        else:
            self.next_index[sender_id] = max(0, self.next_index.get(sender_id, 1) - 1)
            self._send_append(sender_id)

    def _advance_commit(self) -> None:
        quorum = (len(self.peer_ids) + 1) // 2 + 1
        for n in range(len(self.log) - 1, self.commit_index, -1):
            if self.log[n].term != self.current_term:
                continue
            count = 1 + sum(
                1 for p in self.peer_ids if self.match_index.get(p, -1) >= n
            )
            if count >= quorum:
                self.commit_index = n
                self._apply_committed()
                break

    def _apply_committed(self) -> None:
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            entry = self.log[self.last_applied]
            result = self.apply_fn(entry.command)
            request_id = entry.command.get("request_id")
            fut = self._pending.pop(request_id, None) if request_id else None
            if fut is not None and not fut.done():
                fut.set_result(result)

    def _fail_pending(self, exc: Exception) -> None:
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self._pending.clear()

    def _send(self, peer: str, msg: dict) -> None:
        try:
            self.transport(peer, serialize(msg))
        except Exception:
            pass  # unreachable peer: Raft tolerates message loss


class NotLeaderError(Exception):
    def __init__(self, leader_hint: Optional[str]):
        super().__init__(f"not the leader (hint: {leader_hint})")
        self.leader_hint = leader_hint

"""Raft consensus for the distributed notary commit log.

Reference: `node/.../transactions/RaftUniquenessProvider.kt` delegates to
the Copycat library (CopycatServer + DistributedImmutableMap state machine,
`RaftUniquenessProvider.kt:71-156`).  The TPU build implements Raft itself
over the framework's messaging layer — leader election with randomized
timeouts, log replication via AppendEntries, quorum commit — applying
`PutAll` commands to a persisted uniqueness map (the DistributedImmutableMap
equivalent, `DistributedImmutableMap.kt:23-120`).

Determinism: the node is driven externally — `tick(now)` advances election/
heartbeat timers and `on_message` handles peer traffic — so tests step a
cluster through elections, partitions, and leader kills without real time.

Log compaction (Raft §7): once enough entries are applied, the state
machine snapshot (snapshot_fn/restore_fn hooks) replaces the applied log
prefix — the log no longer grows with ledger history, matching the
reference's log-compacting snapshottable DistributedImmutableMap
(`DistributedImmutableMap.kt:23-120`). Followers too far behind receive
an InstallSnapshot instead of unreachable AppendEntries.
"""
from __future__ import annotations

import random
import threading
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..core.serialization.codec import deserialize, serialize
from .database import KVStore, NodeDatabase

import logging as _logging
from ..utils import lockorder

logger = _logging.getLogger("corda_tpu.raft")

RAFT_TOPIC = "platform.raft"

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


@dataclass
class LogEntry:
    term: int
    command: dict  # {"kind": "putall", "entries": {...}, "request_id": str}


class RaftNode:
    """One Raft replica.

    transport: send(peer_id: str, payload: bytes); incoming messages are fed
    to `on_message(sender_id, payload)` by the owner.
    apply_fn(command) -> result: applied exactly once per committed entry,
    in log order, on every replica.
    """

    # Timeouts in abstract "time units" — callers pass a consistent now().
    ELECTION_TIMEOUT = (10, 20)  # randomized range
    HEARTBEAT_INTERVAL = 3
    #: applied entries kept in the log before a snapshot truncates them
    SNAPSHOT_THRESHOLD = 1000

    def __init__(
        self,
        node_id: str,
        peer_ids: List[str],
        transport: Callable[[str, bytes], None],
        apply_fn: Callable[[dict], object],
        db: Optional[NodeDatabase] = None,
        seed: Optional[int] = None,
        snapshot_fn: Optional[Callable[[], bytes]] = None,
        restore_fn: Optional[Callable[[bytes], None]] = None,
    ):
        self.node_id = node_id
        self.peer_ids = [p for p in peer_ids if p != node_id]
        self.transport = transport
        self.apply_fn = apply_fn
        # log compaction hooks: snapshot_fn captures the state machine,
        # restore_fn replaces it (Raft §7); without them the log is kept
        # whole (the pre-compaction behavior)
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        self._rand = random.Random(seed if seed is not None else node_id)
        self._lock = lockorder.make_rlock("RaftNode._lock")
        # persistent state: meta (term/vote/snapshot) + one KV row per log
        # entry, so heartbeats cost nothing and appends are O(1), not O(log).
        self._meta = KVStore(db, "raft_meta") if db is not None else None
        self._log_store = KVStore(db, "raft_log") if db is not None else None
        self.current_term = 0
        self.voted_for: Optional[str] = None
        self.log: List[LogEntry] = []
        # last logical index/term covered by the installed snapshot
        self.snap_index = -1
        self.snap_term = -1
        if self._meta is not None:
            self._load_persistent()
        # volatile state
        self.role = FOLLOWER
        self.leader_id: Optional[str] = None
        self.commit_index = self.snap_index
        self.last_applied = self.snap_index
        self.next_index: Dict[str, int] = {}
        self.match_index: Dict[str, int] = {}
        self._votes: set = set()
        self._last_heard = 0.0
        self._last_heartbeat = 0.0
        self._election_deadline = 0.0
        self._now = 0.0
        # request_id -> future (leader only)
        self._pending: Dict[str, Future] = {}
        # follower-forwarded client requests awaiting the leader's reply:
        # req id -> (future, expiry monotonic time)
        self._client_pending: Dict[str, Tuple[Future, float]] = {}
        self._client_seq = 0
        # prevote round state + leader-freshness for the stickiness check
        self._prevotes: set = set()
        self._last_leader_contact = float("-inf")
        self._reset_election_deadline()

    # -- logical-index helpers (the log may start after a snapshot) ----------

    def last_index(self) -> int:
        return self.snap_index + len(self.log)

    def _entry(self, logical: int) -> LogEntry:
        return self.log[logical - self.snap_index - 1]

    def _term_at(self, logical: int) -> int:
        if logical < 0:
            return -1
        if logical == self.snap_index:
            return self.snap_term
        return self._entry(logical).term

    # -- persistence ---------------------------------------------------------

    @staticmethod
    def _log_key(index: int) -> bytes:
        return index.to_bytes(8, "big")

    def _load_persistent(self) -> None:
        term = self._meta.get(b"term")
        if term is not None:
            self.current_term = deserialize(term)
        vote = self._meta.get(b"voted_for")
        if vote is not None:
            self.voted_for = deserialize(vote)
        snap = self._meta.get(b"snapshot")
        if snap is not None:
            meta = deserialize(self._meta.get(b"snapshot_meta"))
            self.snap_index, self.snap_term = meta[0], meta[1]
            if self.restore_fn is not None:
                self.restore_fn(bytes(snap))
        rows = sorted(self._log_store.items(), key=lambda kv: kv[0])
        self.log = [
            LogEntry(*deserialize(v))
            for k, v in rows
            if int.from_bytes(k, "big") > self.snap_index
        ]

    def _persist_meta(self) -> None:
        if self._meta is None:
            return
        self._meta.put(b"term", serialize(self.current_term))
        self._meta.put(b"voted_for", serialize(self.voted_for))

    def _persist_log_from(self, start_logical: int) -> None:
        """Write log rows [start_logical:); callers handle truncation
        separately. Row keys are LOGICAL indices."""
        if self._log_store is None:
            return
        for logical in range(start_logical, self.last_index() + 1):
            e = self._entry(logical)
            self._log_store.put(
                self._log_key(logical), serialize([e.term, e.command])
            )

    def _persist_log_truncate(self, from_logical: int) -> None:
        if self._log_store is None:
            return
        for k, _ in list(self._log_store.items()):
            if int.from_bytes(k, "big") >= from_logical:
                self._log_store.delete(k)

    # -- snapshotting (Raft §7) ----------------------------------------------

    def _maybe_snapshot(self) -> None:
        """Fold the applied log prefix into a state-machine snapshot once
        it is long enough. Caller holds the lock."""
        if self.snapshot_fn is None:
            return
        applied_in_log = self.last_applied - self.snap_index
        if applied_in_log < self.SNAPSHOT_THRESHOLD:
            return
        self._take_snapshot(self.last_applied)

    def _take_snapshot(self, upto_logical: int) -> None:
        data = self.snapshot_fn()
        new_term = self._term_at(upto_logical)
        # drop entries <= upto_logical
        self.log = self.log[upto_logical - self.snap_index:]
        self.snap_index = upto_logical
        self.snap_term = new_term
        if self._meta is not None:
            self._meta.put(b"snapshot", data)
            self._meta.put(
                b"snapshot_meta", serialize([self.snap_index, self.snap_term])
            )
            for k, _ in list(self._log_store.items()):
                if int.from_bytes(k, "big") <= upto_logical:
                    self._log_store.delete(k)

    def _install_snapshot(self, sender_id: str, msg: dict) -> None:
        """Follower side of InstallSnapshot."""
        if msg["term"] < self.current_term:
            return
        self.role = FOLLOWER
        self.leader_id = sender_id
        self._reset_election_deadline()
        idx, term = msg["snap_index"], msg["snap_term"]
        if idx <= self.snap_index:
            return  # stale snapshot
        if self.restore_fn is not None:
            self.restore_fn(bytes(msg["data"]))
        # Raft §7: if an existing entry matches the snapshot's last entry,
        # retain the following suffix; otherwise discard the whole log.
        if idx <= self.last_index() and self._term_at(idx) == term:
            self.log = self.log[idx - self.snap_index:]
        else:
            self.log = []
        self.snap_index = idx
        self.snap_term = term
        self.commit_index = max(self.commit_index, idx)
        self.last_applied = max(self.last_applied, idx)
        if self._meta is not None:
            self._meta.put(b"snapshot", bytes(msg["data"]))
            self._meta.put(b"snapshot_meta", serialize([idx, term]))
            self._persist_log_truncate(0)
            self._persist_log_from(idx + 1)
        self._send(sender_id, {
            "kind": "append_reply", "term": self.current_term,
            "ok": True, "match_index": idx,
        })

    # -- public API ----------------------------------------------------------

    @property
    def is_leader(self) -> bool:
        return self.role == LEADER

    def submit(self, command: dict) -> Future:
        """Leader: append + replicate; resolves with apply result when the
        entry commits.  Non-leader: fails fast with the leader hint."""
        fut: Future = Future()
        with self._lock:
            if self.role != LEADER:
                fut.set_exception(NotLeaderError(self.leader_id))
                return fut
            request_id = command.get("request_id") or f"{self.node_id}:{self.last_index() + 1}:{self.current_term}"
            command = dict(command, request_id=request_id)
            self.log.append(LogEntry(self.current_term, command))
            self._persist_log_from(self.last_index())
            self._pending[request_id] = fut
            # Single-node cluster commits immediately.
            self._advance_commit()
            for peer in self.peer_ids:
                self._send_append(peer)
        return fut

    def submit_anywhere(self, command: dict, timeout: float = 20.0) -> Future:
        """Submit from ANY member: leaders apply locally, followers forward
        the command to the current leader and resolve the returned future
        with the leader's reply (the CopycatClient-forwarding semantics the
        reference's notary cluster members rely on —
        `RaftUniquenessProvider.kt:71-156`). The future fails with
        NotLeaderError when no leader is known/reachable; callers retry."""
        import time as _time

        with self._lock:
            # sweep forwarded requests nobody is waiting on any more: a
            # leader that died before replying would otherwise leak one
            # future per retry for the process lifetime
            now = _time.monotonic()
            for rid in [
                r for r, (f, exp) in self._client_pending.items()
                if f.done() or now > exp
            ]:
                fut_exp = self._client_pending.pop(rid)
                if not fut_exp[0].done():
                    fut_exp[0].set_exception(NotLeaderError(None))
            if self.role == LEADER:
                pass  # fall through to local submit below (re-locks)
            else:
                leader = self.leader_id
                fut: Future = Future()
                if leader is None:
                    fut.set_exception(NotLeaderError(None))
                    return fut
                self._client_seq += 1
                req_id = f"c:{self.node_id}:{self._client_seq}"
                self._client_pending[req_id] = (fut, now + 60.0)
                logger.debug(
                    "%s forwarding client request %s to leader %s",
                    self.node_id, req_id, leader,
                )
                self._send(leader, {
                    "kind": "client_request",
                    "term": self.current_term,
                    "id": req_id,
                    "command": command,
                })
                return fut
        return self.submit(command)

    def _on_client_request(self, sender_id: str, msg: dict) -> None:
        """Leader side: run the forwarded command through the normal
        submit path and ship the result (or NotLeaderError) back."""
        req_id = msg["id"]

        logger.debug(
            "%s got client request %s from %s (role=%s)",
            self.node_id, req_id, sender_id, self.role,
        )

        def reply(ok, value):
            logger.debug(
                "%s replying to %s for %s: ok=%s",
                self.node_id, sender_id, req_id, ok,
            )
            self._send(sender_id, {
                "kind": "client_reply",
                "term": self.current_term,
                "id": req_id,
                "ok": ok,
                "value": value,
            })

        if self.role != LEADER:
            reply(False, self.leader_id)
            return
        inner = self.submit(msg["command"])

        def done(f: Future):
            try:
                reply(True, f.result())
            except Exception:
                reply(False, self.leader_id)

        inner.add_done_callback(done)

    def _on_client_reply(self, msg: dict) -> None:
        logger.debug(
            "%s got client reply %s ok=%s", self.node_id, msg["id"],
            msg.get("ok"),
        )
        entry = self._client_pending.pop(msg["id"], None)
        if entry is None or entry[0].done():
            return
        fut = entry[0]
        if msg["ok"]:
            fut.set_result(msg["value"])
        else:
            fut.set_exception(NotLeaderError(msg["value"]))

    def tick(self, now: float) -> None:
        """Advance timers: follower/candidate election timeout, leader
        heartbeats."""
        with self._lock:
            self._now = now
            if self.role == LEADER:
                if now - self._last_heartbeat >= self.HEARTBEAT_INTERVAL:
                    self._last_heartbeat = now
                    for peer in self.peer_ids:
                        self._send_append(peer)
            else:
                if now >= self._election_deadline:
                    self._start_election()

    def on_message(self, sender_id: str, payload: bytes) -> None:
        msg = deserialize(payload)
        with self._lock:
            kind = msg["kind"]
            # prevote traffic advertises term+1 but must NOT depose anyone
            # (that is the whole point of the prevote phase)
            if kind not in ("prevote", "prevote_reply") and (
                msg["term"] > self.current_term
            ):
                self._become_follower(msg["term"])
            if kind == "request_vote":
                self._on_request_vote(sender_id, msg)
            elif kind == "vote":
                self._on_vote(sender_id, msg)
            elif kind == "append":
                self._on_append(sender_id, msg)
            elif kind == "append_reply":
                self._on_append_reply(sender_id, msg)
            elif kind == "install_snapshot":
                self._install_snapshot(sender_id, msg)
            elif kind == "client_request":
                self._on_client_request(sender_id, msg)
            elif kind == "client_reply":
                self._on_client_reply(msg)
            elif kind == "prevote":
                self._on_prevote(sender_id, msg)
            elif kind == "prevote_reply":
                self._on_prevote_reply(sender_id, msg)

    # -- elections -----------------------------------------------------------

    def _reset_election_deadline(self) -> None:
        lo, hi = self.ELECTION_TIMEOUT
        self._election_deadline = self._now + self._rand.uniform(lo, hi)

    def _become_follower(self, term: int) -> None:
        self.current_term = term
        self.role = FOLLOWER
        self.voted_for = None
        self._votes.clear()
        self._fail_pending(NotLeaderError(None))
        self._persist_meta()
        self._reset_election_deadline()

    def _start_election(self) -> None:
        """PreVote phase (Raft §9.6 / etcd preVote): before bumping the
        term, ask peers whether an election COULD succeed. A rejoining
        member whose peers still hear a live leader gets no pre-votes and
        never inflates its term — without this, a member returning from a
        partition/restart deposes a healthy leader in a term war (observed
        as livelock in the OS-process cluster under load)."""
        self._prevotes = {self.node_id}
        self._reset_election_deadline()
        if not self.peer_ids:
            self._start_real_election()
            return
        for peer in self.peer_ids:
            self._send(peer, {
                "kind": "prevote", "term": self.current_term + 1,
                "last_log_index": self.last_index(),
                "last_log_term": self._term_at(self.last_index()),
            })

    def _start_real_election(self) -> None:
        logger.info(
            "%s starting election (term %d -> %d)",
            self.node_id, self.current_term, self.current_term + 1,
        )
        self.role = CANDIDATE
        self.current_term += 1
        self.voted_for = self.node_id
        self._votes = {self.node_id}
        self.leader_id = None
        self._persist_meta()
        self._reset_election_deadline()
        for peer in self.peer_ids:
            self._send(peer, {
                "kind": "request_vote", "term": self.current_term,
                "last_log_index": self.last_index(),
                "last_log_term": self._term_at(self.last_index()),
            })
        self._maybe_win()

    def _on_prevote(self, sender_id: str, msg: dict) -> None:
        my_last_term = self._term_at(self.last_index())
        up_to_date = (
            msg["last_log_term"] > my_last_term
            or (
                msg["last_log_term"] == my_last_term
                and msg["last_log_index"] >= self.last_index()
            )
        )
        # refuse while a live leader is heard from: minimum election
        # timeout since the last append (leader-stickiness check)
        lo, _hi = self.ELECTION_TIMEOUT
        leader_fresh = (
            self.role == LEADER
            or self._now - self._last_leader_contact < lo
        )
        grant = msg["term"] > self.current_term and up_to_date and not leader_fresh
        self._send(sender_id, {
            "kind": "prevote_reply", "term": self.current_term,
            "granted": grant, "for_term": msg["term"],
        })

    def _on_prevote_reply(self, sender_id: str, msg: dict) -> None:
        if self.role == LEADER or not msg.get("granted"):
            return
        if msg.get("for_term") != self.current_term + 1:
            return  # stale grant from an abandoned prevote round
        lo, _hi = self.ELECTION_TIMEOUT
        if self._now - self._last_leader_contact < lo:
            # the leader resurfaced while prevotes were in flight: abandon
            # the round instead of deposing it (the race the prevote
            # phase exists to close)
            self._prevotes = set()
            return
        self._prevotes.add(sender_id)
        quorum = (len(self.peer_ids) + 1) // 2 + 1
        if len(self._prevotes) >= quorum:
            self._prevotes = set()
            self._start_real_election()

    def _on_request_vote(self, sender_id: str, msg: dict) -> None:
        grant = False
        if msg["term"] >= self.current_term and self.voted_for in (None, sender_id):
            my_last_term = self._term_at(self.last_index())
            up_to_date = (
                msg["last_log_term"] > my_last_term
                or (
                    msg["last_log_term"] == my_last_term
                    and msg["last_log_index"] >= self.last_index()
                )
            )
            if up_to_date:
                grant = True
                self.voted_for = sender_id
                self._persist_meta()
                self._reset_election_deadline()
        self._send(sender_id, {
            "kind": "vote", "term": self.current_term, "granted": grant,
        })

    def _on_vote(self, sender_id: str, msg: dict) -> None:
        if self.role != CANDIDATE or msg["term"] != self.current_term:
            return
        if msg["granted"]:
            self._votes.add(sender_id)
            self._maybe_win()

    def _maybe_win(self) -> None:
        quorum = (len(self.peer_ids) + 1) // 2 + 1
        if self.role == CANDIDATE and len(self._votes) >= quorum:
            logger.info(
                "%s became leader (term %d)", self.node_id, self.current_term
            )
            from ..utils import eventlog

            eventlog.emit(
                "info", "raft", "became leader",
                member=self.node_id, term=self.current_term,
            )
            self.role = LEADER
            self.leader_id = self.node_id
            self.next_index = {p: self.last_index() + 1 for p in self.peer_ids}
            self.match_index = {p: -1 for p in self.peer_ids}
            self._last_heartbeat = self._now
            for peer in self.peer_ids:
                self._send_append(peer)

    # -- replication ---------------------------------------------------------

    def _send_append(self, peer: str) -> None:
        ni = self.next_index.get(peer, self.last_index() + 1)
        if ni <= self.snap_index:
            # the follower needs entries already folded into the snapshot
            if self.snapshot_fn is not None:
                self._send(peer, {
                    "kind": "install_snapshot", "term": self.current_term,
                    "snap_index": self.snap_index,
                    "snap_term": self.snap_term,
                    "data": self.snapshot_fn(),
                })
            return
        prev_index = ni - 1
        prev_term = self._term_at(prev_index)
        entries = [
            [e.term, e.command] for e in self.log[ni - self.snap_index - 1:]
        ]
        self._send(peer, {
            "kind": "append", "term": self.current_term,
            "prev_index": prev_index, "prev_term": prev_term,
            "entries": entries, "commit_index": self.commit_index,
        })

    def _on_append(self, sender_id: str, msg: dict) -> None:
        if msg["term"] < self.current_term:
            self._send(sender_id, {
                "kind": "append_reply", "term": self.current_term,
                "ok": False, "match_index": -1,
            })
            return
        self.role = FOLLOWER
        self.leader_id = sender_id
        self._last_leader_contact = self._now
        self._reset_election_deadline()
        prev_index = msg["prev_index"]
        entries = list(msg["entries"])
        if prev_index < self.snap_index:
            # entries overlapping our snapshot are already applied: skip
            # them and anchor at the snapshot boundary
            skip = self.snap_index - prev_index
            entries = entries[skip:]
            prev_index = self.snap_index
        if prev_index >= 0 and (
            prev_index > self.last_index()
            or self._term_at(prev_index) != msg["prev_term"]
        ):
            self._send(sender_id, {
                "kind": "append_reply", "term": self.current_term,
                "ok": False, "match_index": -1,
            })
            return
        # Truncate conflicts, append new entries (logical indices).
        idx = prev_index + 1
        first_change: Optional[int] = None
        truncated = False
        for term, command in entries:
            if idx <= self.last_index():
                if self._term_at(idx) != term:
                    del self.log[idx - self.snap_index - 1:]
                    self.log.append(LogEntry(term, command))
                    truncated = True
                    if first_change is None:
                        first_change = idx
            else:
                self.log.append(LogEntry(term, command))
                if first_change is None:
                    first_change = idx
            idx += 1
        if first_change is not None:
            if truncated:
                self._persist_log_truncate(first_change)
            self._persist_log_from(first_change)
        if msg["commit_index"] > self.commit_index:
            self.commit_index = min(msg["commit_index"], self.last_index())
            self._apply_committed()
        # match up to what THIS append covered — not our whole log, which may
        # carry an uncommitted tail from a deposed leader beyond the new
        # leader's log (overstating would crash the leader's next send).
        self._send(sender_id, {
            "kind": "append_reply", "term": self.current_term,
            "ok": True, "match_index": prev_index + len(entries),
        })

    def _on_append_reply(self, sender_id: str, msg: dict) -> None:
        if self.role != LEADER or msg["term"] != self.current_term:
            return
        if msg["ok"]:
            self.match_index[sender_id] = msg["match_index"]
            self.next_index[sender_id] = msg["match_index"] + 1
            self._advance_commit()
        else:
            self.next_index[sender_id] = max(0, self.next_index.get(sender_id, 1) - 1)
            self._send_append(sender_id)

    def _advance_commit(self) -> None:
        quorum = (len(self.peer_ids) + 1) // 2 + 1
        for n in range(self.last_index(), self.commit_index, -1):
            if self._term_at(n) != self.current_term:
                continue
            count = 1 + sum(
                1 for p in self.peer_ids if self.match_index.get(p, -1) >= n
            )
            if count >= quorum:
                self.commit_index = n
                self._apply_committed()
                break

    def _apply_committed(self) -> None:
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            entry = self._entry(self.last_applied)
            result = self.apply_fn(entry.command)
            request_id = entry.command.get("request_id")
            fut = self._pending.pop(request_id, None) if request_id else None
            if fut is not None and not fut.done():
                fut.set_result(result)
        self._maybe_snapshot()

    def _fail_pending(self, exc: Exception) -> None:
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self._pending.clear()

    def _send(self, peer: str, msg: dict) -> None:
        try:
            self.transport(peer, serialize(msg))
        except Exception:
            pass  # unreachable peer: Raft tolerates message loss


class NotLeaderError(Exception):
    def __init__(self, leader_hint: Optional[str]):
        super().__init__(f"not the leader (hint: {leader_hint})")
        self.leader_hint = leader_hint

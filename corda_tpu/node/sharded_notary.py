"""Partitioned uniqueness provider: state-ref-keyed shards + two-phase
cross-shard notarisation (docs/sharding.md).

The round-11 profile pinned the system ceiling on one core: ~25 threads
convoy behind one GIL, and every uniqueness commit — however batched —
funnels through ONE consensus group. This module partitions uniqueness
consensus itself (ROADMAP item 2; PAPERS' "Scalable Multi-domain Trust
Infrastructures for Segmented Networks" motivates the segmented
topology):

  * each consumed StateRef routes to one of N shards by a STABLE hash of
    its commit-log key (sha256 — `hash()` is salted per process and the
    routing must agree across OS workers and restarts);
  * every shard is one independent consensus group — any existing
    provider implementing `commit_many` (Persistent, Raft, BFT) serves
    as the per-shard delegate, so a shard can be a replicated cluster;
  * a transaction whose inputs all land on one shard commits in ONE
    round via that shard's `commit_many` batch seam — the common case
    (issue+pay pairs spend freshly-issued refs, which hash together only
    by accident 1/N of the time);
  * a cross-shard transaction runs a TWO-PHASE protocol: prepare
    reserves its refs on every touched shard (tx-scoped lock + expiry),
    then a second round finalises — or releases, because a conflict or
    prepare-timeout on ANY shard aborts ALL of them. The prepare journal
    makes the coordinator crash-safe: recovery re-drives a commit that
    had passed its prepare point and releases anything that hadn't, so a
    dead coordinator never wedges a state-ref (its reservations also die
    by expiry even with no recovery pass).

Reservations are PER-SHARD state: a key routes to exactly one shard, so
each shard's lock table lives in that shard's own database (falling back
to the shared coordination db, then process memory, when a delegate has
no database of its own). That placement is what lets M worker PROCESSES
(node/shardhost) serve one notary identity WITHOUT serialising every
commit round through one coordination-db write lock: a shard's
reservation screen, conflict check and delegate commit run as ONE write
transaction on that shard's file, atomic against any other process's
round or prepare on the same shard — and fully parallel across shards.
The coordination db keeps only the prepare journal (cross-shard rounds,
~2% of the production spend shape).

The unsharded path is untouched: nothing here is imported unless
`CORDA_TPU_SHARDS` / node.conf `shards` / `create_node(shards=)` asks
for more than one shard.
"""
from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from ..core.contracts.structures import StateRef
from ..core.crypto.secure_hash import SecureHash
from ..core.serialization.codec import deserialize, serialize
from ..utils import eventlog, faultpoints, lockorder
from .notary import (
    Conflict,
    PersistentUniquenessProvider,
    UniquenessException,
    UniquenessProvider,
)

#: default reservation lifetime: a crashed coordinator's locks release
#: themselves after this long even if no recovery pass ever runs
DEFAULT_PREPARE_TTL_S = 30.0


class CoordinatorCrashError(RuntimeError):
    """Raised by the `sharded.*` fault points' "crash" action: simulates
    the coordinator dying mid-protocol with its reservations and journal
    record left behind (recovery-test seam)."""


#: the 2PC ladder's durability barriers (store "sharded_2pc"), in rung
#: order: journal_prepare → per-shard prepare → journal_committing
#: (the decision record) → per-shard finalise → journal_remove.
#: tools/crashmc.py kills the coordinator at each and asserts recover()
#: either re-drives the round to completion or releases every lock.
for _p in (
    "sharded.journal_prepare",
    "sharded.prepare",
    "sharded.journal_committing",
    "sharded.finalise",
    "sharded.journal_remove",
):
    faultpoints.register_crash_point(_p, "sharded_2pc")
del _p


def _key_of(ref: StateRef) -> bytes:
    return PersistentUniquenessProvider._key(ref)


def shard_of_key(key: bytes, n_shards: int) -> int:
    """Stable shard routing: sha256, not `hash()` (which is salted per
    process — OS workers and restarts must agree on the partition).

    Routes on the SOURCE TXHASH (key[:32]), not the full txhash+index
    key: every spend of a given ref still lands on one shard (conflict
    detection is per-ref and both spenders hash the same 32 bytes), but
    all outputs of one source transaction CO-LOCATE — so the common
    spend shape (inputs gathered from one issuing/previous tx) commits
    in ONE single-shard round, and the two-phase protocol is reserved
    for genuinely scattered input sets (docs/sharding.md §routing)."""
    return int.from_bytes(
        hashlib.sha256(key[:32]).digest()[:8], "big"
    ) % n_shards


# ---------------------------------------------------------------------------
# Reservation store (the tx-scoped lock table)
# ---------------------------------------------------------------------------

class ReservationStore:
    """key -> (holding tx, expiry). One per SHARD: in-memory for
    single-process shards; sqlite-backed — in the shard delegate's own
    database — for multi-process workers, where `INSERT OR IGNORE`'s
    per-statement atomicity (and, on the fused round path, the shard
    file's single write lock) arbitrates races."""

    def __init__(self, db=None, table: str = "shard_reservations"):
        self._db = db
        self._table = table
        self._mem: Dict[bytes, Tuple[str, float]] = {}
        # guards _mem: callers race (the coalescing layer drains shard
        # groups in concurrent threads, and abort/recovery releases run
        # outside the provider's per-shard commit lock); sqlite
        # serialises the db path itself
        self._mem_lock = lockorder.make_lock("ReservationStore._mem_lock")
        if db is not None:
            db.execute(
                f"CREATE TABLE IF NOT EXISTS {table} "
                "(key BLOB PRIMARY KEY, tx TEXT NOT NULL, "
                "expires REAL NOT NULL)"
            )

    def holders(self, keys: Sequence[bytes], now: float) -> Dict[bytes, str]:
        """{key: holding tx hex} for unexpired reservations on `keys`.
        One IN-clause query per 500 keys, not one per key — this screen
        runs inside EVERY single-shard commit round."""
        out: Dict[bytes, str] = {}
        if self._db is not None:
            keys = list(keys)
            for i in range(0, len(keys), 500):
                chunk = keys[i:i + 500]
                marks = ",".join("?" * len(chunk))
                for key, tx, expires in self._db.query(
                    f"SELECT key, tx, expires FROM {self._table} "
                    f"WHERE key IN ({marks})",
                    tuple(chunk),
                ):
                    if expires > now:
                        out[bytes(key)] = tx
            return out
        with self._mem_lock:
            for key in keys:
                held = self._mem.get(key)
                if held is not None and held[1] > now:
                    out[key] = held[0]
        return out

    def reserve(self, keys: Sequence[bytes], tx_hex: str, expires: float,
                now: float) -> Dict[bytes, str]:
        """Atomically try to reserve every key for `tx_hex`. Returns the
        conflicts ({key: other tx}); on ANY conflict nothing stays
        reserved (all-or-nothing, so a failed prepare leaves no locks).
        Expired rows are evicted, never counted as conflicts."""
        lost = self.reserve_many({tx_hex: list(keys)}, expires, now)
        return lost.get(tx_hex, {})

    def reserve_many(self, tx_keys: Dict[str, Sequence[bytes]],
                     expires: float, now: float,
                     ) -> Dict[str, Dict[bytes, str]]:
        """Reserve every tx's keys in ONE storage transaction (the
        two-phase prepare runs per-ROUND, not per-tx — a drained batch of
        cross-shard commits pays one coordination-db write per shard).
        Returns {tx_hex: {key: holding tx}} for the txs that LOST —
        losers keep nothing on this shard; within-batch contention on a
        key is decided by insert order. Expired rows are evicted first,
        never counted as conflicts."""
        lost: Dict[str, Dict[bytes, str]] = {}
        if self._db is not None:
            with self._db.transaction():
                self._db.execute(
                    f"DELETE FROM {self._table} WHERE expires <= ?", (now,)
                )
                self._db.executemany(
                    f"INSERT OR IGNORE INTO {self._table} "
                    "(key, tx, expires) VALUES (?, ?, ?)",
                    [(k, tx, expires)
                     for tx, keys in tx_keys.items() for k in keys],
                )
                all_keys = [
                    k for keys in tx_keys.values() for k in keys
                ]
                held = self.holders(all_keys, now)
                victims = []
                for tx, keys in tx_keys.items():
                    bad = {
                        k: held[k] for k in keys
                        if k in held and held[k] != tx
                    }
                    if bad:
                        lost[tx] = bad
                        victims.extend((k, tx) for k in keys)
                if victims:
                    self._db.executemany(
                        f"DELETE FROM {self._table} WHERE key=? AND tx=?",
                        victims,
                    )
            return lost
        with self._mem_lock:
            for tx, keys in tx_keys.items():
                bad = {}
                for k in keys:
                    held = self._mem.get(k)
                    if held is not None and held[1] > now and held[0] != tx:
                        bad[k] = held[0]
                if bad:
                    lost[tx] = bad
                else:
                    for k in keys:
                        self._mem[k] = (tx, expires)
        return lost

    def extend(self, keys: Sequence[bytes], tx_hex: str,
               new_expires: float) -> int:
        """Push `tx_hex`'s reservations ON `keys` to a later expiry and
        return HOW MANY rows moved. The cross-shard decision point calls
        this per shard before flipping the journal to "committing": a
        shortfall against the key count means expiry already released a
        key (a sibling's purge may have let a competitor in), so the
        caller must abort that tx instead of finalising a torn commit.
        Scoped to `keys` — NOT a bare WHERE tx=? — because over_database
        mode backs every shard's store with the same table, where a
        tx-wide UPDATE would count its OTHER shards' rows and mask a
        loss. The UPDATE races sibling purges safely: sqlite serialises
        the writers, so either the purge ran first (we count the loss)
        or the extension ran first (the purge no longer matches)."""
        if self._db is not None:
            keys = list(keys)
            n = 0
            for i in range(0, len(keys), 500):
                chunk = keys[i:i + 500]
                marks = ",".join("?" * len(chunk))
                n += self._db.execute(
                    f"UPDATE {self._table} SET expires=? "
                    f"WHERE tx=? AND key IN ({marks})",
                    (new_expires, tx_hex, *chunk),
                ).rowcount
            return n
        n = 0
        with self._mem_lock:
            for k in keys:
                held = self._mem.get(k)
                if held is not None and held[0] == tx_hex:
                    self._mem[k] = (tx_hex, new_expires)
                    n += 1
        return n

    def release(self, keys: Sequence[bytes], tx_hex: str) -> None:
        """Release `tx_hex`'s reservations on `keys` (others' are never
        touched — a slow abort must not unlock a successor's prepare)."""
        if self._db is not None:
            self._db.executemany(
                f"DELETE FROM {self._table} WHERE key=? AND tx=?",
                [(k, tx_hex) for k in keys],
            )
            return
        with self._mem_lock:
            for k in keys:
                held = self._mem.get(k)
                if held is not None and held[0] == tx_hex:
                    del self._mem[k]

    def release_pairs(self, pairs: Sequence[Tuple[bytes, str]]) -> None:
        """Release many (key, holding tx) reservations in one statement
        (the per-round finalise)."""
        if self._db is not None:
            self._db.executemany(
                f"DELETE FROM {self._table} WHERE key=? AND tx=?",
                list(pairs),
            )
            return
        with self._mem_lock:
            for k, tx in pairs:
                held = self._mem.get(k)
                if held is not None and held[0] == tx:
                    del self._mem[k]

    def release_tx(self, tx_hex: str) -> int:
        """Release EVERY reservation held by `tx_hex` (recovery path)."""
        if self._db is not None:
            cur = self._db.execute(
                f"DELETE FROM {self._table} WHERE tx=?", (tx_hex,)
            )
            return cur.rowcount
        with self._mem_lock:
            victims = [k for k, (t, _) in self._mem.items() if t == tx_hex]
            for k in victims:
                del self._mem[k]
        return len(victims)

    def purge_expired(self, now: float) -> int:
        if self._db is not None:
            return self._db.execute(
                f"DELETE FROM {self._table} WHERE expires <= ?", (now,)
            ).rowcount
        with self._mem_lock:
            victims = [
                k for k, (_, exp) in self._mem.items() if exp <= now
            ]
            for k in victims:
                del self._mem[k]
        return len(victims)

    def held_tx_ids(self) -> Set[str]:
        """Every tx currently holding at least one reservation —
        recovery's leaked-lock check (node/recovery.py): after the
        journal drains, a holder with no journal entry is a lock that
        nothing will ever release before its TTL."""
        if self._db is not None:
            return {
                row[0]
                for row in self._db.query(
                    f"SELECT DISTINCT tx FROM {self._table}"
                )
            }
        with self._mem_lock:
            return {tx for tx, _ in self._mem.values()}


class _ReservationsView:
    """Maintenance/observability facade over the per-shard lock tables
    (tests, recovery): `holders` merges across shards, release/purge fan
    out to every store. Routing stays with the provider — this view
    never decides which shard a key belongs to."""

    def __init__(self, stores: Sequence[ReservationStore]):
        self._stores = list(stores)

    def holders(self, keys: Sequence[bytes], now: float) -> Dict[bytes, str]:
        out: Dict[bytes, str] = {}
        for s in self._stores:
            out.update(s.holders(keys, now))
        return out

    def release(self, keys: Sequence[bytes], tx_hex: str) -> None:
        for s in self._stores:
            s.release(keys, tx_hex)

    def release_tx(self, tx_hex: str) -> int:
        # stores sharing one db handle (over_database) dedupe naturally:
        # the first DELETE empties the shared table, the rest count 0
        return sum(s.release_tx(tx_hex) for s in self._stores)

    def purge_expired(self, now: float) -> int:
        return sum(s.purge_expired(now) for s in self._stores)


# ---------------------------------------------------------------------------
# Prepare journal (coordinator crash recovery)
# ---------------------------------------------------------------------------

class PrepareJournal:
    """tx -> {phase, keys per shard, expiry}. The write ORDER is the
    protocol: the record exists before any reservation is taken (so
    recovery can always find what to release), flips to "committing"
    only once every shard prepared (so recovery knows the commit is
    decided and must be re-driven, never rolled back), and is removed
    only after every shard finalised."""

    def __init__(self, db=None, table: str = "shard_prepare_journal"):
        self._db = db
        self._mem: Dict[str, dict] = {}
        if db is not None:
            from .database import KVStore

            self._kv = KVStore(db, table)
            # the db's resting durability level (0=OFF 1=NORMAL 2=FULL
            # 3=EXTRA): put() raises it around the "committing" flip
            row = db.query("PRAGMA synchronous")
            self._sync_level = int(row[0][0]) if row else 1

    def put(self, tx_hex: str, record: dict) -> None:
        if self._db is not None:
            if record.get("phase") == "committing" and self._sync_level < 2:
                # The DECISION record. The per-shard commit logs run
                # synchronous=FULL while the coordination db keeps the
                # node default (NORMAL), whose WAL commits can vanish on
                # power loss — recovery would then read the stale
                # "prepare" record and abort a round one shard already
                # durably finalised (a torn commit). Make exactly this
                # write as durable as the commits it orders.
                with self._db.lock:
                    self._db.execute("PRAGMA synchronous=FULL")
                    try:
                        self._kv.put(tx_hex.encode(), serialize(record))
                    finally:
                        self._db.execute(
                            f"PRAGMA synchronous={self._sync_level}"
                        )
            else:
                self._kv.put(tx_hex.encode(), serialize(record))
        else:
            self._mem[tx_hex] = dict(record)

    def get(self, tx_hex: str) -> Optional[dict]:
        if self._db is not None:
            blob = self._kv.get(tx_hex.encode())
            return None if blob is None else deserialize(blob)
        rec = self._mem.get(tx_hex)
        return dict(rec) if rec is not None else None

    def remove(self, tx_hex: str) -> None:
        if self._db is not None:
            self._kv.delete(tx_hex.encode())
        else:
            self._mem.pop(tx_hex, None)

    def items(self) -> List[Tuple[str, dict]]:
        if self._db is not None:
            return [
                (bytes(k).decode(), deserialize(v))
                for k, v in self._kv.items()
            ]
        # list() snapshots first: recovery may scan while a drain
        # thread puts (single-key ops are GIL-atomic; iteration is not)
        return [(k, dict(v)) for k, v in list(self._mem.items())]


# ---------------------------------------------------------------------------
# The provider
# ---------------------------------------------------------------------------

class ShardedUniquenessProvider(UniquenessProvider):
    """Routes each consumed state-ref to one of N shard delegates; commits
    single-shard transactions in one round and cross-shard transactions
    via prepare/commit with abort-on-any-conflict (module docstring)."""

    def __init__(self, delegates: Sequence[UniquenessProvider], db=None,
                 prepare_ttl_s: Optional[float] = None,
                 clock: Callable[[], float] = time.time):
        if not delegates:
            raise ValueError("at least one shard delegate required")
        for d in delegates:
            if not hasattr(d, "commit_many"):
                raise ValueError(
                    f"shard delegate {type(d).__name__} lacks commit_many"
                )
        self.delegates = list(delegates)
        self.n_shards = len(self.delegates)
        self._locks = [
            lockorder.make_lock(f"ShardedUniquenessProvider.shard{i}")
            for i in range(len(self.delegates))
        ]
        self._probes = [self._probe_fn(d) for d in self.delegates]
        self._db = db
        self.clock = clock
        self.prepare_ttl_s = (
            float(prepare_ttl_s) if prepare_ttl_s is not None
            else float(os.environ.get(
                "CORDA_TPU_SHARD_PREPARE_TTL", DEFAULT_PREPARE_TTL_S
            ))
        )
        # per-shard lock tables (module docstring): a key routes to
        # exactly one shard, so its reservation lives in that shard's
        # OWN database when the delegate exposes one — fused rounds
        # (screen + delegate commit in one write transaction, parallel
        # across shard files). Delegates without a database (Raft/BFT
        # cluster objects) write-arbitrate through the coordination db,
        # or process memory when there is none.
        self._stores: List[ReservationStore] = []
        self._fused: List[bool] = []
        for d in self.delegates:
            ddb = getattr(d, "_db", None)
            self._stores.append(
                ReservationStore(ddb if ddb is not None else db)
            )
            self._fused.append(ddb is not None)
        self.reservations = _ReservationsView(self._stores)
        self.journal = PrepareJournal(db)
        # telemetry (bench stage + /workers operator view); increments
        # come from CONCURRENT per-shard drain threads (the coalescing
        # layer runs shard groups in parallel), so they serialise on one
        # lock — unsynchronized '+=' would drop updates
        self._stats_lock = lockorder.make_lock(
            "ShardedUniquenessProvider._stats_lock"
        )
        self.single_commits = 0
        self.cross_commits = 0
        self.cross_aborts = 0
        self.reservation_conflicts = 0
        self.recovered_commits = 0
        self.recovered_aborts = 0
        self.shard_rounds: Dict[int, int] = {
            i: 0 for i in range(self.n_shards)
        }
        if db is not None:
            # a restarted coordinator drains what its predecessor left
            self.recover()

    # -- construction helpers ------------------------------------------------

    @classmethod
    def over_database(cls, db, n_shards: int,
                      **kw) -> "ShardedUniquenessProvider":
        """N PersistentUniquenessProvider shards over one node database —
        the in-memory/MockNetwork configuration (every table lives in
        the shared sqlite handle; no cross-process story needed)."""
        return cls(
            [
                PersistentUniquenessProvider(db, table=f"uniqueness_s{i}")
                for i in range(int(n_shards))
            ],
            db=db, **kw,
        )

    @classmethod
    def over_directory(cls, coord_db, directory: str, n_shards: int,
                       synchronous: str = "FULL",
                       **kw) -> "ShardedUniquenessProvider":
        """N shards with ONE SQLITE FILE EACH under `directory` (each
        holding that shard's commit log AND its reservation lock table),
        plus the shared coordination db (prepare journal only) — the
        file-backed/worker-process configuration. The per-shard files
        are the whole point: sqlite serialises WRITERS (and their
        durability fsyncs — `synchronous=FULL`, because a commit log
        that loses a commit on power-off later admits the double-spend)
        per DATABASE, so four shards in one file would still commit one
        at a time across OS workers, while four files commit four-wide
        (docs/sharding.md §scale)."""
        from .database import NodeDatabase

        os.makedirs(directory, exist_ok=True)
        prov = cls(
            [
                PersistentUniquenessProvider(
                    NodeDatabase(os.path.join(directory, f"shard{i}.db"),
                                 synchronous=synchronous)
                )
                for i in range(int(n_shards))
            ],
            db=coord_db, **kw,
        )
        # Hot-path pragmas, applied AFTER construction (table creation
        # and recovery above race sibling workers and want the patient
        # 30s busy handler):
        #   * wal_autocheckpoint=0 — a mid-round auto-checkpoint stalls
        #     the round for two extra fsyncs and N workers' checkpoints
        #     collide on the device (measured: the 4-shard A/B loses
        #     ~25% throughput to them); the sweeper thread below runs
        #     PASSIVE checkpoints off the commit path instead, which
        #     never block writers.
        #   * busy_timeout=5 — sqlite's default busy handler backs off
        #     to 25-100ms sleeps per attempt, so a cross-shard prepare
        #     against a hot sibling shard file paid tens of ms per lock
        #     acquisition; with a 5ms timeout the blocked writer raises
        #     and `_retry_locked` polls at millisecond granularity.
        for d in prov.delegates:
            d._db.execute("PRAGMA busy_timeout=5")
            d._db.execute("PRAGMA wal_autocheckpoint=0")
        prov._start_wal_sweeper()
        return prov

    @staticmethod
    def _probe_fn(delegate) -> Optional[Callable]:
        """Committed-state read for prepare-time conflict detection:
        {key: consuming tx id} for already-spent keys. Required for
        cross-shard safety — without it a conflict could surface only at
        finalise time, AFTER an earlier shard finalised."""
        probe = getattr(delegate, "probe_commits", None)
        if probe is not None:
            return probe
        kv = getattr(delegate, "_map", None)
        if kv is not None:  # Persistent / Raft applied map

            def probe_map(keys):
                out = {}
                for k in keys:
                    blob = kv.get(k)
                    if blob is not None:
                        out[k] = deserialize(blob)["tx_id"]
                return out

            return probe_map
        return None

    # -- shard-db scheduling (lock polling + WAL maintenance) ----------------

    def _retry_locked(self, fn, deadline_s: float = 30.0):
        """Run `fn`, retrying on SQLITE_BUSY. The shard dbs run with a
        ~5ms busy_timeout (over_directory) so a blocked writer raises
        quickly and THIS loop polls at millisecond granularity — sqlite's
        default busy handler backs off to 25-100ms sleeps per attempt,
        which starved cross-shard rounds acquiring a hot sibling shard
        file. Every retried body is idempotent: reservation writes are
        INSERT OR IGNORE / tx-scoped DELETEs, delegate commits are
        idempotent per tx id, and the failed transaction rolled back
        before we re-enter."""
        import sqlite3

        deadline = time.monotonic() + deadline_s
        while True:
            try:
                return fn()
            except sqlite3.OperationalError as exc:
                msg = str(exc)
                if "locked" not in msg and "busy" not in msg:
                    raise
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.0005)

    def checkpoint_shards(self) -> None:
        """PASSIVE WAL checkpoint on every file-backed shard. Commit-path
        writers never checkpoint (over_directory sets
        wal_autocheckpoint=0): a mid-round auto-checkpoint stalls the
        round for extra fsyncs and N workers' checkpoints collide on the
        device. PASSIVE never blocks writers; a contended call simply
        checkpoints less of the WAL and the next sweep catches up."""
        for d in self.delegates:
            sdb = getattr(d, "_db", None)
            if sdb is None:
                continue
            try:
                sdb.execute("PRAGMA wal_checkpoint(PASSIVE)")
            except Exception:
                pass  # busy/locked: the WAL survives until the next pass

    def _start_wal_sweeper(self, interval_s: Optional[float] = None) -> None:
        interval = (
            float(interval_s) if interval_s is not None
            else float(os.environ.get("CORDA_TPU_SHARD_WAL_SWEEP", "5"))
        )
        if interval <= 0:
            return
        self._sweep_stop = threading.Event()

        def sweep():
            while not self._sweep_stop.wait(interval):
                self.checkpoint_shards()

        threading.Thread(
            target=sweep, name="shard-wal-sweeper", daemon=True
        ).start()

    def close(self) -> None:
        stop = getattr(self, "_sweep_stop", None)
        if stop is not None:
            stop.set()

    # -- routing -------------------------------------------------------------

    def shard_of(self, ref: StateRef) -> int:
        return shard_of_key(_key_of(ref), self.n_shards)

    def shards_of(self, states: Sequence[StateRef]) -> List[int]:
        """Sorted distinct shards a transaction's inputs touch (an empty
        input set — issuance — is shard 0: it consumes nothing, but the
        delegate round still records the commit idempotently)."""
        return sorted({self.shard_of(r) for r in states}) or [0]

    # -- UniquenessProvider --------------------------------------------------

    def commit(self, states: List[StateRef], tx_id, requesting_party):
        result = self.commit_many([(states, tx_id, requesting_party)])[0]
        if result is not None:
            raise UniquenessException(result)

    def commit_many(self, requests: Sequence[Tuple]) -> List[Optional[Conflict]]:
        """Group single-shard requests per shard (ONE delegate round per
        shard, never one per request) and run each cross-shard request
        through the two-phase protocol. Shard groups are independent by
        construction; the coalescing layer dispatches them in parallel
        (CoalescingUniquenessProvider shard-awareness)."""
        results: List[Optional[Conflict]] = [None] * len(requests)
        per_shard: Dict[int, List[Tuple[int, Tuple]]] = {}
        cross: List[Tuple[int, Tuple, List[int]]] = []
        for i, req in enumerate(requests):
            shards = self.shards_of(req[0])
            if len(shards) == 1:
                per_shard.setdefault(shards[0], []).append((i, req))
            else:
                cross.append((i, req, shards))
        for shard, items in per_shard.items():
            for (idx, _), res in zip(
                items, self._commit_shard_batch(shard, [r for _, r in items])
            ):
                results[idx] = res
        if cross:
            for (idx, _, _), res in zip(
                cross, self._commit_cross_batch(cross)
            ):
                results[idx] = res
        return results

    # -- single-shard path ---------------------------------------------------

    def _commit_shard_batch(
        self, shard: int, reqs: Sequence[Tuple]
    ) -> List[Optional[Conflict]]:
        """One delegate round for a batch of same-shard requests,
        screened against the shard's reservation table (live cross-shard
        prepares' locks).

        FUSED path (the store lives in the shard delegate's own db —
        over_database / over_directory): the whole round is ONE write
        transaction on that database. purge_expired runs first because
        python sqlite3 defers BEGIN past SELECTs — the round's first
        statement must be a WRITE for the screen to happen inside the
        transaction, which is what makes screen + conflict check +
        delegate commit atomic against any other OS worker's round or
        prepare on this shard's file. A sibling's prepare either
        serialises before us (its reservation shows in our screen → we
        lose) or after (its post-reserve probe sees our committed rows →
        it aborts). Zero hot-path writes touch the shared coordination
        db, so N shards commit N-wide — the write-arbitration variant
        (reserve_many/release on the coordination db per round)
        serialised every worker on one write lock and collapsed the
        4-shard A/B speedup to ~1.1×.

        Delegates without their own db write-arbitrate through the
        shared store instead: `INSERT OR IGNORE`'s per-statement
        atomicity is the only lock a sibling process shares with us
        there, so a read-only screen would leave a prepare/commit
        interleaving window."""
        now = self.clock()
        out: List[Optional[Conflict]] = [None] * len(reqs)
        store = self._stores[shard]
        with self._locks[shard]:
            if self._fused[shard]:
                def attempt():
                    with store._db.transaction():
                        store.purge_expired(now)  # WRITE-first: opens txn
                        return self._screened_round(store, shard, reqs, now)
                out, rounds, commits, res_conflicts = \
                    self._retry_locked(attempt)
                # telemetry applied AFTER the retry loop settles, so an
                # attempt that lost the write lock mid-round is not
                # double-counted
                with self._stats_lock:
                    self.shard_rounds[shard] += rounds
                    self.single_commits += commits
                    self.reservation_conflicts += res_conflicts
            else:
                self._arbitrated_round(shard, store, reqs, now, out)
        return out

    def _screened_round(self, store: ReservationStore, shard: int,
                        reqs: Sequence[Tuple], now: float):
        """Read-screen + delegate commit (caller holds the fused write
        transaction, so the screen cannot interleave with a sibling's
        reserve). Within-batch double-spends are the delegate's job —
        commit_many stages earlier requests against later ones. Returns
        (results, rounds, commits, reservation_conflicts) — counters,
        not self-mutations, because the caller may retry the whole
        transaction after a lost lock race."""
        out: List[Optional[Conflict]] = [None] * len(reqs)
        rounds = commits = res_conflicts = 0
        key_lists = [[_key_of(r) for r in states] for states, _, _ in reqs]
        held = store.holders(
            [k for ks in key_lists for k in ks], now
        )
        forward: List[Tuple[int, Tuple]] = []
        for i, (states, tx_id, party) in enumerate(reqs):
            tx_hex = tx_id.bytes.hex()
            bad = {
                k: held[k] for k in key_lists[i]
                if k in held and held[k] != tx_hex
            }
            if bad:
                # a live cross-shard prepare holds these refs: the
                # competing spend loses, attributed to the reserver
                key_to_ref = dict(zip(key_lists[i], states))
                res_conflicts += 1
                out[i] = Conflict(tx_id, {
                    repr(key_to_ref[k]): SecureHash(bytes.fromhex(other))
                    for k, other in bad.items()
                })
            else:
                forward.append((i, (states, tx_id, party)))
        if forward:
            rounds += 1
            delegate_res = self.delegates[shard].commit_many(
                [r for _, r in forward]
            )
            for (i, _), res in zip(forward, delegate_res):
                out[i] = res
                if res is None:
                    commits += 1
        return out, rounds, commits, res_conflicts

    def _arbitrated_round(self, shard: int, store: ReservationStore,
                          reqs: Sequence[Tuple], now: float,
                          out: List[Optional[Conflict]]) -> None:
        """Write-arbitrated round for shards whose store cannot share the
        delegate's transaction (in-memory, or the coordination-db
        fallback): reserve_many is the lock acquire, release_pairs the
        unlock around the delegate commit."""
        lost = store.reserve_many(
            {
                tx_id.bytes.hex(): [_key_of(r) for r in states]
                for states, tx_id, _ in reqs
            },
            now + self.prepare_ttl_s, now,
        )
        forward: List[Tuple[int, Tuple]] = []
        for i, (states, tx_id, party) in enumerate(reqs):
            bad = lost.get(tx_id.bytes.hex())
            if bad:
                key_to_ref = {_key_of(r): r for r in states}
                with self._stats_lock:
                    self.reservation_conflicts += 1
                out[i] = Conflict(tx_id, {
                    repr(key_to_ref[k]): SecureHash(bytes.fromhex(other))
                    for k, other in bad.items()
                })
            else:
                forward.append((i, (states, tx_id, party)))
        if forward:
            try:
                with self._stats_lock:
                    self.shard_rounds[shard] += 1
                delegate_res = self.delegates[shard].commit_many(
                    [r for _, r in forward]
                )
                for (i, _), res in zip(forward, delegate_res):
                    out[i] = res
                    if res is None:
                        with self._stats_lock:
                            self.single_commits += 1
            finally:
                store.release_pairs([
                    (_key_of(r), tx_id.bytes.hex())
                    for _, (states, tx_id, _) in forward
                    for r in states
                ])

    # -- cross-shard two-phase path ------------------------------------------

    def _fire(self, point: str, **detail):
        if faultpoints.hook is not None:
            action = faultpoints.fire(point, **detail)
            if action == "crash":
                raise CoordinatorCrashError(
                    f"injected coordinator crash at {point} "
                    f"(shard {detail.get('shard')})"
                )
            if isinstance(action, tuple) and action[:1] == ("delay",):
                time.sleep(float(action[1]))

    def _commit_cross_batch(self, cross) -> List[Optional[Conflict]]:
        """One two-phase ROUND for every cross-shard request in a drained
        batch (2112.02229's no-stage-blocks-another discipline at the
        commit path): ONE journal record and ONE reservation transaction
        per shard cover the whole group, instead of ~7 coordination-db
        writes per transaction. Conflicts stay per-transaction — a loser
        is dropped from the round (its reservations released everywhere)
        without aborting its batch-mates."""
        txs: List[dict] = []
        for _idx, (states, tx_id, party), shards in cross:
            keys_by_shard: Dict[int, List[bytes]] = {s: [] for s in shards}
            ref_of_key: Dict[bytes, StateRef] = {}
            for ref in states:
                key = _key_of(ref)
                keys_by_shard[shard_of_key(key, self.n_shards)].append(key)
                ref_of_key[key] = ref
            txs.append({
                "tx_hex": tx_id.bytes.hex(), "tx_id": tx_id, "party": party,
                "keys_by_shard": keys_by_shard, "ref_of_key": ref_of_key,
                "shards": shards,
            })
        union = sorted({s for t in txs for s in t["shards"]})
        now = self.clock()
        expires = now + self.prepare_ttl_s
        round_id = txs[0]["tx_hex"]
        # journal FIRST: recovery must be able to find (and release) any
        # reservation this round takes from here on
        self._fire("sharded.journal_prepare", tx_id=round_id)
        self.journal.put(round_id, self._journal_record(
            "prepare", union, txs, expires
        ))
        results: Dict[str, Optional[Conflict]] = {
            t["tx_hex"]: None for t in txs
        }
        alive = list(txs)
        try:
            for s in union:  # ascending order: no lock-cycle livelock
                todo = [t for t in alive if t["keys_by_shard"].get(s)]
                if not todo:
                    continue
                self._fire("sharded.prepare", shard=f"s{s}",
                           tx_id=round_id)
                conflicts = self._prepare_shard_batch(s, todo, expires)
                for t in todo:
                    c = conflicts.get(t["tx_hex"])
                    if c is not None:
                        # loser: drop from the round, release whatever
                        # it reserved on earlier shards
                        results[t["tx_hex"]] = c
                        for rs in t["shards"]:
                            self._retry_locked(
                                lambda rs=rs:
                                self._stores[rs].release_tx(t["tx_hex"])
                            )
                        with self._stats_lock:
                            self.cross_aborts += 1
                        alive.remove(t)
        except CoordinatorCrashError:
            # the simulated death: reservations + journal stay behind —
            # expiry (or a recovery pass) is what must clean them up
            raise
        except BaseException:
            for t in alive:
                for rs in t["shards"]:
                    self._retry_locked(
                        lambda rs=rs, t=t:
                        self._stores[rs].release_tx(t["tx_hex"])
                    )
            self.journal.remove(round_id)
            raise
        if not alive:
            self.journal.remove(round_id)
            return [results[t["tx_hex"]] for t in txs]
        # decision point: every surviving tx is reserved on every shard.
        # The reservations still carry the PREPARE-phase expiry — if the
        # prepares ate most of the TTL, a sibling's purge could free the
        # keys mid-finalise and admit a competitor (a torn commit). So
        # extend every survivor's locks into a fresh window sized for
        # finalise + a coordinator respawn, and VERIFY the extension
        # moved every row: a shortfall means expiry already released a
        # key, and that tx must abort HERE, before any shard finalises.
        now = self.clock()
        finalise_expires = now + 10 * self.prepare_ttl_s
        for t in list(alive):
            expected = sum(len(t["keys_by_shard"][s]) for s in t["shards"])
            moved = sum(
                self._retry_locked(
                    lambda s=s, t=t: self._stores[s].extend(
                        t["keys_by_shard"][s], t["tx_hex"],
                        finalise_expires
                    )
                )
                for s in t["shards"]
            )
            if moved < expected:
                results[t["tx_hex"]] = self._expiry_conflict(t)
                for rs in t["shards"]:
                    self._retry_locked(
                        lambda rs=rs, t=t:
                        self._stores[rs].release_tx(t["tx_hex"])
                    )
                with self._stats_lock:
                    self.cross_aborts += 1
                alive.remove(t)
                eventlog.emit(
                    "warning", "notary",
                    "cross-shard prepare outlived its TTL; aborted before "
                    "finalise", tx_id=t["tx_hex"][:16],
                )
        if not alive:
            self.journal.remove(round_id)
            return [results[t["tx_hex"]] for t in txs]
        # every survivor is re-locked past the finalise window — flip the
        # journal so a crash from here on RE-DRIVES the commit instead of
        # aborting
        self._fire("sharded.journal_committing", tx_id=round_id)
        self.journal.put(round_id, self._journal_record(
            "committing", union, alive, finalise_expires
        ))
        for s in union:
            items = [t for t in alive if t["keys_by_shard"].get(s)]
            if not items:
                continue
            self._fire("sharded.finalise", shard=f"s{s}", tx_id=round_id)
            self._finalise_shard_batch(s, items)
        self._fire("sharded.journal_remove", tx_id=round_id)
        self.journal.remove(round_id)
        with self._stats_lock:
            self.cross_commits += len(alive)
        eventlog.emit(
            "info", "notary", "cross-shard round committed",
            round=round_id[:16], shards=list(union), txs=len(alive),
            aborted=len(txs) - len(alive),
        )
        return [results[t["tx_hex"]] for t in txs]

    def _expiry_conflict(self, t: dict) -> Conflict:
        """Attribution for a tx whose reservation expired before the
        decision point: name the committed competitor where a shard's
        probe can see one; keys with no visible winner (purged but not
        yet re-taken) report the zero hash — the caller can safely
        retry, which re-screens against the live commit logs."""
        detail = {}
        for s in t["shards"]:
            keys = t["keys_by_shard"][s]
            probe = self._probes[s]
            committed = probe(keys) if probe is not None else {}
            for k in keys:
                winner = committed.get(k)
                if winner is not None and winner != t["tx_id"]:
                    detail[repr(t["ref_of_key"][k])] = winner
        if not detail:
            detail = {
                repr(t["ref_of_key"][k]): SecureHash(bytes(32))
                for s in t["shards"] for k in t["keys_by_shard"][s]
            }
        return Conflict(t["tx_id"], detail)

    @staticmethod
    def _journal_record(phase: str, union, txs, expires: float) -> dict:
        return {
            "phase": phase,
            "shards": list(union),
            "txs": {
                t["tx_hex"]: {
                    "keys": {
                        str(s): [k.hex() for k in ks]
                        for s, ks in t["keys_by_shard"].items()
                    },
                    "by": getattr(t["party"], "name", str(t["party"])),
                }
                for t in txs
            },
            "expires": expires,
        }

    def _prepare_shard_batch(self, shard: int, todo: List[dict],
                             expires: float) -> Dict[str, Conflict]:
        """Reserve every tx's keys on one shard; returns per-tx conflicts
        ({tx_hex: Conflict}) for the losers. Conflicts come from (a)
        another transaction's live reservation — including a
        batch-mate's, decided by insert order — or (b) the shard's
        committed log, probed AFTER our reservation landed: once we hold
        the key, a competing single-shard commit in another OS worker
        must lose at ITS reservation step, so any commit the post-reserve
        probe can't see is one that cannot happen. (Probe-first would
        leave a window: probe clean, sibling reserves+commits+releases,
        our reserve then succeeds — and the conflict would surface only
        at finalise, after earlier shards finalised.) Same-tx
        idempotency: our own rows and commits never conflict (a re-driven
        prepare after a retry). Losers keep their reservations here; the
        caller releases everything via release_tx on the spot."""
        probe = self._probes[shard]
        if probe is None:
            raise UniquenessException(Conflict(todo[0]["tx_id"], {
                "<config>": f"shard {shard} delegate "
                f"{type(self.delegates[shard]).__name__} supports no "
                "committed-state probe; cross-shard transactions require "
                "probeable delegates (docs/sharding.md)",
            }))
        now = self.clock()
        out: Dict[str, Conflict] = {}
        with self._locks[shard]:
            lost = self._retry_locked(
                lambda: self._stores[shard].reserve_many(
                    {t["tx_hex"]: t["keys_by_shard"][shard] for t in todo},
                    expires, now,
                )
            )
            held = []
            for t in todo:
                bad = lost.get(t["tx_hex"])
                if bad:
                    with self._stats_lock:
                        self.reservation_conflicts += 1
                    out[t["tx_hex"]] = Conflict(t["tx_id"], {
                        repr(t["ref_of_key"][k]):
                            SecureHash(bytes.fromhex(other))
                        for k, other in bad.items()
                    })
                else:
                    held.append(t)
            if held:
                committed = probe(
                    [k for t in held for k in t["keys_by_shard"][shard]]
                )
                for t in held:
                    bad = {
                        repr(t["ref_of_key"][k]): committed[k]
                        for k in t["keys_by_shard"][shard]
                        if k in committed and committed[k] != t["tx_id"]
                    }
                    if bad:
                        out[t["tx_hex"]] = Conflict(t["tx_id"], bad)
        return out

    def _finalise_shard_batch(self, shard: int, items: List[dict]) -> None:
        """Second round on one shard: ONE delegate commit_many for the
        group (idempotent by tx id) then one reservation release — one
        write transaction on a fused shard, so a sibling's screen sees
        either (reservation held, rows absent) or (released, rows
        present), never a torn middle. A conflict here is an INVARIANT
        BREACH — something committed these refs without going through
        this provider — surfaced loudly, never swallowed."""
        store = self._stores[shard]

        def _round():
            res = self.delegates[shard].commit_many([
                (
                    [t["ref_of_key"][k] for k in t["keys_by_shard"][shard]],
                    t["tx_id"], t["party"],
                )
                for t in items
            ])
            store.release_pairs([
                (k, t["tx_hex"])
                for t in items for k in t["keys_by_shard"][shard]
            ])
            return res

        def _fused_round():
            with store._db.transaction():
                return _round()

        with self._locks[shard]:
            if self._fused[shard]:
                res = self._retry_locked(_fused_round)
            else:
                res = _round()
            with self._stats_lock:
                self.shard_rounds[shard] += 1
        for t, r in zip(items, res):
            if r is not None:
                eventlog.emit(
                    "error", "notary",
                    "cross-shard finalise conflict (partition invariant "
                    "breached: a commit bypassed the sharded provider)",
                    tx_id=t["tx_hex"][:16], shard=shard,
                )
                raise UniquenessException(r)

    # -- recovery ------------------------------------------------------------

    def recover(self) -> Dict[str, int]:
        """Replay the prepare journal (restart / takeover): "committing"
        round records re-drive every member tx's finalise on every shard
        (delegate commits are idempotent per tx id), anything earlier
        aborts ONCE EXPIRED — releasing its reservations so no state-ref
        stays wedged, while an unexpired prepare round is presumed to
        belong to a live sibling coordinator and left alone.
        Expired reservations with no journal record die here too."""
        recovered = {"committed": 0, "aborted": 0, "expired": 0,
                     "conflicted": 0}
        now = self.clock()
        for round_id, rec in self.journal.items():
            txs = rec.get("txs", {})
            if (
                rec.get("phase") != "committing"
                and rec.get("expires", 0) > now
            ):
                # a LIVE sibling coordinator's prepare round (shared-db
                # mode spawns/respawns workers while rounds are in
                # flight): aborting it would release reservations its
                # owner is about to finalise against. Not ours until it
                # expires — a genuinely dead coordinator's round becomes
                # abortable then, and its reservations die by expiry
                # even sooner. "committing" rounds re-drive regardless:
                # past the decision point the commit is idempotent per
                # tx id, live owner or not.
                continue
            if rec.get("phase") == "committing":
                for tx_hex, info in txs.items():
                    tx_id = SecureHash(bytes.fromhex(tx_hex))
                    party = type("_Recovered", (), {
                        "name": info.get("by", "recovered"),
                    })()
                    conflicted_shard = None
                    for s_str, key_hexes in info.get("keys", {}).items():
                        s = int(s_str)
                        keys = [bytes.fromhex(k) for k in key_hexes]
                        # the commit-log key is txhash(32) + index(4):
                        # the StateRefs rebuild exactly, so the
                        # re-driven delegate round writes the same rows
                        refs = [
                            StateRef(SecureHash(k[:32]),
                                     int.from_bytes(k[32:], "big"))
                            for k in keys
                        ]
                        with self._locks[s]:
                            with self._stats_lock:
                                self.shard_rounds[s] += 1

                            def redrive(s=s, refs=refs, keys=keys,
                                        tx_id=tx_id, party=party,
                                        tx_hex=tx_hex):
                                res = self.delegates[s].commit_many(
                                    [(refs, tx_id, party)]
                                )
                                self._stores[s].release(keys, tx_hex)
                                return res

                            res = self._retry_locked(redrive)
                        if res and res[0] is not None:
                            conflicted_shard = s
                    if conflicted_shard is None:
                        with self._stats_lock:
                            self.recovered_commits += 1
                        recovered["committed"] += 1
                    else:
                        # a competitor consumed the refs during the
                        # outage window (the reservation expired before
                        # this recovery ran): the decided round is now
                        # torn — count and log it LOUDLY, never as a
                        # recovered commit
                        recovered["conflicted"] += 1
                        eventlog.emit(
                            "error", "notary",
                            "re-driven cross-shard commit conflicted: "
                            "refs consumed by a competitor during the "
                            "outage window",
                            tx_id=tx_hex[:16], shard=conflicted_shard,
                        )
                self.journal.remove(round_id)
            else:
                for tx_hex in txs or {round_id: None}:
                    released = self._retry_locked(
                        lambda tx_hex=tx_hex:
                        self.reservations.release_tx(tx_hex)
                    )
                    with self._stats_lock:
                        self.recovered_aborts += 1
                    recovered["aborted"] += 1
                    recovered["expired"] += released
                self.journal.remove(round_id)
        recovered["expired"] += self._retry_locked(
            lambda: self.reservations.purge_expired(self.clock())
        )
        if any(recovered.values()):
            eventlog.emit(
                "warning", "notary", "sharded prepare-journal recovery",
                **recovered,
            )
        return recovered

    # -- observability -------------------------------------------------------

    def is_consumed(self, ref: StateRef) -> bool:
        d = self.delegates[self.shard_of(ref)]
        if hasattr(d, "is_consumed"):
            return d.is_consumed(ref)
        probe = self._probes[self.shard_of(ref)]
        return bool(probe and probe([_key_of(ref)]))

    def stats(self) -> dict:
        with self._stats_lock:  # one consistent snapshot
            return {
                "n_shards": self.n_shards,
                "single_commits": self.single_commits,
                "cross_commits": self.cross_commits,
                "cross_aborts": self.cross_aborts,
                "reservation_conflicts": self.reservation_conflicts,
                "recovered_commits": self.recovered_commits,
                "recovered_aborts": self.recovered_aborts,
                "shard_rounds": dict(self.shard_rounds),
            }

"""Flow session wire protocol + per-session state.

Reference: `node/.../services/statemachine/SessionMessage.kt` — SessionInit /
SessionConfirm / SessionReject / SessionData / SessionEnd, with the
Initiating→Initiated handshake (`FlowSessionState.kt`).

Additions for the replay-checkpoint model (no Quasar stack serialization):
every data message carries a per-direction sequence number, so re-sends
after a crash-restore are idempotent — the receiving side drops seqs it has
already consumed.  SessionInit is deduplicated by initiator session id.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..core.identity import Party
from ..core.serialization.codec import register_adapter

SESSION_TOPIC = "platform.session"


@dataclass(frozen=True)
class SessionInit:
    initiator_session_id: str
    flow_name: str
    flow_version: int
    first_payload: Optional[bytes]  # pre-serialized, seq 0 if present


@dataclass(frozen=True)
class SessionConfirm:
    initiator_session_id: str
    initiated_session_id: str


@dataclass(frozen=True)
class SessionReject:
    initiator_session_id: str
    error: str


@dataclass(frozen=True)
class SessionData:
    recipient_session_id: str
    seq: int
    payload: bytes  # pre-serialized


@dataclass(frozen=True)
class SessionEnd:
    recipient_session_id: str
    error: Optional[str]  # FlowException message propagated to the peer


for cls, name, fields in [
    (SessionInit, "SessionInit",
     ["initiator_session_id", "flow_name", "flow_version", "first_payload"]),
    (SessionConfirm, "SessionConfirm",
     ["initiator_session_id", "initiated_session_id"]),
    (SessionReject, "SessionReject", ["initiator_session_id", "error"]),
    (SessionData, "SessionData", ["recipient_session_id", "seq", "payload"]),
    (SessionEnd, "SessionEnd", ["recipient_session_id", "error"]),
]:
    register_adapter(
        cls, name,
        (lambda fs: lambda m: {f: getattr(m, f) for f in fs})(fields),
        (lambda c, fs: lambda d: c(**{f: d[f] for f in fs}))(cls, fields),
    )


#: broker header carrying the session-routing hint (below); a sharded
#: node's router dispatches on it without deserializing the payload
ROUTE_HINT_HEADER = "x-session-route"


def route_hint(msg) -> Optional[str]:
    """Routing hint the SENDER stamps into broker headers so a sharded
    receiver's router (shardhost.ShardRouter) can pick the worker
    without codec-deserializing every payload on its one thread:
    "h:<sid>" = stable-hash this id across workers (SessionInit — no
    local owner yet), "t:<sid>" = the id carries the owning worker's
    tag (`w<k>-` prefix, or none ⇒ supervisor). Messages without the
    header (older senders) fall back to payload decode."""
    if isinstance(msg, SessionInit):
        return "h:" + msg.initiator_session_id
    if isinstance(msg, (SessionData, SessionEnd)):
        return "t:" + msg.recipient_session_id
    if isinstance(msg, (SessionConfirm, SessionReject)):
        return "t:" + msg.initiator_session_id
    return None


class SessionState(enum.Enum):
    INITIATING = "initiating"  # init sent, awaiting confirm
    INITIATED = "initiated"
    ENDED = "ended"


@dataclass
class FlowSession:
    """One side of a peer-to-peer session within a flow."""
    local_id: str
    peer: Party
    state: SessionState
    peer_id: Optional[str] = None
    send_seq: int = 0
    recv_seq: int = 0  # next expected incoming seq
    # incoming data buffered out-of-order or before the flow asks
    inbox: Dict[int, bytes] = field(default_factory=dict)
    # outgoing data buffered while INITIATING (flushed on confirm)
    outbox: List[bytes] = field(default_factory=list)
    # the payload that rode the SessionInit (seq 0), kept for init re-sends
    init_payload: Optional[bytes] = None
    # True on the responder side (used to rebuild init-dedup after restore)
    is_initiated_side: bool = False
    # set when the peer ended the session (error message or "" for clean end)
    end_error: Optional[str] = None
    ended_by_peer: bool = False

    def to_dict(self) -> dict:
        return {
            "local_id": self.local_id,
            "peer": self.peer,
            "state": self.state.value,
            "peer_id": self.peer_id,
            "send_seq": self.send_seq,
            "recv_seq": self.recv_seq,
            "inbox": {str(k): v for k, v in self.inbox.items()},
            "outbox": list(self.outbox),
            "init_payload": self.init_payload,
            "is_initiated_side": self.is_initiated_side,
            "end_error": self.end_error,
            "ended_by_peer": self.ended_by_peer,
        }

    @staticmethod
    def from_dict(d: dict) -> "FlowSession":
        return FlowSession(
            local_id=d["local_id"],
            peer=d["peer"],
            state=SessionState(d["state"]),
            peer_id=d["peer_id"],
            send_seq=d["send_seq"],
            recv_seq=d["recv_seq"],
            inbox={int(k): v for k, v in d["inbox"].items()},
            outbox=list(d["outbox"]),
            init_payload=d["init_payload"],
            is_initiated_side=d["is_initiated_side"],
            end_error=d["end_error"],
            ended_by_peer=d["ended_by_peer"],
        )

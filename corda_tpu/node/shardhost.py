"""Multi-process node workers behind one broker (docs/sharding.md).

The round-11 profiler put a number on the ceiling: one CPython process
runs the whole node — ~25 threads convoy behind one GIL on one core
(docs/perf-system.md round-11 addendum). This module splits the
flow/verify hot path across M OS worker processes, each with its OWN
GIL, behind the ONE existing broker (`messaging/net.py` BrokerServer):

    peers/bridges ──> p2p.inbound.<name> ──ShardRouter──┬─> …<name>.w0 ─ worker 0
                                                        ├─> …<name>.w1 ─ worker 1
                                                        └─> …<name>.sup ─ supervisor node
    workers ──> p2p.egress ──EgressPump──> bridges / local inbound

  * **Routing** pins a SESSION to the worker that owns its flow: worker
    flow ids carry a `w<k>-` tag (StateMachineManager.flow_id_tag), and
    every session id is `<flow id>:<n>`, so SessionData/End route by
    their recipient id's tag and SessionConfirm/Reject by the initiator
    id's tag. A SessionInit has no local owner yet — it routes by a
    STABLE hash of the initiator's session id, which also sends every
    re-transmitted init to the same worker so init-dedup keeps working.
    Non-session topics (raft, bft, network map) and untagged session ids
    (supervisor-started flows) go to the supervisor's `.sup` leg.
  * **Workers** are real `python -m corda_tpu.node <dir> --shard-worker
    k` processes: RemoteBroker to the supervisor's socket, the SHARED
    node database (WAL sqlite; flow checkpoints partition by the id
    tag), the same legal identity (entropy pinned in
    `<base>/identity.entropy`), their own InMemory verifier (the verify
    hot path scales with them), their own RPC server as a COMPETING
    consumer on `rpc.server.requests`, and an OpsServer each.
  * **Supervisor** spawns/monitors/respawns workers, registers every
    shard-addressed queue EAGERLY (so the PR-3 `P2P.QueueDepth` gauges
    and PR-5 bounded-queue caps cover worker queues from the first
    message — no unbounded window before the first consumer attaches),
    replays peer registrations to (re)spawned workers over per-worker
    control queues, aggregates worker /healthz + key metrics behind
    `GET /workers`, and reports a `workers` health component.
  * **A worker death is a transient**, not a loss: its unacked queue
    messages redeliver to the respawned process, whose state machine
    restores the dead worker's checkpoints (same `w<k>-` partition) and
    whose hospital readmits transient failures exactly as on a
    single-process node. Admission caps apply per worker.

Notary nodes compose with this through the PARTITIONED uniqueness
provider (sharded_notary.py) in shared-database mode: reservations and
the prepare journal live in sqlite, so any worker can coordinate a
cross-shard commit and any other can recover it. Raft/BFT cluster
membership stays single-process (the replica state machines are not
multi-process safe); a cluster member node ignores `node_workers`.
"""
from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Dict, List, Optional

from ..core.serialization.codec import deserialize
from ..utils import atomicfile, eventlog, lockorder
from .session import (
    ROUTE_HINT_HEADER,
    SESSION_TOPIC,
    SessionConfirm,
    SessionData,
    SessionEnd,
    SessionInit,
    SessionReject,
)

#: workers' outbound funnel: one queue the supervisor's egress pump
#: drains into bridges / local inbound queues
EGRESS_QUEUE = "p2p.egress"


def rpc_session_secret(identity_entropy: int) -> bytes:
    """The shared HMAC key making RPC session tokens portable across the
    supervisor's and every worker's RPC server (rpc/server.py
    session_secret): all serve one identity, so they derive one secret
    from its (never client-visible) entropy."""
    import hashlib

    return hashlib.sha256(
        b"corda-tpu-rpc-session:" + str(int(identity_entropy)).encode()
    ).digest()

# ASCII digits ONLY (not \d): tags are generated as f"w{index}-" so
# only ASCII ever appears, and the native route_hints_many parser is
# ASCII-only — \d's Unicode-digit acceptance would let a hostile hint
# like "t:w٣-…" route differently on the native vs fallback path,
# splitting a session across workers
_TAG = re.compile(r"^w([0-9]+)-")


def worker_queue(node_name: str, index: int) -> str:
    return f"p2p.inbound.{node_name}.w{index}"


def supervisor_queue(node_name: str) -> str:
    return f"p2p.inbound.{node_name}.sup"


def control_queue(index: int) -> str:
    return f"shardhost.control.w{index}"


def worker_tag_of(session_or_flow_id: str) -> Optional[int]:
    """The owning worker index encoded in a tagged flow/session id
    (`w3-<uuid>[:n]`), or None for supervisor/unsharded ids."""
    m = _TAG.match(session_or_flow_id)
    return int(m.group(1)) if m else None


def _stable_hash(s: str) -> int:
    import hashlib

    return int.from_bytes(hashlib.sha256(s.encode()).digest()[:8], "big")


#: route_session_hint: "no usable hint — decode the payload instead"
_NO_HINT = object()


def route_session_hint(hint: Optional[str], n_workers: int):
    """Worker index (int) or None (supervisor) from a sender-stamped
    `x-session-route` header (session.route_hint: "h:<sid>" = stable
    hash, "t:<sid>" = the id's `w<k>-` tag), or the `_NO_HINT` sentinel
    when the header is absent/malformed (older sender) — the caller
    then falls back to payload decode. Pure function like
    route_session_payload, and MUST agree with it on every hint the
    current senders emit (a retransmit may arrive once with and once
    without the header; both copies have to land on the same worker
    for session dedup to absorb the duplicate)."""
    if not hint or len(hint) < 3 or hint[1] != ":":
        return _NO_HINT
    kind, sid = hint[0], hint[2:]
    if kind == "h":
        return _stable_hash(sid) % n_workers
    if kind == "t":
        tag = worker_tag_of(sid)
        if tag is not None and 0 <= tag < n_workers:
            return tag
        return None
    return _NO_HINT


def route_session_payload(payload: bytes, n_workers: int) -> Optional[int]:
    """Worker index a session message belongs to, or None (supervisor).
    Pure function — the router's whole policy, unit-testable without
    processes. Undecodable payloads fall to the supervisor, whose pump
    already tolerates junk."""
    try:
        msg = deserialize(payload)
    except Exception:
        return None
    if isinstance(msg, SessionInit):
        # no local owner yet: stable hash keeps retransmits (and their
        # dedup) on one worker
        return _stable_hash(msg.initiator_session_id) % n_workers
    if isinstance(msg, (SessionData, SessionEnd)):
        sid = msg.recipient_session_id
    elif isinstance(msg, (SessionConfirm, SessionReject)):
        sid = msg.initiator_session_id
    else:
        return None
    tag = worker_tag_of(sid)
    if tag is not None and 0 <= tag < n_workers:
        return tag
    return None


class ShardRouter:
    """Consumes the node's bare inbound queue and forwards each message
    to its shard-addressed leg (worker k or the supervisor). At-least-
    once: forward THEN ack — a router crash redelivers, and session
    seq-dedup absorbs the duplicate downstream."""

    def __init__(self, broker, node_name: str, n_workers: int):
        self.broker = broker
        self.node_name = node_name
        self.n_workers = n_workers
        self.routed = 0
        self.to_supervisor = 0
        self._stop = threading.Event()
        self._consumer = broker.create_consumer(f"p2p.inbound.{node_name}")
        self._thread = threading.Thread(
            target=self._run, name=f"shard-router-{node_name}", daemon=True
        )

    def target_of(self, msg) -> str:
        if msg.headers.get("topic") != SESSION_TOPIC:
            return supervisor_queue(self.node_name)
        # fast path: route on the sender-stamped hint header alone —
        # no codec deserialize of the payload on this one thread
        k = route_session_hint(
            msg.headers.get(ROUTE_HINT_HEADER), self.n_workers
        )
        if k is _NO_HINT:
            k = route_session_payload(msg.payload, self.n_workers)
        if k is None:
            return supervisor_queue(self.node_name)
        return worker_queue(self.node_name, k)

    def targets_of(self, batch) -> List[str]:
        """Route a whole drain batch: ONE GIL-releasing native call
        resolves every hint-carrying session message
        (pumpcore.route_hints_many — header-only, payloads untouched);
        only hint-less messages (older senders) fall back to the
        per-message payload decode. Differentially pinned against
        target_of: both paths must send a retransmit to the same
        worker or session dedup breaks."""
        from ..messaging import pumpcore

        sup = supervisor_queue(self.node_name)
        targets: List[Optional[str]] = [None] * len(batch)
        rows: List[int] = []
        hints: List[Optional[str]] = []
        for i, msg in enumerate(batch):
            if msg.headers.get("topic") != SESSION_TOPIC:
                targets[i] = sup
            else:
                rows.append(i)
                hints.append(msg.headers.get(ROUTE_HINT_HEADER))
        if rows:
            codes = pumpcore.route_hints_many(hints, self.n_workers)
            for i, code in zip(rows, codes):
                if code == pumpcore.NO_HINT:
                    k = route_session_payload(
                        batch[i].payload, self.n_workers
                    )
                elif code == pumpcore.SUPERVISOR:
                    k = None
                else:
                    k = code
                targets[i] = (
                    sup if k is None else worker_queue(self.node_name, k)
                )
        return targets  # type: ignore[return-value]

    def start(self) -> "ShardRouter":
        self._thread.start()
        return self

    def _run(self) -> None:
        from ..messaging.broker import QueueFullError

        while not self._stop.is_set():
            batch = self._consumer.receive_many(64, timeout=0.2)
            if not batch:
                continue
            items = []
            for msg, target in zip(batch, self.targets_of(batch)):
                if target.endswith(".sup"):
                    self.to_supervisor += 1
                items.append((target, msg.payload, msg.headers))
            try:
                self.broker.send_many(items)
            except QueueFullError:
                # a bounded worker queue is full: BLOCK here per message
                # until it drains — the router propagating backpressure
                # upstream (its own inbound queue fills, whose reject
                # policy then pushes back on the senders) is the design
                aborted = False
                for target, payload, headers in items:
                    sent = False
                    while not self._stop.is_set():
                        try:
                            self.broker.send(target, payload, headers)
                            sent = True
                            break
                        except QueueFullError:
                            time.sleep(0.02)
                    if not sent:
                        aborted = True
                        break
                if aborted:
                    # stop() mid-backpressure: ack NOTHING — the whole
                    # unacked batch redelivers after restart ("forward
                    # THEN ack"; session dedup absorbs duplicates of the
                    # items that did go out before the abort)
                    continue
            self._consumer.ack_many(batch)
            self.routed += len(batch)

    def stop(self) -> None:
        self._stop.set()
        self._consumer.close()
        if self._thread.ident is not None:
            self._thread.join(timeout=2)


class EgressPump:
    """Drains workers' outbound messages (EGRESS_QUEUE, `x-dest` header)
    into the supervisor's bridge outbound queues — or straight back into
    a local inbound queue for loopback/same-broker peers."""

    def __init__(self, broker, bridges=None):
        self.broker = broker
        self.bridges = bridges
        self.forwarded = 0
        self.dropped = 0
        self._stop = threading.Event()
        broker.create_queue(
            EGRESS_QUEUE,
            durable=getattr(broker, "_journal_dir", None) is not None,
        )
        self._consumer = broker.create_consumer(EGRESS_QUEUE)
        self._thread = threading.Thread(
            target=self._run, name="shard-egress", daemon=True
        )

    def start(self) -> "EgressPump":
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            batch = self._consumer.receive_many(64, timeout=0.2)
            if not batch:
                continue
            # resolve every target first — header-only work, payloads
            # untouched — so the happy path forwards the whole drain in
            # ONE broker.send_many (one lock acquisition / native-framed
            # wire call) instead of N per-message sends
            resolved = []
            for msg in batch:
                headers = dict(msg.headers)
                dest = headers.pop("x-dest", None)
                try:
                    if dest is None:
                        raise ValueError("egress message without x-dest")
                    if (
                        self.bridges is not None
                        and self.bridges.route_for(dest) is not None
                    ):
                        target = self.bridges.outbound_queue(dest)
                    else:
                        target = f"p2p.inbound.{dest}"
                    resolved.append((target, msg.payload, headers))
                except Exception as exc:
                    # an unroutable peer is an operational fact, not a
                    # pump-killing one
                    self.dropped += 1
                    eventlog.emit(
                        "warning", "messaging", "egress drop",
                        dest=dest, error=type(exc).__name__,
                    )
            aborted = False
            if resolved:
                try:
                    self.broker.send_many(resolved)
                    self.forwarded += len(resolved)
                # lint: allow(swallow) — _forward_slow reports per message
                except Exception:
                    # ANY batch failure falls back to the per-message
                    # path (exact blocking-backpressure and per-message
                    # drop semantics — the old loop caught Exception per
                    # message, and this pump thread must never die).
                    # BrokerError is all-or-nothing; a non-broker error
                    # (journal OSError mid-batch) may have applied a
                    # prefix, whose per-message resend duplicates are
                    # absorbed by session seq-dedup downstream — the
                    # documented at-least-once contract.
                    aborted = self._forward_slow(resolved)
            if aborted:
                # stop() mid-backpressure: not a drop — ack NOTHING so
                # the durable egress queue redelivers the batch after
                # restart (duplicates of already-forwarded items are
                # absorbed by session seq-dedup downstream)
                continue
            self._consumer.ack_many(batch)

    def _forward_slow(self, resolved) -> bool:
        """Per-message forwarding for a drain the batch path refused:
        block on full destinations (backpressure), drop unroutable ones.
        Returns True when stop() aborted mid-backpressure (caller must
        NOT ack)."""
        from ..messaging.broker import QueueFullError

        for target, payload, headers in resolved:
            try:
                while True:
                    try:
                        self.broker.send(target, payload, headers)
                        break
                    except QueueFullError:
                        # a bounded destination queue is full: BLOCK
                        # until it drains, like ShardRouter — a session
                        # message dropped here has no retransmit, the
                        # flow would hang to timeout
                        if self._stop.is_set():
                            return True
                        time.sleep(0.02)
                self.forwarded += 1
            except Exception as exc:
                self.dropped += 1
                eventlog.emit(
                    "warning", "messaging", "egress drop",
                    dest=target, error=type(exc).__name__,
                )
        return False

    def stop(self) -> None:
        self._stop.set()
        self._consumer.close()
        if self._thread.ident is not None:
            self._thread.join(timeout=2)


class _WorkerProc:
    """One spawned worker process + its lifecycle counters."""

    def __init__(self, index: int):
        self.index = index
        self.proc: Optional[subprocess.Popen] = None
        self.respawns = 0
        self.last_exit: Optional[int] = None
        self.started_at: Optional[float] = None

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class ShardSupervisor:
    """Spawns, monitors and respawns the M worker processes; owns the
    router + egress pump; aggregates worker health/metrics (module
    docstring). Construct AFTER the node object (it registers gauges and
    a health component on it) and start() after node.start()."""

    #: respawn backoff: a worker that dies instantly must not spin-fork
    RESPAWN_DELAY_S = 0.5

    def __init__(self, broker, node, config_dir: str, n_workers: int,
                 broker_port: int, bridges=None,
                 jax_platform: Optional[str] = "cpu",
                 base_directory: Optional[str] = None):
        self.broker = broker
        self.node = node
        self.config_dir = config_dir
        self.n_workers = int(n_workers)
        self.broker_port = broker_port
        self.bridges = bridges
        self.jax_platform = jax_platform
        self.base_directory = base_directory or config_dir
        self.name = node.info.name
        self.workers = [_WorkerProc(i) for i in range(self.n_workers)]
        self._peers: Dict[str, tuple] = {}  # name -> (party, services)
        self._lock = lockorder.make_lock("ShardSupervisor._lock")
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self.router: Optional[ShardRouter] = None
        self.egress: Optional[EgressPump] = None
        self._register_queues()
        self._register_telemetry()

    # -- queue registration (eager: gauges + caps from message one) ----------

    def _register_queues(self) -> None:
        """EVERY shard-addressed queue exists — created, bounded, gauged
        — before any worker attaches or any message arrives. Without
        this, a queue created lazily by its first producer would sit
        uncapped and uncounted until its consumer showed up."""
        durable = getattr(self.broker, "_journal_dir", None) is not None
        max_depth = int(os.environ.get("CORDA_TPU_P2P_QUEUE_MAX", 10_000))
        # the bare inbound queue (what peers' bridges address) feeds the
        # router; it must exist before the first bridge delivery
        self.broker.create_queue(f"p2p.inbound.{self.name}", durable=durable)
        if max_depth > 0:
            self.broker.set_queue_bound(
                f"p2p.inbound.{self.name}", max_depth, "reject"
            )
        # ALL THREE shard-addressed legs: every worker's ".w<k>" AND the
        # supervisor's ".sup" (created here before BrokerMessagingService
        # attaches to it — otherwise it would sit uncapped, the one leg
        # the CORDA_TPU_P2P_QUEUE_MAX cap silently missed)
        legs = [worker_queue(self.name, k) for k in range(self.n_workers)]
        legs.append(supervisor_queue(self.name))
        for q in legs:
            self.broker.create_queue(q, durable=durable)
            if max_depth > 0:
                self.broker.set_queue_bound(q, max_depth, "reject")
        for k in range(self.n_workers):
            # control traffic is tiny and replayable: bounded drop-oldest
            self.broker.create_queue(control_queue(k))
            self.broker.set_queue_bound(control_queue(k), 1024, "drop_oldest")
        self.broker.create_queue(EGRESS_QUEUE, durable=durable)
        if max_depth > 0:
            self.broker.set_queue_bound(EGRESS_QUEUE, max_depth, "reject")

    def _register_telemetry(self) -> None:
        metrics = self.node.metrics
        metrics.gauge(
            "Shard.Workers.Alive",
            lambda: sum(1 for w in self.workers if w.alive()),
        )
        metrics.gauge(
            "Shard.Workers.Respawns",
            lambda: sum(w.respawns for w in self.workers),
        )
        metrics.gauge(
            "Shard.Router.Routed",
            lambda: self.router.routed if self.router else 0,
        )
        metrics.gauge(
            "Shard.Egress.Forwarded",
            lambda: self.egress.forwarded if self.egress else 0,
        )
        for k in range(self.n_workers):
            metrics.gauge(
                f"Shard.QueueDepth{{worker={k}}}",
                lambda q=worker_queue(self.name, k): (
                    self.broker.message_count(q)
                ),
            )
        self.node.health.register("workers", self._check_workers)

    def _check_workers(self) -> dict:
        detail = {
            f"w{w.index}": {
                "alive": w.alive(), "respawns": w.respawns,
                "queue_depth": self.broker.message_count(
                    worker_queue(self.name, w.index)
                ),
            }
            for w in self.workers
        }
        # a dead worker mid-respawn is degraded, not down: readiness
        # holds as long as at least one worker serves
        detail["ok"] = any(w.alive() for w in self.workers)
        return detail

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ShardSupervisor":
        self.router = ShardRouter(
            self.broker, self.name, self.n_workers
        ).start()
        self.egress = EgressPump(self.broker, self.bridges).start()
        for w in self.workers:
            self._spawn(w)
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="shard-monitor", daemon=True
        )
        self._monitor.start()
        eventlog.emit(
            "info", "shardhost", "supervisor started",
            workers=self.n_workers, node=self.name,
        )
        return self

    def _spawn(self, w: _WorkerProc) -> None:
        env = dict(os.environ)
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        )))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        # supervisor death must reap the fleet
        env["CORDA_TPU_EXIT_ON_ORPHAN"] = "1"
        log_path = os.path.join(
            self.base_directory, f"worker{w.index}.log"
        )
        args = [
            sys.executable, "-m", "corda_tpu.node", self.config_dir,
            "--shard-worker", str(w.index),
            "--workers", str(self.n_workers),
            "--broker-port", str(self.broker_port),
        ]
        if self.jax_platform:
            args += ["--jax-platform", self.jax_platform]
        with open(log_path, "a") as log_file:  # Popen dups the fd
            w.proc = subprocess.Popen(
                args, stdout=log_file, stderr=subprocess.STDOUT, env=env,
            )
        w.started_at = time.monotonic()
        # the worker's control queue replays every peer it missed
        with self._lock:
            peers = list(self._peers.values())
        for party, services in peers:
            self._send_control(w.index, {
                "kind": "peer", "party": party, "services": list(services),
            })

    def _monitor_loop(self) -> None:
        while not self._stop.wait(0.5):
            for w in self.workers:
                if w.proc is not None and not w.alive():
                    w.last_exit = w.proc.returncode
                    w.respawns += 1
                    eventlog.emit(
                        "warning", "shardhost", "worker died; respawning",
                        worker=w.index, exit=w.last_exit,
                    )
                    # transient, not a loss: unacked messages already
                    # redelivered broker-side; checkpoints restore in
                    # the respawn; hospital readmits in-flight retries
                    time.sleep(self.RESPAWN_DELAY_S)
                    if not self._stop.is_set():
                        self._spawn(w)

    def broadcast_peer(self, party, services) -> None:
        """Forward a network-map registration to every worker (and
        remember it for respawn replay)."""
        with self._lock:
            self._peers[party.name] = (party, tuple(services))
        for w in self.workers:
            self._send_control(w.index, {
                "kind": "peer", "party": party, "services": list(services),
            })

    def _send_control(self, index: int, record: dict) -> None:
        from ..core.serialization.codec import serialize

        try:
            self.broker.send(control_queue(index), serialize(record))
        except Exception:
            pass  # bounded drop-oldest queue; respawn replays anyway

    # -- aggregation ---------------------------------------------------------

    def _worker_ops_port(self, index: int) -> Optional[int]:
        try:
            with open(os.path.join(
                self.base_directory, f"worker{index}.ops_port"
            )) as fh:
                return int(fh.read().strip())
        except (OSError, ValueError):
            return None

    def _fetch_json(self, port: int, path: str) -> Optional[dict]:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=2
            ) as resp:
                return json.loads(resp.read())
        except Exception:
            return None

    def snapshot(self, probe_workers: bool = True) -> dict:
        """The `GET /workers` operator view: per-worker process state,
        queue depth, and (when probe_workers) each worker's own /healthz
        verdict + flow counts aggregated over HTTP."""
        out = {
            "workers": self.n_workers,
            "router_routed": self.router.routed if self.router else 0,
            "router_to_supervisor": (
                self.router.to_supervisor if self.router else 0
            ),
            "egress_forwarded": self.egress.forwarded if self.egress else 0,
            "egress_dropped": self.egress.dropped if self.egress else 0,
            "detail": {},
        }
        for w in self.workers:
            entry = {
                "alive": w.alive(),
                "pid": w.proc.pid if w.proc is not None else None,
                "respawns": w.respawns,
                "last_exit": w.last_exit,
                "queue_depth": self.broker.message_count(
                    worker_queue(self.name, w.index)
                ),
                "ops_port": self._worker_ops_port(w.index),
            }
            out["detail"][f"w{w.index}"] = entry
        if probe_workers:
            # probe concurrently: one wedged worker costs ONE probe
            # timeout for the whole /workers request, not one per worker
            def _probe(entry: dict) -> None:
                health = self._fetch_json(entry["ops_port"], "/healthz")
                if health is not None:
                    entry["healthz"] = health.get("status", health)

            probes = [
                threading.Thread(
                    target=_probe, args=(e,), daemon=True,
                    name=f"worker-probe-{e['ops_port']}",
                )
                for e in out["detail"].values()
                if e["alive"] and e["ops_port"]
            ]
            for t in probes:
                t.start()
            deadline = time.monotonic() + 3
            for t in probes:
                t.join(timeout=max(0.1, deadline - time.monotonic()))
        return out

    def stop(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=2)
        for w in self.workers:
            self._send_control(w.index, {"kind": "stop"})
        deadline = time.monotonic() + 5
        for w in self.workers:
            if w.proc is None:
                continue
            try:
                w.proc.terminate()
                w.proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except Exception:
                try:
                    w.proc.kill()
                except Exception:
                    pass
        if self.router is not None:
            self.router.stop()
        if self.egress is not None:
            self.egress.stop()


# ---------------------------------------------------------------------------
# Worker-process side
# ---------------------------------------------------------------------------

class _PrefetchOneBroker:
    """RemoteBroker facade whose consumers take prefetch=1: COMPETING
    consumers (worker RPC servers sharing rpc.server.requests) must not
    buffer requests an idle sibling could serve (net.RemoteConsumer
    docstring)."""

    def __init__(self, broker):
        self._broker = broker

    def __getattr__(self, name):
        return getattr(self._broker, name)

    def create_consumer(self, queue_name: str, prefetch: int = 1):
        return self._broker.create_consumer(queue_name, prefetch=1)


def make_worker_messaging(broker, me, worker_index: int):
    """The worker's messaging service: a BrokerMessagingService that
    consumes the worker's shard-addressed inbound leg and funnels every
    outbound send through the shared egress queue (the supervisor's pump
    owns bridge routing) — the pump, handlers, metrics and health
    surface stay stock."""
    from ..utils import tracing
    from .network import BrokerMessagingService

    class WorkerMessaging(BrokerMessagingService):
        def send(self, peer, topic, payload, headers=None):
            extra = headers
            headers = {
                "topic": topic, "sender": self.me.name,
                "sender_key": self.me.owning_key.encoded.hex(),
                "x-dest": peer.name,
            }
            if extra:
                # e.g. the session route hint — rides through the
                # egress pump so the PEER's router gets the fast path
                headers.update(extra)
            tp = tracing.current_traceparent()
            if tp is not None:
                headers[tracing.TRACEPARENT_HEADER] = tp
            self.broker.send(EGRESS_QUEUE, payload, headers)

    svc = WorkerMessaging(
        broker, me, bridges=None, queue_suffix=f".w{worker_index}"
    )
    svc.worker_index = worker_index
    return svc


def run_worker(config_dir: str, index: int, n_workers: int,
               broker_port: int) -> int:
    """`python -m corda_tpu.node <dir> --shard-worker K` entry: one
    worker process of a sharded node (module docstring)."""
    from ..messaging.net import RemoteBroker
    from ..rpc.ops import CordaRPCOps
    from ..rpc.server import RPCServer, RPCUser
    from .config import load_config
    from .node import AbstractNode

    cfg = load_config(config_dir, {})
    base = cfg.base_directory
    import importlib

    for mod in cfg.cordapps:  # same CorDapp scan as the supervisor
        importlib.import_module(mod)
    if cfg.node.identity_entropy is None:
        # the supervisor pinned the shared identity before spawning us
        with open(os.path.join(base, "identity.entropy")) as fh:
            cfg.node.identity_entropy = int(fh.read().strip())
    # each worker serves its own ops endpoint on an ephemeral port; the
    # supervisor discovers it through the port file for /workers
    cfg.node.ops_port = 0
    # worker verification is in-process BY DESIGN: the verify hot path
    # scales with worker count (an OutOfProcess config would funnel all
    # workers back into one shared pool — still possible, but opt-in by
    # running the workers' node.conf unsharded)
    cfg.node.verifier_type = "InMemory"

    # TLS nodes wrap the supervisor's broker socket (pki.server_wrap in
    # __main__); the worker must speak the same mutual TLS or its
    # handshake fails and the supervisor respawn-loops it forever
    client_wrap = None
    if cfg.tls:
        from ..core.crypto import pki

        entries = pki.dev_certificates(
            cfg.certificates_dir, cfg.node.my_legal_name
        )
        client_wrap = pki.client_wrap(
            pki.client_ssl_context(cfg.certificates_dir, entries)
        )

    broker = RemoteBroker("127.0.0.1", broker_port, client_wrap=client_wrap)
    node = AbstractNode(
        cfg.node,
        messaging_factory=lambda me: make_worker_messaging(broker, me, index),
        broker=None,
    )
    node.smm.flow_id_tag = f"w{index}"
    tag = f"w{index}-"
    node.smm.checkpoint_filter = lambda fid: fid.startswith(tag)

    users = [
        RPCUser(u["username"], u["password"], set(u.get("permissions", ["ALL"])))
        for u in cfg.rpc_users
    ] or None
    # competing consumer on the shared rpc.server.requests queue:
    # prefetch=1 so an idle sibling can serve what this worker hasn't
    # started yet (net.RemoteConsumer competing-consumer contract), and
    # the shared session secret so a login any sibling served
    # authenticates here too
    rpc = RPCServer(
        _PrefetchOneBroker(broker),
        CordaRPCOps(node.services, node.smm), users=users,
        session_secret=rpc_session_secret(cfg.node.identity_entropy),
        shard_role="worker",
    )

    stop = threading.Event()

    def control_loop() -> None:
        consumer = broker.create_consumer(control_queue(index))
        while not stop.is_set():
            msg = consumer.receive(timeout=0.5)
            if msg is None:
                continue
            try:
                record = deserialize(msg.payload)
                if record.get("kind") == "peer":
                    node.register_peer(
                        record["party"], record.get("services", ())
                    )
                elif record.get("kind") == "stop":
                    stop.set()
            except Exception:
                pass
            finally:
                try:
                    consumer.ack(msg)
                except Exception:
                    pass

    control = threading.Thread(
        target=control_loop, name=f"shard-control-w{index}", daemon=True
    )
    control.start()
    node.start()
    if getattr(node, "ops_server", None) is not None:
        atomicfile.write_atomic(
            os.path.join(base, f"worker{index}.ops_port"),
            str(node.ops_server.port),
        )
    print(f"worker ready: {cfg.node.my_legal_name} w{index}/{n_workers}",
          flush=True)

    import signal

    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    exit_on_orphan = os.environ.get("CORDA_TPU_EXIT_ON_ORPHAN") == "1"
    parent = os.getppid()
    try:
        while not stop.wait(0.5):
            if exit_on_orphan and os.getppid() != parent:
                break
    finally:
        rpc.stop()
        node.stop()
        try:
            broker.close()
        except Exception:
            pass
    return 0

"""StateMachineManager: flow scheduling, checkpointing, session management.

Reference: `node/.../services/statemachine/StateMachineManager.kt` (590 LoC)
+ `FlowStateMachineImpl.kt`.  The Quasar fiber model (serialize the actual
call stack on every suspend) is replaced by **deterministic replay**: a
checkpoint is (flow class, constructor args, ordered log of IO results,
session states).  Restore re-runs the flow generator from the top, feeding
recorded results for already-completed suspensions — sends are suppressed
during replay and the session sequence counters persisted in the checkpoint
make post-restore re-sends idempotent (receivers drop already-seen seqs).
This gives the same exactly-once-ish semantics as the reference's
checkpoint + message-dedup machinery with zero bytecode instrumentation.

Sessions are keyed by (counterparty, initiating flow class) exactly like the
reference's `openSessions` map keyed on (Party, sessionFlow)
(`FlowStateMachineImpl.kt` getSession), so an @initiating_flow sub-flow
opens its own session while plain sub-flows share their parent's.
"""
from __future__ import annotations

import logging
import threading
import uuid
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core.flows.api import (
    AwaitBlocking,
    FlowException,
    FlowKilledException,
    FlowLogic,
    Receive,
    RecordValue,
    Send,
    SendAndReceive,
    WaitForLedgerCommit,
    _as_generator,
    encode_flow_exception,
    flow_registry,
    get_initiated_by,
    rebuild_flow_exception,
)
from ..core.identity import Party
from ..core.serialization.codec import deserialize, serialize
from ..utils import eventlog, lockorder, tracing
from ..utils.metrics import MetricRegistry
from . import recovery
from .session import (
    ROUTE_HINT_HEADER,
    SESSION_TOPIC,
    FlowSession,
    SessionConfirm,
    SessionData,
    SessionEnd,
    SessionInit,
    SessionReject,
    SessionState,
    route_hint,
)


class FlowSessionException(FlowException):
    """The counterparty session ended or rejected while we needed data."""


@dataclass
class FlowHandle:
    flow_id: str
    result: Future


class _Suspended(Exception):
    """Internal marker: the fiber parked; unwind out of the advance loop."""


class FlowStateMachine:
    """One running (or restored) flow."""

    def __init__(
        self,
        flow_id: str,
        flow: FlowLogic,
        smm: "StateMachineManager",
        args: Tuple = (),
        kwargs: Optional[dict] = None,
        is_responder: bool = False,
        io_log: Optional[List[bytes]] = None,
        sessions: Optional[Dict[str, FlowSession]] = None,
        session_keys: Optional[Dict[str, str]] = None,
        session_owner_flows: Optional[Dict[str, str]] = None,
    ):
        self.flow_id = flow_id
        self.flow = flow
        self.smm = smm
        self.args = args
        self.kwargs = kwargs or {}
        self.is_responder = is_responder
        self.result: Future = Future()
        # replay state: everything before _replay_limit is history to feed
        # back; entries appended after construction are live recordings.
        self.io_log: List[bytes] = io_log or []
        self.replay_pos = 0
        self._replay_limit = len(self.io_log)
        # sessions
        self.sessions: Dict[str, FlowSession] = sessions or {}
        self.session_keys: Dict[str, str] = session_keys or {}  # key -> local_id
        self.session_owner_flows: Dict[str, str] = session_owner_flows or {}
        # parking
        self.waiting_session: Optional[str] = None
        self.waiting_expected_type: type = object
        self.waiting_tx: Optional[Any] = None
        self.waiting_blocking = False  # parked on an await_blocking
        self.done = False
        self._gen = None
        # per-flow structured logger (reference: logger named
        # `net.corda.flow.$id`, FlowStateMachineImpl.kt:77)
        self.logger = logging.getLogger(f"corda_tpu.flow.{flow_id}")
        self._session_counter = len(self.sessions)
        # sub_flow instance ordinals: reset at construction so replay hands
        # out the same sequence (sub_flow calls re-execute in order).
        self._subflow_counter = 0
        # incremental-checkpoint bookkeeping. Starts at zero even for
        # restored flows: the first incremental write backfills header +
        # every io entry and supersedes any legacy full-blob row, so a
        # flow that checkpointed under dev mode (or an older build) can
        # never resurrect stale state after a mode flip.
        self._cp_header_written = False
        self._cp_io_written = 0
        # sendAndReceiveWithRetry state: session local_id -> retry record
        # (in-memory only; a flow restored from a checkpoint loses pending
        # retries and surfaces the peer error instead — safe, just louder)
        self._failover_retries: Dict[str, dict] = {}
        # tracing spine: one root-or-child span for the whole flow run
        # (created in start(); parented on whatever context is current —
        # the RPC span for started flows, the delivering P2P span for
        # responders) plus a child span per park/suspend window
        self._span = None
        self._wait_span = None
        # Serializes generator stepping + park/deliver decisions between
        # the messaging pump and the blocking executor (await_blocking
        # resumes on an executor thread; an unlocked check-then-park
        # against deliver_data loses wakeups). RLock: deliveries cascade
        # into _run on the same thread.
        self._step_lock = lockorder.make_rlock("FlowStateMachine._step_lock")

    def next_subflow_ordinal(self) -> int:
        self._subflow_counter += 1
        return self._subflow_counter

    # -- service access used by FlowLogic -----------------------------------

    @property
    def service_hub(self):
        return self.smm.service_hub

    @property
    def our_identity(self) -> Party:
        return self.smm.our_identity

    @property
    def replaying(self) -> bool:
        return self.replay_pos < self._replay_limit

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self.flow.state_machine = self
        self._span = self.smm.tracer.start_span(
            f"flow.{self.flow.flow_name()}",
            parent=tracing.current_context(),
            flow_id=self.flow_id,
            node=self.smm.our_identity.name,
            responder=self.is_responder,
        )
        # flight recorder: the start event carries the flow's own trace
        # context (activated explicitly — _run establishes it only for
        # the generator steps)
        with tracing.activate(self._trace_ctx):
            eventlog.emit(
                "info", "statemachine", "flow started",
                flow=self.flow.flow_name(), flow_id=self.flow_id,
                node=self.smm.our_identity.name,
                responder=self.is_responder,
            )
        self._gen = _as_generator(self.flow)
        self._run(feed=None, first=True)

    # -- tracing helpers ----------------------------------------------------

    @property
    def _trace_ctx(self):
        return self._span.context if self._span is not None else None

    def _park_span(self, kind: str, **tags) -> None:
        """Open a child span covering the upcoming park window (finished
        by _unpark_span when the flow resumes or dies parked)."""
        if self._span is not None and self._wait_span is None:
            self._wait_span = self.smm.tracer.start_span(
                "flow.suspend", parent=self._span.context, kind=kind, **tags
            )

    def _unpark_span(self) -> None:
        ws, self._wait_span = self._wait_span, None
        if ws is not None:
            ws.finish()

    def _run(self, feed=None, first=False, throw: Optional[BaseException] = None):
        """Drive the generator until it completes or parks. Holds the
        step lock for the whole step so a concurrent delivery (pump
        thread) cannot interleave with a check-then-park (executor
        thread). The flow's trace context is current for the step, so
        every send/submit/commit a step performs joins the flow's trace."""
        from ..utils.flowcontext import running_flow

        with self._step_lock:
            with running_flow(self.flow_id, trace=self._trace_ctx):
                self._run_inner(feed, first, throw)

    def _run_inner(self, feed, first, throw) -> None:
        try:
            while True:
                try:
                    if throw is not None:
                        exc, throw = throw, None
                        req = self._gen.throw(exc)
                    elif first:
                        first = False
                        req = self._gen.send(None)
                    else:
                        req = self._gen.send(feed)
                        feed = None
                except StopIteration as stop:
                    self._complete(stop.value)
                    return
                except _Suspended:
                    raise
                except BaseException as exc:
                    self._fail(exc)
                    return
                try:
                    feed = self._handle_request(req)
                except _Suspended:
                    raise
                except BaseException as exc:
                    # IO-layer errors (ended session, bad type, non-initiating
                    # flow) are thrown INTO the generator so user flows can
                    # catch them like the reference's FlowException model.
                    throw = exc
        except _Suspended:
            return

    def _handle_request(self, req):
        if isinstance(req, Send):
            self._io_send(req.party, req.payload, req.owner_name)
            return None
        if isinstance(req, SendAndReceive):
            if not self.replaying:
                self._io_send(req.party, req.payload, req.owner_name)
                if req.retry_on_failover:
                    # sendAndReceiveWithRetry (FlowLogic.kt:98-110): if the
                    # peer service dies before answering, re-initiate and
                    # resend instead of failing the flow — the client-side
                    # failover notary clusters rely on
                    sid = self.session_keys.get(
                        self._session_key(req.party, req.owner_name)
                    )
                    if sid is not None:
                        self._failover_retries[sid] = {
                            "party": req.party,
                            "payload": serialize(req.payload),
                            "owner": req.owner_name,
                            "attempts": 3,
                        }
            return self._io_receive(req.party, req.expected_type, req.owner_name)
        if isinstance(req, Receive):
            # An initiating receive must still open the session.
            if not self.replaying:
                self._session_for(req.party, req.owner_name, first_payload=None)
            return self._io_receive(req.party, req.expected_type, req.owner_name)
        if isinstance(req, WaitForLedgerCommit):
            return self._io_wait_ledger(req.tx_id)
        if isinstance(req, RecordValue):
            return self._io_record(req)
        if isinstance(req, AwaitBlocking):
            return self._io_await_blocking(req)
        raise TypeError(f"flow yielded a non-FlowIORequest: {req!r}")

    def _io_await_blocking(self, req: AwaitBlocking):
        if self.replaying:
            blob = self.io_log[self.replay_pos]
            self.replay_pos += 1
            return deserialize(blob)
        executor = self.smm._blocking_executor
        if not self.smm.dispatches_blocking_off_pump:
            # deterministic in-memory network: run inline (tests pump
            # synchronously; blocking the pump is harmless in-process)
            value = req.compute()
            self.io_log.append(serialize(value))
            self._checkpoint()
            return value

        from ..utils.flowcontext import running_flow

        ctx = self._trace_ctx

        def work():
            # executor thread: re-establish the flow's identity + trace
            # context so the blocking body (notary commits, batcher
            # waits) attributes to this flow's trace
            with running_flow(self.flow_id, trace=ctx):
                try:
                    value = req.compute()
                except BaseException as exc:
                    self.smm._resume_from_blocking(self, error=exc)
                else:
                    self.smm._resume_from_blocking(self, value=value)

        self.waiting_blocking = True
        self._park_span("blocking")
        self._checkpoint()
        try:
            executor.submit(work)
        except RuntimeError:
            # node stopping: leave the flow parked; the checkpoint
            # restores and re-executes the computation after restart
            pass
        raise _Suspended()

    def _io_record(self, req: RecordValue):
        if self.replaying:
            blob = self.io_log[self.replay_pos]
            self.replay_pos += 1
            return deserialize(blob)
        value = req.compute()
        self.io_log.append(serialize(value))
        self._checkpoint()
        return value

    # -- IO implementation --------------------------------------------------

    def _session_key(self, party: Party, owner_name: str) -> str:
        return f"{party.name}|{owner_name}"

    def _session_for(
        self, party: Party, owner_name: str, first_payload: Optional[bytes],
        create: bool = True,
    ) -> FlowSession:
        key = self._session_key(party, owner_name)
        local_id = self.session_keys.get(key)
        if local_id is not None:
            sess = self.sessions[local_id]
            drained = sess.recv_seq not in sess.inbox
            dead = sess.state is SessionState.ENDED or (
                sess.ended_by_peer and drained
            )
            if not dead:
                return sess
            if sess.end_error:
                # The peer errored; reusing the channel is a flow error the
                # author can catch, not a silent new exchange.
                raise self._peer_end_exception(sess)
            # Clean end: the previous exchange with this (party, flow class)
            # completed. Retire the key so a NEW sub_flow instance opens a
            # fresh session (reference keys sessions per sub-flow instance).
            del self.session_keys[key]
        if not create:
            raise FlowSessionException(f"no session with {party.name}")
        registered_name = owner_name.split("#", 1)[0]
        flow_cls = flow_registry.get(registered_name)
        if flow_cls is None or not getattr(flow_cls, "_initiating", False):
            raise FlowException(
                f"{registered_name} is not an @initiating_flow but tried to "
                f"open a session with {party.name}"
            )
        local_id = f"{self.flow_id}:{self._session_counter}"
        self._session_counter += 1
        sess = FlowSession(
            local_id=local_id, peer=party, state=SessionState.INITIATING,
        )
        if first_payload is not None:
            sess.send_seq = 1  # payload rides the init as seq 0
            sess.init_payload = first_payload
        self.sessions[local_id] = sess
        self.session_keys[key] = local_id
        self.session_owner_flows[local_id] = owner_name
        self.smm._register_session(local_id, self)
        self.smm._send_session_message(
            party,
            SessionInit(
                initiator_session_id=local_id,
                flow_name=registered_name,
                flow_version=getattr(flow_cls, "_flow_version", 1),
                first_payload=first_payload,
            ),
        )
        return sess

    def _io_send(self, party: Party, payload: Any, owner_name: str) -> None:
        if self.replaying:
            return  # already sent before the checkpoint we restored from
        blob = serialize(payload)
        key = self._session_key(party, owner_name)
        before = self.session_keys.get(key)
        sess = self._session_for(party, owner_name, first_payload=blob)
        if self.session_keys.get(key) != before:
            return  # fresh session: the payload rode the SessionInit
        if sess.state is SessionState.INITIATING:
            sess.outbox.append(blob)
            sess.send_seq += 1
        elif sess.state is SessionState.INITIATED:
            self.smm._send_session_message(
                party, SessionData(sess.peer_id, sess.send_seq, blob)
            )
            sess.send_seq += 1
        else:
            raise FlowSessionException(
                f"session with {party.name} has ended"
                + (f": {sess.end_error}" if sess.end_error else "")
            )

    def _io_receive(self, party: Party, expected_type: type, owner_name: str):
        if self.replaying:
            blob = self.io_log[self.replay_pos]
            self.replay_pos += 1
            return deserialize(blob)
        sess = self._session_for(party, owner_name, first_payload=None)
        if sess.recv_seq in sess.inbox:
            blob = sess.inbox.pop(sess.recv_seq)
            sess.recv_seq += 1
            self._failover_retries.pop(sess.local_id, None)
            value = deserialize(blob)
            self._check_type(value, expected_type, party)
            self.io_log.append(blob)
            self._checkpoint()
            return value
        if sess.ended_by_peer:
            raise self._peer_end_exception(sess)
        # park
        self.waiting_session = sess.local_id
        self.waiting_expected_type = expected_type
        self._park_span("receive", peer=party.name)
        self._checkpoint()
        raise _Suspended()

    def _io_wait_ledger(self, tx_id):
        if self.replaying:
            blob = self.io_log[self.replay_pos]
            self.replay_pos += 1
            return deserialize(blob)
        stx = self.smm.service_hub.validated_transactions.get(tx_id)
        if stx is not None:
            blob = serialize(stx)
            self.io_log.append(blob)
            self._checkpoint()
            return stx
        self.waiting_tx = tx_id
        self.smm._register_ledger_waiter(tx_id, self)
        self._park_span("ledger_commit")
        self._checkpoint()
        raise _Suspended()

    def _check_type(self, value, expected_type: type, party: Party) -> None:
        if expected_type is not object and not isinstance(value, expected_type):
            raise FlowException(
                f"received {type(value).__name__} from {party.name}, "
                f"expected {expected_type.__name__}"
            )

    # -- resume paths (called by SMM) ---------------------------------------

    def deliver_data(self, sess: FlowSession) -> None:
        """Called when new data arrived for a session; resumes if parked on it."""
        with self._step_lock:
            self._deliver_data_locked(sess)

    def _deliver_data_locked(self, sess: FlowSession) -> None:
        if self.done or self.waiting_session != sess.local_id:
            return
        if sess.recv_seq not in sess.inbox:
            return
        blob = sess.inbox.pop(sess.recv_seq)
        sess.recv_seq += 1
        self.waiting_session = None
        self._unpark_span()
        # reply arrived: a later session end must not replay the request
        self._failover_retries.pop(sess.local_id, None)
        try:
            value = deserialize(blob)
            self._check_type(value, self.waiting_expected_type, sess.peer)
        except BaseException as exc:
            self._run(throw=exc)
            return
        self.io_log.append(blob)
        self._checkpoint()
        self._run(feed=value)

    def deliver_session_end(self, sess: FlowSession) -> None:
        with self._step_lock:
            self._deliver_session_end_locked(sess)

    def _deliver_session_end_locked(self, sess: FlowSession) -> None:
        if self.done or self.waiting_session != sess.local_id:
            return
        # If buffered data can still satisfy the receive, let it.
        if sess.recv_seq in sess.inbox:
            self._deliver_data_locked(sess)
            return
        retry = self._failover_retries.pop(sess.local_id, None)
        if retry is not None and retry["attempts"] > 0:
            # retry-marked request: the counter-service died before
            # answering — open a FRESH session resending the SAME payload
            # (notary requests are idempotent per tx, so a commit that
            # landed before the crash simply re-acks) and stay parked.
            retry["attempts"] -= 1
            self.logger.warning(
                "session with %s ended before reply (%s); failover retry "
                "(%d attempts left)",
                sess.peer.name, sess.end_error, retry["attempts"],
            )
            key = self._session_key(retry["party"], retry["owner"])
            if self.session_keys.get(key) == sess.local_id:
                del self.session_keys[key]
            new_sess = self._session_for(
                retry["party"], retry["owner"],
                first_payload=retry["payload"],
            )
            self._failover_retries[new_sess.local_id] = retry
            self.waiting_session = new_sess.local_id
            self._checkpoint()
            return
        self.waiting_session = None
        self._unpark_span()
        self._run(throw=self._peer_end_exception(sess))

    def _peer_end_exception(self, sess: FlowSession) -> FlowException:
        """A propagated FlowException is rethrown as its original type; a
        clean-but-premature end becomes a FlowSessionException."""
        if sess.end_error and "|" in sess.end_error:
            return rebuild_flow_exception(sess.end_error)
        return FlowSessionException(
            f"session with {sess.peer.name} ended before data arrived"
            + (f": {sess.end_error}" if sess.end_error else "")
        )

    def deliver_ledger_commit(self, stx) -> None:
        with self._step_lock:
            self._deliver_ledger_commit_locked(stx)

    def _deliver_ledger_commit_locked(self, stx) -> None:
        if self.done or self.waiting_tx is None:
            return
        self.waiting_tx = None
        self._unpark_span()
        blob = serialize(stx)
        self.io_log.append(blob)
        self._checkpoint()
        self._run(feed=stx)

    # -- completion ---------------------------------------------------------

    def _end_sessions(self, error: Optional[str]) -> None:
        for sess in self.sessions.values():
            if sess.state is SessionState.INITIATED and not sess.ended_by_peer:
                self.smm._send_session_message(
                    sess.peer, SessionEnd(sess.peer_id, error)
                )
            sess.state = SessionState.ENDED

    def _complete(self, value) -> None:
        self.done = True
        self.logger.info(
            "flow %s completed", self.flow.flow_name(),
        )
        eventlog.emit(
            "info", "statemachine", "flow completed",
            flow=self.flow.flow_name(), flow_id=self.flow_id,
            node=self.smm.our_identity.name,
        )
        self._unpark_span()
        if self._span is not None:
            self._span.finish()
        self._end_sessions(None)
        self.smm._flow_finished(self)
        # the future may already be failed by a racing kill of a
        # hospital-readmitted flow (same preserved future) — a done
        # future must win, not raise InvalidStateError into the runner
        if not self.result.done():
            self.result.set_result(value)

    def _fail(self, exc: BaseException) -> None:
        # flow hospital triage first: a transient failure is re-admitted
        # (checkpoint replayed after backoff, the caller's future kept
        # pending) instead of failing
        hospital = getattr(self.smm, "hospital", None)
        if hospital is not None and hospital.consider(self, exc) is not None:
            self._hospitalize(exc)
            return
        self.done = True
        self.logger.warning(
            "flow %s failed: %s", self.flow.flow_name(), exc,
        )
        eventlog.emit(
            "warning", "statemachine", "flow failed",
            flow=self.flow.flow_name(), flow_id=self.flow_id,
            node=self.smm.our_identity.name,
            error=f"{type(exc).__name__}: {exc}",
        )
        self._unpark_span()
        if self._span is not None:
            self._span.finish(error=exc)
        # Only FlowExceptions propagate their type+message to peers (reference
        # FlowException model); anything else is an opaque counter-flow error.
        msg = (
            encode_flow_exception(exc)
            if isinstance(exc, FlowException)
            else "counter-flow error"
        )
        self._end_sessions(msg)
        if hospital is not None:
            # ward BEFORE _flow_finished drops the checkpoint, so the
            # blob is still readable for retry_flow()
            hospital.record_fatal(self, exc)
        self.smm._flow_finished(self)
        if not self.result.done():  # see _complete: a racing kill wins
            self.result.set_exception(exc)

    def _hospitalize(self, exc: BaseException) -> None:
        """Transient failure: this attempt's machine is retired (done,
        span closed) but sessions stay open, the checkpoint stays
        written, and the result future stays pending — the hospital's
        readmission timer replays a fresh machine under the same flow id
        and the same future."""
        self.done = True
        self.logger.warning(
            "flow %s hospitalized after transient failure: %s",
            self.flow.flow_name(), exc,
        )
        self._unpark_span()
        if self._span is not None:
            self._span.finish(error=exc)

    # -- checkpointing ------------------------------------------------------

    def _sessions_state(self) -> dict:
        return {
            "sessions": [s.to_dict() for s in self.sessions.values()],
            "session_keys": dict(self.session_keys),
            "session_owner_flows": dict(self.session_owner_flows),
        }

    def _checkpoint(self) -> None:
        storage = self.smm.checkpoint_storage
        if self.smm.dev_checkpoint_check or not hasattr(
            storage, "put_incremental"
        ):
            # dev mode re-validates the FULL blob each write, so build it;
            # re-serializing everything per step is O(steps^2) — fine for
            # tests, disabled on the production throughput path
            blob = serialize(
                {
                    "flow_id": self.flow_id,
                    "flow_name": self.flow.flow_name(),
                    "args": list(self.args),
                    "kwargs": dict(self.kwargs),
                    "is_responder": self.is_responder,
                    "io_log": list(self.io_log),
                    **self._sessions_state(),
                }
            )
            storage.put(self.flow_id, blob)
            if self.smm.dev_checkpoint_check:
                self.smm._check_checkpoint_restorable(self.flow_id, blob)
        else:
            header = None
            if not self._cp_header_written:
                header = serialize(
                    {
                        "flow_id": self.flow_id,
                        "flow_name": self.flow.flow_name(),
                        "args": list(self.args),
                        "kwargs": dict(self.kwargs),
                        "is_responder": self.is_responder,
                    }
                )
            new_io = [
                (i, self.io_log[i])
                for i in range(self._cp_io_written, len(self.io_log))
            ]
            storage.put_incremental(
                self.flow_id, header, new_io, serialize(self._sessions_state())
            )
            # bookkeeping only advances on SUCCESS: a failed write must
            # leave the header/io entries queued for the next checkpoint
            # (the old full-blob path self-healed the same way)
            self._cp_header_written = True
            self._cp_io_written = len(self.io_log)
        self.smm.checkpoints_written += 1
        self.smm.metrics.meter("Flows.CheckpointingRate").mark()
        if self._span is not None:
            # point-in-time trail on the flow's root span (bounded)
            self._span.add_event("checkpoint", io=len(self.io_log))


class StateMachineManager:
    """Flow scheduler: starts flows, restores them from checkpoints, routes
    session messages (reference `StateMachineManager.kt`)."""

    def __init__(self, service_hub, messaging, checkpoint_storage, our_identity: Party,
                 dev_checkpoint_check: bool = True):
        """dev_checkpoint_check: validate every written checkpoint is
        restorable — deserializable, flow class registered, constructor
        args intact — surfacing unrestorable flows at WRITE time instead
        of at the restart that needs them (reference dev-mode checkpoint
        deserialization checker, StateMachineManager.kt:114-115).
        Synchronous and cheap (one decode); disable for max throughput."""
        self.service_hub = service_hub
        self.messaging = messaging
        self.checkpoint_storage = checkpoint_storage
        self.our_identity = our_identity
        self.dev_checkpoint_check = dev_checkpoint_check
        self.flows: Dict[str, FlowStateMachine] = {}
        self._sessions: Dict[str, FlowStateMachine] = {}  # local session id -> fsm
        self._initiated_dedup: Dict[Tuple[str, str], str] = {}  # (peer, init_id) -> local id
        self._ledger_waiters: Dict[Any, List[FlowStateMachine]] = {}
        # Executor for FlowLogic.await_blocking computations (cluster
        # notary commits etc.): a flow body blocking minutes on the P2P
        # pump thread starves the very messages it waits for (observed as
        # a 30 s Raft-commit livelock on OS-process notary members). The
        # deterministic in-memory network (no ASYNC_FLOW_DISPATCH attr)
        # runs these computations inline so tests stay pump-synchronous.
        self._blocking_executor = None
        if getattr(messaging, "ASYNC_FLOW_DISPATCH", False):
            import os as _os
            from concurrent.futures import ThreadPoolExecutor

            # env-tunable: these threads mostly BLOCK (cluster commits,
            # batcher futures) so they are cheap, but the count also
            # bounds how many concurrent commits the notary's coalescing
            # uniqueness layer can fold into one consensus round
            self._blocking_executor = ThreadPoolExecutor(
                max_workers=int(
                    _os.environ.get("CORDA_TPU_FLOW_BLOCKING_THREADS", 4)
                ),
                thread_name_prefix="flow-blocking",
            )
        self.checkpoints_written = 0
        # Key metric names mirror the reference (StateMachineManager.kt:127-133)
        self.metrics = (
            getattr(getattr(service_hub, "monitoring", None), "metrics", None)
            or MetricRegistry()
        )
        self.metrics.gauge("Flows.InFlight", lambda: self.in_flight_count)
        self._changes: List[Callable] = []  # observers: fn(event, fsm)
        # Node-local responder registrations override the global registry
        # (reference: registerInitiatedFlows is per-node, AbstractNode.kt:291)
        self._initiated_overrides: Dict[str, type] = {}
        # failure triage: transient-failure auto-retry + dead-letter ward
        from .hospital import FlowHospital

        self.hospital = FlowHospital(self)
        # overload protection: AbstractNode installs an AdmissionController
        # here when admission is configured; None = every start admitted.
        # The gate covers NEW top-level flows only — responders, hospital
        # readmissions (_restore) and checkpoint restores are priority
        # traffic and enter below this seam. _start_gate makes the
        # cap-check + flows-registration atomic: two RPC pool threads
        # racing start_flow must not both pass a max_flows-1 reading.
        self.admission = None
        self._start_gate = lockorder.make_lock(
            "StateMachineManager._start_gate"
        )
        # Multi-process sharding (node/shardhost.py): workers set a tag
        # ("w0", "w1", …) that prefixes every flow id — and therefore
        # every session id ("<flow id>:<n>") — so the supervisor's
        # router can pin a session's messages to the worker that owns
        # the flow without any shared session table. checkpoint_filter
        # partitions restore the same way: a respawned worker must
        # resume ITS flows from the shared db, never its siblings' live
        # ones. Both default to the single-process behaviour.
        self.flow_id_tag = ""
        self.checkpoint_filter: Optional[Callable[[str], bool]] = None
        messaging.add_handler(SESSION_TOPIC, self._on_session_message)

    def _new_flow_id(self) -> str:
        fid = str(uuid.uuid4())
        return f"{self.flow_id_tag}-{fid}" if self.flow_id_tag else fid

    # -- public API ---------------------------------------------------------

    def start_flow(self, flow: FlowLogic, *args_for_restore, **kw) -> FlowHandle:
        """Run a new top-level flow.  For checkpoint-restorability pass the
        flow's constructor args via args_for_restore (they must be
        codec-serializable); flows started without them still run but
        restore will fail loudly.

        Raises NodeOverloadedError (with a retry_after_ms hint) when an
        installed AdmissionController sheds the start — system flows
        (`_system_flow = True` classes) are priority and never shed."""
        flow_id = self._new_flow_id()
        fsm = FlowStateMachine(
            flow_id, flow, self, args=tuple(args_for_restore), kwargs=kw
        )
        with self._start_gate:
            # admit + register atomically: the live-flow cap reads
            # in_flight_count, so the admitted flow must be visible
            # before the next admission decision runs
            if self.admission is not None:
                self.admission.admit(flow=flow)
            self.flows[flow_id] = fsm
        self._notify("started", fsm)
        fsm.start()
        return FlowHandle(flow_id, fsm.result)

    def start(self) -> None:
        """Restore checkpointed flows and resume them (reference
        restoreFibersFromCheckpoints, `StateMachineManager.kt:227-241`).
        With a checkpoint_filter (shardhost workers over a shared db)
        only this manager's own partition restores."""
        for flow_id, blob in self.checkpoint_storage.all_checkpoints():
            if (
                self.checkpoint_filter is not None
                and not self.checkpoint_filter(flow_id)
            ):
                continue
            try:
                self._restore(flow_id, blob)
            except Exception as exc:
                # ONE unrestorable checkpoint (torn write the CRC frame
                # could not catch, flow class gone after an upgrade) must
                # not wedge the whole node out of serving: park it and
                # keep restoring the rest (node/recovery.py contract)
                park = getattr(self.checkpoint_storage, "_quarantine", None)
                if park is not None:
                    # moves the blob into cp_quarantine (keeps evidence)
                    # and already counts + eventlogs the quarantine
                    park(flow_id, "restore", blob,
                         f"{type(exc).__name__}: {exc}")
                else:
                    recovery.quarantine_record(
                        "checkpoints", f"restore:{flow_id}",
                        f"{type(exc).__name__}: {exc}",
                    )
                    remove = getattr(self.checkpoint_storage, "remove", None)
                    if remove is not None:
                        remove(flow_id)

    @property
    def in_flight_count(self) -> int:
        return sum(1 for f in self.flows.values() if not f.done)

    @property
    def tracer(self) -> tracing.Tracer:
        """The tracing spine's span sink: the process tracer (per node in
        OS-process deployments; shared across MockNetwork's in-process
        nodes so cross-node traces assemble). Resolved dynamically so
        tests installing a fresh tracer take effect immediately."""
        return tracing.get_tracer()

    @property
    def dispatches_blocking_off_pump(self) -> bool:
        """Whether await_blocking computations run on an executor thread
        (real async messaging) instead of inline on the pump
        (deterministic in-memory networks). The single source of truth
        for callers that adapt to the dispatch mode — e.g. the notary
        flushes the signature batcher before blocking when inline,
        because nothing else can feed the batch while the pump waits."""
        return self._blocking_executor is not None

    def track(self, observer: Callable) -> None:
        """observer(event: str, fsm) on started/finished."""
        self._changes.append(observer)

    def _check_checkpoint_restorable(self, flow_id: str, blob: bytes) -> None:
        """Surface restore problems at checkpoint-WRITE time: a
        non-deserializable checkpoint is a hard error (the bytes are
        garbage); an unregistered flow class is a loud warning (starting
        unregistered flows is allowed — they run, but a restart could not
        restore them; register via the node's cordapps config)."""
        try:
            state = deserialize(blob)
        except Exception as exc:
            raise FlowException(
                f"checkpoint for {flow_id} is not restorable: {exc}"
            ) from exc
        if flow_registry.get(state["flow_name"]) is None:
            logging.getLogger(f"corda_tpu.flow.{flow_id}").warning(
                "flow %s is not in the flow registry — a restart could "
                "not restore this checkpoint",
                state["flow_name"],
            )

    def kill_flow(self, flow_id: str) -> bool:
        """Forcibly fail a live flow (reference CordaRPCOps.killFlow):
        peers get a SessionEnd carrying the error, the checkpoint is
        dropped, and the caller's future raises FlowKilledException.
        Also reaches flows the hospital holds: a scheduled retry is
        cancelled (the preserved future raises), a ward record is
        discharged."""
        fsm = self.flows.get(flow_id)
        if fsm is not None and not fsm.done:
            fsm._fail(FlowKilledException(f"flow {flow_id} killed via RPC"))
            return True
        return self.hospital.kill(flow_id)

    def register_initiated_flow(self, initiator_cls, responder_cls) -> None:
        """Node-local responder for an initiating flow (overrides the global
        @initiated_by registration for this node only)."""
        self._initiated_overrides[initiator_cls.flow_name()] = responder_cls

    # -- restore ------------------------------------------------------------

    def _restore(self, flow_id: str, blob: bytes,
                 result_future: Optional[Future] = None,
                 merge_inbox_from: Optional[FlowStateMachine] = None) -> None:
        """`result_future`: reuse an existing Future as the restored
        flow's result (hospital readmission — the original caller keeps
        its handle). `merge_inbox_from`: a retired machine for the same
        flow whose sessions may have received data AFTER the checkpoint
        was written; that data lives only on the old session objects, so
        it is merged into the restored ones (the peer will not re-send)."""
        state = deserialize(blob)
        flow_cls = flow_registry.get(state["flow_name"])
        if flow_cls is None:
            raise FlowException(
                f"checkpoint for unknown flow {state['flow_name']}"
            )
        flow = flow_cls(*state["args"], **state["kwargs"])
        sessions = {
            d["local_id"]: FlowSession.from_dict(d) for d in state["sessions"]
        }
        fsm = FlowStateMachine(
            flow_id, flow, self,
            args=tuple(state["args"]), kwargs=state["kwargs"],
            is_responder=state["is_responder"],
            io_log=list(state["io_log"]),
            sessions=sessions,
            session_keys=dict(state["session_keys"]),
            session_owner_flows=dict(state["session_owner_flows"]),
        )
        if result_future is not None:
            fsm.result = result_future
        self.flows[flow_id] = fsm
        for local_id, sess in sessions.items():
            self._register_session(local_id, fsm)
            if merge_inbox_from is not None:
                # AFTER re-pointing the route: anything the pump wrote to
                # the retired machine's session up to this instant is
                # caught here, and anything later lands on the new one
                # directly (list() snapshot: the pump may still be
                # appending to the old inbox mid-copy)
                old = merge_inbox_from.sessions.get(local_id)
                if old is not None:
                    for seq, payload in list(old.inbox.items()):
                        if seq >= sess.recv_seq:
                            sess.inbox.setdefault(seq, payload)
                    if old.ended_by_peer:
                        sess.ended_by_peer = True
                        sess.end_error = old.end_error
            if sess.is_initiated_side and sess.peer_id is not None:
                # Rebuild init-dedup so a re-delivered SessionInit does not
                # spawn a duplicate responder after restart.
                self._initiated_dedup[(sess.peer.name, sess.peer_id)] = local_id
            if sess.state is SessionState.INITIATING:
                # Re-announce: the pre-crash init may have been lost.  The
                # responder dedups by initiator session id; the init payload
                # (seq 0) rides again from its persisted copy.
                owner = fsm.session_owner_flows[local_id].split("#", 1)[0]
                owner_cls = flow_registry.get(owner)
                init = SessionInit(
                    initiator_session_id=local_id,
                    flow_name=owner,
                    flow_version=getattr(owner_cls, "_flow_version", 1),
                    first_payload=sess.init_payload,
                )
                self.messaging.send(
                    sess.peer, SESSION_TOPIC, serialize(init),
                    headers={ROUTE_HINT_HEADER: route_hint(init)},
                )
        self._notify("restored", fsm)
        fsm.start()

    def _start_fresh_retry(self, flow_id: str, flow_cls, args, kwargs,
                           is_responder: bool, result_future: Future) -> None:
        """Hospital readmission of a flow that failed BEFORE its first
        checkpoint: rebuild it from its constructor args under the SAME
        flow id and result future and run it from the top."""
        flow = flow_cls(*args, **(kwargs or {}))
        fsm = FlowStateMachine(
            flow_id, flow, self, args=tuple(args), kwargs=dict(kwargs or {}),
            is_responder=is_responder,
        )
        fsm.result = result_future
        self.flows[flow_id] = fsm
        self._notify("restored", fsm)
        fsm.start()

    # -- session message routing --------------------------------------------

    def _on_session_message(self, sender: Party, payload: bytes) -> None:
        """Runs on the delivering transport thread: the p2p pump itself,
        or — with CORDA_TPU_FLOW_LANES > 0 — a flow-lane thread with
        per-flow affinity (node/flowlanes.py). Either way the broker
        acks a message only after its handler chain returns (the lane
        path defers the ack to completion), so processing completing
        here preserves at-least-once delivery — an ack-then-process
        hand-off would lose messages on crash. Long blocking work inside
        a flow goes through `FlowLogic.await_blocking`, which parks the
        flow and runs the work off-pump instead.

        Lane concurrency: messages of one session (and one flow) always
        land on one lane (affinity on the route hint's flow id), so the
        per-session mutations below stay ordered; cross-thread state
        against the flow's OWN steps (blocking executor, RPC threads) is
        serialized by each FSM's step lock, which the _on_* handlers
        take before touching session state."""
        msg = deserialize(payload)
        if isinstance(msg, SessionInit):
            self._on_init(sender, msg)
        elif isinstance(msg, SessionConfirm):
            self._on_confirm(sender, msg)
        elif isinstance(msg, SessionReject):
            self._on_reject(sender, msg)
        elif isinstance(msg, SessionData):
            self._on_data(sender, msg)
        elif isinstance(msg, SessionEnd):
            self._on_end(sender, msg)

    def _on_init(self, sender: Party, msg: SessionInit) -> None:
        dedup_key = (sender.name, msg.initiator_session_id)
        if dedup_key in self._initiated_dedup:
            local_id = self._initiated_dedup[dedup_key]
            self._send_session_message(
                sender, SessionConfirm(msg.initiator_session_id, local_id)
            )
            return
        responder_cls = self._initiated_overrides.get(
            msg.flow_name
        ) or get_initiated_by(msg.flow_name)
        if responder_cls is None:
            self._send_session_message(
                sender,
                SessionReject(
                    msg.initiator_session_id,
                    f"no flow registered to respond to {msg.flow_name}",
                ),
            )
            return
        flow = responder_cls(sender)
        # responder flows are PRIORITY traffic: they complete work a peer
        # already admitted (notary commits arrive exactly this way), so
        # admission counts them but can never shed them
        if self.admission is not None:
            self.admission.admit(flow=flow, is_responder=True)
        flow_id = self._new_flow_id()
        fsm = FlowStateMachine(
            flow_id, flow, self, args=(sender,), is_responder=True
        )
        local_id = f"{flow_id}:0"
        fsm._session_counter = 1
        sess = FlowSession(
            local_id=local_id, peer=sender, state=SessionState.INITIATED,
            peer_id=msg.initiator_session_id, is_initiated_side=True,
        )
        if msg.first_payload is not None:
            sess.inbox[0] = msg.first_payload
        fsm.sessions[local_id] = sess
        key = fsm._session_key(sender, flow.session_owner_name())
        fsm.session_keys[key] = local_id
        fsm.session_owner_flows[local_id] = flow.session_owner_name()
        self.flows[flow_id] = fsm
        self._register_session(local_id, fsm)
        self._initiated_dedup[dedup_key] = local_id
        self._send_session_message(
            sender, SessionConfirm(msg.initiator_session_id, local_id)
        )
        self._notify("started", fsm)
        fsm.start()

    def _on_confirm(self, sender: Party, msg: SessionConfirm) -> None:
        fsm = self._sessions.get(msg.initiator_session_id)
        if fsm is None:
            return
        # step lock: the confirm mutates session state and checkpoints,
        # racing the flow's own steps on the blocking executor (and, with
        # lanes, running off the single pump thread)
        with fsm._step_lock:
            sess = fsm.sessions.get(msg.initiator_session_id)
            if sess is None or sess.state is not SessionState.INITIATING:
                return  # duplicate confirm
            sess.state = SessionState.INITIATED
            sess.peer_id = msg.initiated_session_id
            # Flush sends buffered while the handshake was in flight.  seq 0
            # may have ridden the init itself (send_seq started at 1).
            start_seq = sess.send_seq - len(sess.outbox)
            for i, blob in enumerate(sess.outbox):
                self._send_session_message(
                    sess.peer, SessionData(sess.peer_id, start_seq + i, blob)
                )
            # Keep outbox[0] around only while INITIATING for init re-sends;
            # once confirmed, the data is delivered and the buffer can go.
            sess.outbox.clear()
            fsm._checkpoint()

    def _on_reject(self, sender: Party, msg: SessionReject) -> None:
        fsm = self._sessions.get(msg.initiator_session_id)
        if fsm is None:
            return
        with fsm._step_lock:
            sess = fsm.sessions.get(msg.initiator_session_id)
            if sess is None:
                return
            sess.state = SessionState.ENDED
            sess.ended_by_peer = True
            sess.end_error = msg.error
            fsm._deliver_session_end_locked(sess)

    def _on_data(self, sender: Party, msg: SessionData) -> None:
        fsm = self._sessions.get(msg.recipient_session_id)
        if fsm is None:
            return
        with fsm._step_lock:
            sess = fsm.sessions.get(msg.recipient_session_id)
            if sess is None:
                return
            if msg.seq < sess.recv_seq or msg.seq in sess.inbox:
                return  # duplicate (re-send after restore)
            sess.inbox[msg.seq] = msg.payload
            fsm._deliver_data_locked(sess)

    def _on_end(self, sender: Party, msg: SessionEnd) -> None:
        fsm = self._sessions.get(msg.recipient_session_id)
        if fsm is None:
            return
        with fsm._step_lock:
            sess = fsm.sessions.get(msg.recipient_session_id)
            if sess is None:
                return
            sess.ended_by_peer = True
            sess.end_error = msg.error
            if sess.recv_seq not in sess.inbox:
                sess.state = SessionState.ENDED
            fsm._deliver_session_end_locked(sess)

    # -- internals ----------------------------------------------------------

    def _resume_from_blocking(self, fsm: FlowStateMachine, value=None,
                              error=None) -> None:
        """Continuation for FlowLogic.await_blocking: runs on the blocking
        executor thread (not the pump); records the result for replay,
        then steps the flow."""
        with fsm._step_lock:
            if fsm.done or not fsm.waiting_blocking:
                return
            fsm.waiting_blocking = False
            fsm._unpark_span()
            if error is not None:
                fsm._run(throw=error)
                return
            fsm.io_log.append(serialize(value))
            fsm._checkpoint()
            fsm._run(feed=value)

    def _register_session(self, local_id: str, fsm: FlowStateMachine) -> None:
        self._sessions[local_id] = fsm

    def _register_ledger_waiter(self, tx_id, fsm: FlowStateMachine) -> None:
        self._ledger_waiters.setdefault(tx_id, []).append(fsm)

    def notify_transaction_committed(self, stx) -> None:
        """Called by the service hub after recordTransactions."""
        for fsm in self._ledger_waiters.pop(stx.id, []):
            fsm.deliver_ledger_commit(stx)

    def _send_session_message(self, party: Party, msg) -> None:
        # the route hint lets a sharded receiver's router pick the
        # worker from headers alone (no payload decode on its thread)
        hint = route_hint(msg)
        self.messaging.send(
            party, SESSION_TOPIC, serialize(msg),
            headers={ROUTE_HINT_HEADER: hint} if hint else None,
        )

    def _flow_finished(self, fsm: FlowStateMachine) -> None:
        self.checkpoint_storage.remove(fsm.flow_id)
        self.hospital.discharge(fsm.flow_id)
        self._notify("finished", fsm)

    def _notify(self, event: str, fsm: FlowStateMachine) -> None:
        if event == "started" or event == "restored":
            self.metrics.meter("Flows.Started").mark()
        elif event == "finished":
            self.metrics.meter("Flows.Finished").mark()
        audit = getattr(self.service_hub, "audit_service", None)
        if audit is not None:
            audit.record_event(
                self.our_identity.name, f"flow.{event}",
                flow_id=fsm.flow_id, flow=fsm.flow.flow_name(),
            )
        for obs in self._changes:
            obs(event, fsm)

"""corda_tpu.node: the node runtime (reference `node/`, 26.5k LoC Kotlin).

Services, state machine (flow scheduler + checkpoints), messaging, storage,
notaries.  The compute-heavy paths (signature batches) dispatch to
corda_tpu.ops / corda_tpu.parallel; everything here is orchestration.
"""

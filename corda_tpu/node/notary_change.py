"""Crash-safe cross-domain notary change: journal, crash seams, recovery.

The multi-domain federation (docs/robustness.md §6) re-pins a state from
domain A's notary to domain B's with a TWO-PHASE protocol that reuses the
sharded provider's `PrepareJournal` machinery (node/sharded_notary.py):

  1. journal `{phase: "prepare", stx}`  — durable intent, BEFORE any
     notary sees the tx, so recovery always knows what was in flight;
  2. CONSUME: notarise the NotaryChangeWireTransaction at the OLD notary
     (it alone governs the inputs) — durable in the old domain's log;
  3. flip the journal to `{phase: "assume", stx+old sigs}` — the
     decision record, written with the same raised-durability semantics
     the sharded journal uses for its "committing" flip;
  4. ASSUME: send the old-notary-signed tx to the NEW notary, which
     durably records the migrated refs in ITS commit log (gated on the
     old notary's signature — see NotaryServiceFlow._verify_notary_change);
  5. remove the journal entry.

Both notary commits are idempotent (re-committing the same refs for the
same tx id is success, not conflict), so a crash at ANY point recovers by
re-driving forward: `NotaryChangeRecoveryFlow` replays "prepare" entries
from step 2 and "assume" entries from step 4, landing every state with
exactly one owning notary — never torn, never doubly-spendable. The
four crash seams (`notary_change.before_prepare` / `.after_prepare` /
`.between_consume_and_assume` / `.after_commit`) ride the process fault
hook (utils/faultpoints.py) and honour the action "crash".
"""
from __future__ import annotations

from typing import List, Tuple

from ..core.flows.api import FlowLogic, initiating_flow
from ..core.flows.library import NotaryClientFlowRef
from ..utils import eventlog, faultpoints

#: journal table in the instigator's node database
JOURNAL_TABLE = "notary_change_journal"

#: the four injectable coordinator-crash seams, in protocol order —
#: registered as durability barriers of the change journal so the
#: crash-point explorer (tools/crashmc.py) enumerates the whole ladder
CRASH_POINTS = tuple(
    faultpoints.register_crash_point(p, "notary_change_journal")
    for p in (
        "notary_change.before_prepare",
        "notary_change.after_prepare",
        "notary_change.between_consume_and_assume",
        "notary_change.after_commit",
    )
)


class NotaryChangeCrashError(RuntimeError):
    """Injected coordinator crash (faultpoints action "crash"): the
    instigating flow dies at a protocol seam exactly as a process kill
    would leave it, and recovery must re-drive from the journal."""


def fire_crash_point(point: str, **detail) -> None:
    """Consult the process fault hook at one protocol seam. Production
    fast path: one global load + None check (like every other seam)."""
    if faultpoints.hook is None:
        return
    if faultpoints.fire(point, **detail) == "crash":
        raise NotaryChangeCrashError(
            f"injected coordinator crash at {point}"
        )


def change_journal(hub):
    """The hub's notary-change journal (lazily created, one per node
    database). Reuses PrepareJournal: same durable-phase-flip semantics
    — the "assume" record is the decision and gets the raised-durability
    write path the sharded journal applies to "committing"."""
    journal = getattr(hub, "_notary_change_journal", None)
    if journal is None:
        from .sharded_notary import PrepareJournal

        journal = _ChangeJournal(getattr(hub, "db", None))
        hub._notary_change_journal = journal
    return journal


def pending_notary_changes(hub) -> List[Tuple[str, dict]]:
    """Incomplete (crash-interrupted) notary changes awaiting recovery."""
    return change_journal(hub).items()


class _ChangeJournal:
    """PrepareJournal specialised to the notary-change table, mapping
    this protocol's decision phase ("assume") onto the raised-durability
    write the base class applies to "committing"."""

    def __init__(self, db):
        from .sharded_notary import PrepareJournal

        self._inner = PrepareJournal(db, table=JOURNAL_TABLE)

    def put(self, tx_hex: str, record: dict) -> None:
        if record.get("phase") == "assume":
            # borrow the base journal's durable-decision write path
            record = dict(record)
            record["phase"] = "committing"
            self._inner.put(tx_hex, record)
            return
        self._inner.put(tx_hex, record)

    def get(self, tx_hex: str):
        rec = self._inner.get(tx_hex)
        if rec is not None and rec.get("phase") == "committing":
            rec = dict(rec)
            rec["phase"] = "assume"
        return rec

    def remove(self, tx_hex: str) -> None:
        self._inner.remove(tx_hex)

    def items(self) -> List[Tuple[str, dict]]:
        out = []
        for tx_hex, rec in self._inner.items():
            if rec.get("phase") == "committing":
                rec = dict(rec)
                rec["phase"] = "assume"
            out.append((tx_hex, rec))
        return out


@initiating_flow
class NotaryChangeRecoveryFlow(FlowLogic):
    """Re-drive every incomplete notary change forward to completion.

    Safe to run any time (idempotent: both notary commits accept a
    replay of the same tx), and after any crash point:

      * no journal entry (crash before prepare): nothing happened; the
        state still has exactly its old owner — nothing to do;
      * phase "prepare": the old notary may or may not have committed —
        re-drive the consume (idempotent either way), then the assume;
      * phase "assume": the consume is durable; re-drive the assume
        (idempotent if it already landed) and finish.
    """

    def call(self):
        hub = self.service_hub
        journal = change_journal(hub)
        recovered = []
        for tx_hex, rec in journal.items():
            stx = rec["stx"]
            wtx = stx.tx
            if rec.get("phase") == "prepare":
                old_sigs = yield from self.sub_flow(NotaryClientFlowRef(stx))
                stx = stx.with_additional_signatures(old_sigs)
                journal.put(tx_hex, dict(rec, phase="assume", stx=stx))
            cross_domain = (
                wtx.new_notary.owning_key.encoded
                != wtx.notary.owning_key.encoded
            )
            if cross_domain:
                new_sigs = yield from self.sub_flow(
                    NotaryClientFlowRef(stx, notary=wtx.new_notary)
                )
                stx = stx.with_additional_signatures(new_sigs)
            hub.record_transactions([stx])
            journal.remove(tx_hex)
            eventlog.emit(
                "info", "notary", "notary change recovered",
                tx_id=tx_hex[:16], old=wtx.notary.name,
                new=wtx.new_notary.name,
            )
            recovered.append(tx_hex)
        return recovered

"""NodeSchedulerService: run flows when SchedulableStates come due
(reference `node/.../services/events/NodeSchedulerService.kt:38-218` +
`ScheduledActivityObserver.kt`).

The vault feed drives the schedule: every relevant SchedulableState output
registers its next activity; consuming the state unregisters it.  The
schedule persists in the node DB so a restarted node resumes its timers.
`wake()` fires everything due — called by the node's timer thread in real
deployments and directly by deterministic tests (TestClock pattern).
"""
from __future__ import annotations

import threading
from typing import List, Optional, Tuple

from ..core.contracts.structures import SchedulableState, StateRef
from ..core.flows.api import flow_registry
from ..core.serialization.codec import deserialize, serialize
from .database import KVStore, NodeDatabase
from ..utils import lockorder


class SchedulerService:
    def __init__(self, db: NodeDatabase, services, smm):
        self._store = KVStore(db, "scheduled_states")
        self._services = services
        self._smm = smm
        self._lock = lockorder.make_lock("SchedulerService._lock")
        services.vault_service.track(self._on_vault_update)

    @staticmethod
    def _key(ref: StateRef) -> bytes:
        return ref.txhash.bytes + ref.index.to_bytes(4, "big")

    def _on_vault_update(self, produced, consumed) -> None:
        for ref in consumed:
            self._store.delete(self._key(ref))
        for sr in produced:
            state = sr.state.data
            if not isinstance(state, SchedulableState):
                continue
            activity = state.next_scheduled_activity(sr.ref)
            if activity is None:
                continue
            self._store.put(
                self._key(sr.ref),
                serialize({
                    "flow_name": activity.flow_name,
                    "flow_args": list(activity.flow_args),
                    "at": activity.scheduled_at,
                    "ref": sr.ref,
                }),
            )

    def scheduled_count(self) -> int:
        return len(self._store)

    def next_scheduled_time(self) -> Optional[int]:
        times = [deserialize(v)["at"] for _, v in self._store.items()]
        return min(times) if times else None

    def wake(self, now: Optional[int] = None) -> List[str]:
        """Start every due activity; returns started flow ids.  `now` is
        unix nanos (defaults to the service-hub clock)."""
        if now is None:
            now = int(self._services.clock() * 1_000_000_000)
        started = []
        with self._lock:
            due: List[Tuple[bytes, dict]] = []
            for k, v in list(self._store.items()):
                entry = deserialize(v)
                if entry["at"] <= now:
                    due.append((k, entry))
            for k, entry in due:
                # Remove first: if the flow crashes we do not re-fire forever
                # (the reference relies on the flow consuming the state).
                self._store.delete(k)
            for k, entry in due:
                cls = flow_registry.get(entry["flow_name"])
                if cls is None:
                    import logging as _logging

                    _logging.getLogger(__name__).warning(
                        "no flow registered as %r; dropping activity",
                        entry["flow_name"],
                    )
                    continue
                args = tuple(entry["flow_args"])
                flow = cls(*args)
                try:
                    handle = self._smm.start_flow(flow, *args)
                except Exception as exc:
                    # admission shed (NodeOverloadedError) or any other
                    # start failure: a time-triggered activity must be
                    # DEFERRED, never silently lost — put the entry back
                    # so the next wake retries it once load drops
                    from ..utils import eventlog
                    from .admission import NodeOverloadedError

                    self._store.put(k, serialize(entry))
                    eventlog.emit(
                        "warning", "scheduler",
                        "scheduled activity deferred",
                        flow=entry["flow_name"],
                        error=f"{type(exc).__name__}: {exc}",
                    )
                    if not isinstance(exc, NodeOverloadedError):
                        raise
                    continue
                started.append(handle.flow_id)
        if started:
            from ..utils import eventlog

            eventlog.emit(
                "info", "scheduler", "scheduled activities fired",
                fired=len(started),
            )
        return started

"""Node file configuration: defaults overlaid by node.conf.

Reference parity: typesafe-config `reference.conf` defaults overlaid by the
node's `node.conf`, bound to `FullNodeConfiguration`
(`node/src/main/resources/reference.conf`, `services/config/
NodeConfiguration.kt:21-98`).  The file format here is JSON (one parser in
the stdlib beats a HOCON re-implementation); the overlay semantics are the
same: every key is optional, defaults below are the reference.conf
analogue.
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import List, Optional

from .node import NodeConfiguration

#: reference.conf analogue (reference `reference.conf:1-21`, incl.
#: `verifierType = InMemory`).
DEFAULTS = {
    "my_legal_name": "Anonymous Node",
    "base_directory": ".",
    "db_file": "node.db",          # relative to base_directory
    "journal_dir": "journal",      # relative; broker durability
    "verifier_type": "InMemory",   # InMemory | OutOfProcess
    "notary_type": None,            # None | simple | validating
    "identity_entropy": None,
    "broker_host": "127.0.0.1",
    "broker_port": 0,               # 0 = pick a free port, written to port file
    # what this node REGISTERS as its reachable address ("HOST:PORT");
    # null = broker_host:actual_port. Set it when peers must reach the
    # node through an interposed hop — a NAT'd/forwarded port, or the
    # soak's partition proxy (loadtest/netproxy.py) in front of the
    # broker.
    "advertised_address": None,
    "rpc_users": [],                # [{"username","password","permissions":[...]}]
    "jax_platform": None,
    "network_map": None,            # "HOST:PORT" of the directory node, or None
    "network_map_service": False,   # True: this node IS the directory node
    "tls": False,                   # mutual-TLS on the broker transport
    # cluster members re-register their SHARED identity this often (s) so
    # the route fails over to a live member quickly (0 disables)
    "cluster_route_refresh": 20.0,
    "certificates_dir": "certificates",  # may be shared between dev nodes
    # CorDapp scan analogue (reference AbstractNode.scanCordapps /
    # installCordaServices, AbstractNode.kt:291-315): python modules to
    # import at startup so their @startable_by_rpc / @initiated_by flows
    # register.
    "cordapps": ["corda_tpu.finance.flows"],
    # observability endpoint (GET /metrics Prometheus + GET /traces/*):
    # null = off, 0 = ephemeral port, N = fixed port
    "ops_port": None,
    # overload protection (docs/robustness.md): token-bucket rate limit
    # on new client flow starts (flows/s; null = CORDA_TPU_ADMISSION_RATE
    # or no gate), bucket burst, live-flow concurrency cap
    "admission_rate": None,
    "admission_burst": None,
    "admission_max_flows": None,
    # horizontal scale (docs/sharding.md): notary uniqueness partition
    # count (null = CORDA_TPU_SHARDS or unsharded) and the number of OS
    # worker processes serving this node's flow/verify hot path behind
    # its broker (null = CORDA_TPU_NODE_WORKERS or single-process)
    "shards": None,
    "node_workers": None,
    # multi-domain federation (docs/robustness.md §6): the named trust
    # segment this node belongs to (null = domainless, visible
    # fleet-wide — byte-identical to a single-domain network) and
    # whether it advertises as a cross-domain gateway
    "domain": None,
    "gateway": False,
}


@dataclass
class FullNodeConfiguration:
    """Everything a standalone node process needs (node + transport)."""

    node: NodeConfiguration
    base_directory: str
    journal_dir: str
    broker_host: str
    broker_port: int
    advertised_address: Optional[str] = None
    rpc_users: List[dict] = field(default_factory=list)
    jax_platform: Optional[str] = None
    network_map: Optional[str] = None
    network_map_service: bool = False
    tls: bool = False
    certificates_dir: str = "certificates"
    cordapps: List[str] = field(default_factory=list)
    cluster_route_refresh: float = 20.0


def load_config(config_dir: str, overrides: Optional[dict] = None) -> FullNodeConfiguration:
    """DEFAULTS <- node.conf <- overrides, then resolve paths."""
    cfg = dict(DEFAULTS)
    path = os.path.join(config_dir, "node.conf")
    if os.path.exists(path):
        with open(path) as fh:
            cfg.update(json.load(fh))
    cfg.update(overrides or {})

    base = os.path.abspath(
        os.path.join(config_dir, cfg.get("base_directory", "."))
    )
    os.makedirs(base, exist_ok=True)
    node_cfg = NodeConfiguration(
        my_legal_name=cfg["my_legal_name"],
        db_path=os.path.join(base, cfg["db_file"]),
        verifier_type=cfg["verifier_type"],
        notary_type=cfg["notary_type"],
        identity_entropy=cfg["identity_entropy"],
        # production processes take the incremental-checkpoint fast path
        # unless node.conf opts back into per-step validation
        dev_checkpoint_check=bool(cfg.get("dev_checkpoint_check", False)),
        raft_cluster=cfg.get("raft_cluster"),
        bft_cluster=cfg.get("bft_cluster"),
        ops_port=(
            int(cfg["ops_port"]) if cfg.get("ops_port") is not None else None
        ),
        admission_rate=(
            float(cfg["admission_rate"])
            if cfg.get("admission_rate") is not None else None
        ),
        admission_burst=(
            float(cfg["admission_burst"])
            if cfg.get("admission_burst") is not None else None
        ),
        admission_max_flows=(
            int(cfg["admission_max_flows"])
            if cfg.get("admission_max_flows") is not None else None
        ),
        shards=(
            int(cfg["shards"]) if cfg.get("shards") is not None
            else (int(os.environ["CORDA_TPU_SHARDS"])
                  if os.environ.get("CORDA_TPU_SHARDS") else None)
        ),
        node_workers=(
            int(cfg["node_workers"]) if cfg.get("node_workers") is not None
            else (int(os.environ["CORDA_TPU_NODE_WORKERS"])
                  if os.environ.get("CORDA_TPU_NODE_WORKERS") else None)
        ),
        domain=cfg.get("domain"),
        gateway=bool(cfg.get("gateway", False)),
    )
    return FullNodeConfiguration(
        node=node_cfg,
        base_directory=base,
        journal_dir=os.path.join(base, cfg["journal_dir"]),
        broker_host=cfg["broker_host"],
        broker_port=int(cfg["broker_port"]),
        advertised_address=cfg.get("advertised_address"),
        rpc_users=list(cfg["rpc_users"]),
        jax_platform=cfg["jax_platform"],
        network_map=cfg.get("network_map"),
        network_map_service=bool(cfg["network_map_service"]),
        tls=bool(cfg["tls"]),
        certificates_dir=(
            cfg["certificates_dir"]
            if os.path.isabs(cfg["certificates_dir"])
            else os.path.join(base, cfg["certificates_dir"])
        ),
        cordapps=list(cfg["cordapps"]),
        cluster_route_refresh=float(cfg["cluster_route_refresh"]),
    )

"""Node persistence: a single embedded SQLite store.

The reference stacks four JVM ORMs over H2 (SURVEY.md section 2.9); here one
sqlite3 database holds every node-side table.  Each storage service owns its
tables and goes through `NodeDatabase`, which serializes access with a lock
(the node's logical server thread + background threads share it safely).

Reference seams:
  * CheckpointStorage   — `node/.../api/CheckpointStorage.kt:33`,
                          `DBCheckpointStorage.kt:18-60`
  * TransactionStorage  — `node/.../persistence/DBTransactionStorage.kt`
  * AttachmentStorage   — `node/.../persistence/NodeAttachmentService.kt`
  * generic KV map      — `node/.../utilities/JDBCHashMap.kt` (508 LoC of
                          blob-map plumbing the TPU build gets from sqlite)
"""
from __future__ import annotations

import sqlite3
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ..core.crypto.secure_hash import SecureHash
from ..core.serialization.codec import deserialize, serialize
from ..utils import eventlog, faultpoints, lockorder
from . import recovery

#: injectable sqlite connection factory (the VFS seam of ISSUE 20):
#: testing/crashstore.py swaps this to RECORD which database files a
#: node opens, so a simulated power cut can tear their unsynced WAL
#: tails before a relaunch reopens them. Read at call time — rebinding
#: the module attribute is the whole protocol.
connect_factory: Callable[..., sqlite3.Connection] = sqlite3.connect

#: durability barriers of the checkpoint store (store "checkpoints"):
#: each op fires `<point>` before its write reaches sqlite and
#: `<point>.committed` once the commit returned — a crash between the
#: two is the torn window tools/crashmc.py explores.
_P_CP_PUT = faultpoints.register_crash_point(
    "checkpoint.put", "checkpoints")
_P_CP_PUT_INC = faultpoints.register_crash_point(
    "checkpoint.put_incremental", "checkpoints")
_P_CP_REMOVE = faultpoints.register_crash_point(
    "checkpoint.remove", "checkpoints")
for _p in (_P_CP_PUT, _P_CP_PUT_INC, _P_CP_REMOVE):
    faultpoints.register_crash_point(_p + ".committed", "checkpoints")
_P_GC_DRAIN = faultpoints.register_crash_point(
    "checkpoint.group_commit.drain", "checkpoints")
_P_GC_COMMITTED = faultpoints.register_crash_point(
    "checkpoint.group_commit.committed", "checkpoints")


class NodeDatabase:
    """Shared sqlite connection. path=':memory:' for tests/MockNetwork."""

    def __init__(self, path: str = ":memory:", synchronous: str = "NORMAL"):
        """`synchronous`: sqlite durability level. "NORMAL" (default) is
        the node-db setting of every prior round; the sharded notary's
        per-shard COMMIT LOGS use "FULL" — a uniqueness commit that can
        vanish on power loss is a double-spend waiting to be admitted
        (docs/sharding.md §durability)."""
        self.path = path
        self._conn = connect_factory(path, check_same_thread=False,
                                     timeout=30.0)
        # busy-wait instead of instant OperationalError under contention:
        # a sharded node's WORKER PROCESSES share this file (shardhost)
        self._conn.execute("PRAGMA busy_timeout=30000")
        # the journal-mode switch needs an exclusive lock that concurrent
        # initialisers race for, and sqlite returns SQLITE_BUSY from it
        # WITHOUT consulting the busy handler — retry explicitly
        import time as _time

        for attempt in range(200):
            try:
                self._conn.execute("PRAGMA journal_mode=WAL")
                break
            except sqlite3.OperationalError:
                if attempt == 199:
                    raise
                _time.sleep(0.01)
        self._conn.execute(f"PRAGMA synchronous={synchronous}")
        self.lock = lockorder.make_rlock("NodeDatabase.lock")
        # depth of open transaction() contexts on the holding thread:
        # per-statement autocommit is suppressed inside, so a batch
        # (e.g. record_transactions' tx + vault + attribute rows) pays
        # ONE commit cycle instead of ~10. A rollback anywhere poisons
        # the whole nested batch (one shared sqlite transaction).
        self._batch_depth = 0
        self._batch_failed = False

    def execute(self, sql: str, params: Tuple = ()) -> sqlite3.Cursor:
        with self.lock:
            cur = self._conn.execute(sql, params)
            if self._batch_depth == 0:
                self._conn.commit()
            return cur

    def executemany(self, sql: str, rows) -> None:
        with self.lock:
            self._conn.executemany(sql, rows)
            if self._batch_depth == 0:
                self._conn.commit()

    def query(self, sql: str, params: Tuple = ()) -> List[Tuple]:
        with self.lock:
            return self._conn.execute(sql, params).fetchall()

    def transaction(self):
        """Context manager: BEGIN ... COMMIT/ROLLBACK under the lock."""
        return _Tx(self)

    def close(self) -> None:
        with self.lock:
            self._conn.close()


class _Tx:
    """Holds the db lock for the block; per-statement autocommit is
    suppressed inside (reentrant: only the OUTERMOST exit commits, and
    an exception anywhere rolls the whole batch back)."""

    def __init__(self, db: NodeDatabase):
        self.db = db

    def __enter__(self):
        self.db.lock.acquire()
        self.db._batch_depth += 1
        return self.db._conn

    def __exit__(self, exc_type, exc, tb):
        try:
            self.db._batch_depth -= 1
            if exc_type is not None:
                # one shared sqlite transaction: this rollback discards
                # the OUTER levels' statements too, so poison the batch —
                # a caller that swallows the inner exception must not get
                # a partial commit of whatever it issues afterwards
                self.db._conn.rollback()
                self.db._batch_failed = True
            elif self.db._batch_depth == 0:
                if self.db._batch_failed:
                    self.db._conn.rollback()
                    raise sqlite3.OperationalError(
                        "batch poisoned by an inner rollback"
                    )
                self.db._conn.commit()
            if self.db._batch_depth == 0:
                self.db._batch_failed = False
        finally:
            self.db.lock.release()
        return False


class _GroupCommitter:
    """Leader/follower sqlite group commit (the coalescing shape the
    notary commit path proved in PR 1, one layer down): concurrent
    writers enqueue their statement closures; the first becomes the
    LEADER and executes everything pending in ONE transaction (one
    commit cycle — one WAL append, one fsync at FULL durability) while
    followers park on an event that only sets after THEIR batch
    committed. Durability semantics are therefore unchanged: every
    `run()` returns with its writes committed, exactly like the direct
    per-op transaction it replaces — concurrency is what buys the win
    (the batch grows with the arrivals during the previous commit
    cycle, plus an optional bounded linger).

    A writer already inside a db transaction (reentrant db.lock holder)
    bypasses the group — becoming a follower there would deadlock the
    leader against the held lock, and its statements already ride the
    outer batch's single commit."""

    def __init__(self, db: NodeDatabase, linger_s: float = 0.0):
        self.db = db
        self.linger_s = linger_s
        self._lock = lockorder.make_lock("_GroupCommitter._lock")
        # guarded-by: _lock
        self._pending: List[Tuple[Callable, threading.Event, dict]] = []
        self._leader_active = False
        self.stats = {"batches": 0, "ops": 0, "max_batch": 0}

    def run(self, op: Callable) -> None:
        """Execute `op(conn)` durably: coalesced into the current drain
        window's shared commit, or directly when re-entrant."""
        owned = getattr(self.db.lock, "_is_owned", None)
        if owned is not None and owned():
            with self.db.transaction() as tx:
                op(tx)
            return
        ev = threading.Event()
        box: dict = {}
        with self._lock:
            self._pending.append((op, ev, box))
            leader = not self._leader_active
            if leader:
                self._leader_active = True
        if not leader:
            ev.wait()  # the leader always drains the batch it saw us in
            if "err" in box:
                raise box["err"]
            return
        try:
            while True:
                if self.linger_s > 0:
                    time.sleep(self.linger_s)  # bounded accumulation
                with self._lock:
                    batch, self._pending = self._pending, []
                if batch:
                    self._commit_batch(batch)
                with self._lock:
                    if not self._pending:
                        self._leader_active = False
                        break
            # the leader's OWN op rode its first batch: surface its
            # error exactly like a follower's
            if "err" in box:
                raise box["err"]
            return
        except BaseException:
            # a leader must never die holding the flag: fail whatever is
            # still queued loudly instead of wedging future writers
            with self._lock:
                orphans, self._pending = self._pending, []
                self._leader_active = False
            for _op, oev, obox in orphans:
                obox["err"] = RuntimeError("group-commit leader died")
                oev.set()
            raise

    def _commit_batch(self, batch) -> None:
        # BEFORE the try: a crash injected at the drain barrier must be
        # the leader dying, not a poisoned batch the individual re-run
        # below would quietly absorb
        faultpoints.crash_fire(_P_GC_DRAIN, batch=len(batch))
        try:
            with self.db.transaction() as tx:
                for op, _ev, _box in batch:
                    op(tx)
        except BaseException as exc:
            # shared transaction poisoned: one bad op must not fail its
            # innocent batch-mates — re-run each alone, surfacing each
            # op's own error to its own caller
            eventlog.emit(
                "warning", "checkpoint",
                "group-commit batch poisoned; re-running ops individually",
                error=f"{type(exc).__name__}: {exc}", batch=len(batch),
            )
            for op, ev, box in batch:
                try:
                    with self.db.transaction() as tx:
                        op(tx)
                except BaseException as exc:
                    box["err"] = exc
                finally:
                    ev.set()
        else:
            for _op, ev, _box in batch:
                ev.set()
        self.stats["batches"] += 1
        self.stats["ops"] += len(batch)
        self.stats["max_batch"] = max(self.stats["max_batch"], len(batch))
        # post-barrier: the batch is durable, followers already released
        faultpoints.crash_fire(_P_GC_COMMITTED, batch=len(batch))


class CheckpointStorage:
    """flow_id -> checkpoint (replay state, not a serialized stack).

    Two write paths with one read contract:
      * `put(flow_id, blob)` — a full serialized checkpoint dict;
      * `put_incremental(...)` — the hot path: the flow header (identity,
        ctor args) is written once, io-log entries append, and only the
        small session-counter blob rewrites per step. Re-serializing the
        entire checkpoint on EVERY suspension was O(steps^2) per flow and
        one of the biggest CPU items in the round-3 system profile.
    `all_checkpoints()` returns full blobs for both paths (incremental
    rows are assembled at read time — restores are rare, steps are not).

    GROUP COMMIT (docs/perf-system.md round 20): with concurrent flows
    (multi-lane executor + blocking pool + RPC threads) every per-step
    write paid its own sqlite commit under the db lock. AbstractNode
    arms `enable_group_commit()` on async transports so concurrent
    step-checkpoints coalesce into one commit cycle per drain window —
    writers still block until THEIR write is durably committed, so a
    flow that parks has its checkpoint on disk exactly as before
    (suspend durability unchanged; see the crash-redelivery pin in
    tests/test_flowpath.py). CORDA_TPU_CP_GROUP_COMMIT=0 restores the
    per-op commits; the deterministic MockNetwork transport never arms
    it."""

    def __init__(self, db: NodeDatabase):
        self.db = db
        self._group: Optional[_GroupCommitter] = None
        db.execute(
            "CREATE TABLE IF NOT EXISTS checkpoints "
            "(flow_id TEXT PRIMARY KEY, blob BLOB NOT NULL)"
        )
        db.execute(
            "CREATE TABLE IF NOT EXISTS cp_header "
            "(flow_id TEXT PRIMARY KEY, blob BLOB NOT NULL)"
        )
        db.execute(
            "CREATE TABLE IF NOT EXISTS cp_io "
            "(flow_id TEXT NOT NULL, pos INTEGER NOT NULL, blob BLOB NOT NULL,"
            " PRIMARY KEY (flow_id, pos))"
        )
        db.execute(
            "CREATE TABLE IF NOT EXISTS cp_sessions "
            "(flow_id TEXT PRIMARY KEY, blob BLOB NOT NULL)"
        )
        # corrupt rows are MOVED here (never silently destroyed, never
        # re-deserialized at the next restart) — the quarantine half of
        # the CRC frame contract (node/recovery.py)
        db.execute(
            "CREATE TABLE IF NOT EXISTS cp_quarantine "
            "(flow_id TEXT NOT NULL, src TEXT NOT NULL, "
            "blob BLOB NOT NULL, reason TEXT NOT NULL)"
        )

    def enable_group_commit(self, linger_ms: float = 0.0) -> None:
        """Arm checkpoint write coalescing (idempotent). `linger_ms`
        bounds how long a commit leader waits for more writers to
        accumulate (0 = drain-window coalescing only)."""
        if self._group is None:
            self._group = _GroupCommitter(self.db, linger_s=linger_ms / 1000.0)

    @property
    def group_commit_stats(self) -> Optional[dict]:
        return None if self._group is None else dict(self._group.stats)

    def _write(self, op: Callable, point: Optional[str] = None) -> None:
        if point is not None:
            faultpoints.crash_fire(point)
        if self._group is not None:
            self._group.run(op)
        else:
            with self.db.transaction() as tx:
                op(tx)
        if point is not None:
            faultpoints.crash_fire(point + ".committed")

    def put(self, flow_id: str, blob: bytes) -> None:
        framed = recovery.frame(blob)
        self._write(lambda tx: tx.execute(
            "INSERT INTO checkpoints(flow_id, blob) VALUES(?, ?) "
            "ON CONFLICT(flow_id) DO UPDATE SET blob = excluded.blob",
            (flow_id, framed),
        ), point=_P_CP_PUT)

    def put_incremental(
        self,
        flow_id: str,
        header_blob: Optional[bytes],
        new_io: List[Tuple[int, bytes]],
        sessions_blob: bytes,
    ) -> None:
        """One atomic step-checkpoint: optional header upsert + appended
        io entries + the session-state blob. Writing the header also
        deletes any legacy full-blob row — the incremental rows are now
        authoritative (all_checkpoints would otherwise prefer the stale
        legacy blob forever)."""
        def op(tx):
            if header_blob is not None:
                tx.execute(
                    "INSERT INTO cp_header(flow_id, blob) VALUES(?, ?) "
                    "ON CONFLICT(flow_id) DO UPDATE SET blob = excluded.blob",
                    (flow_id, recovery.frame(header_blob)),
                )
                tx.execute(
                    "DELETE FROM checkpoints WHERE flow_id = ?", (flow_id,)
                )
            for pos, blob in new_io:
                tx.execute(
                    "INSERT OR REPLACE INTO cp_io(flow_id, pos, blob)"
                    " VALUES(?, ?, ?)",
                    (flow_id, pos, recovery.frame(blob)),
                )
            tx.execute(
                "INSERT INTO cp_sessions(flow_id, blob) VALUES(?, ?) "
                "ON CONFLICT(flow_id) DO UPDATE SET blob = excluded.blob",
                (flow_id, recovery.frame(sessions_blob)),
            )

        self._write(op, point=_P_CP_PUT_INC)

    def remove(self, flow_id: str) -> None:
        def op(tx):
            for table in ("checkpoints", "cp_header", "cp_io", "cp_sessions"):
                tx.execute(
                    f"DELETE FROM {table} WHERE flow_id = ?", (flow_id,)
                )

        self._write(op, point=_P_CP_REMOVE)

    def _quarantine(self, flow_id: str, src: str, blob: bytes,
                    reason: str) -> None:
        """Move one corrupt row aside (keep the evidence, drop the wedge):
        the flow's rows are removed from the live tables so the NEXT
        restart does not re-trip on them, and the torn blob is parked in
        cp_quarantine for the operator."""
        recovery.quarantine_record("checkpoints", f"{src}:{flow_id}", reason)
        with self.db.transaction() as tx:
            tx.execute(
                "INSERT INTO cp_quarantine(flow_id, src, blob, reason)"
                " VALUES(?, ?, ?, ?)",
                (flow_id, src, blob, reason),
            )
            for table in ("checkpoints", "cp_header", "cp_io", "cp_sessions"):
                tx.execute(
                    f"DELETE FROM {table} WHERE flow_id = ?", (flow_id,)
                )

    def quarantined(self) -> List[Tuple[str, str, str]]:
        """(flow_id, src table, reason) of every parked corrupt record."""
        return [
            (r[0], r[1], r[2])
            for r in self.db.query(
                "SELECT flow_id, src, reason FROM cp_quarantine"
            )
        ]

    def _assemble(self, flow_id: str, header_blob: bytes) -> bytes:
        state = deserialize(recovery.unframe(header_blob))
        state["io_log"] = [
            recovery.unframe(row[0])
            for row in self.db.query(
                "SELECT blob FROM cp_io WHERE flow_id = ? ORDER BY pos",
                (flow_id,),
            )
        ]
        rows = self.db.query(
            "SELECT blob FROM cp_sessions WHERE flow_id = ?", (flow_id,)
        )
        state.update(
            deserialize(recovery.unframe(rows[0][0]))
            if rows
            else {"sessions": [], "session_keys": {}, "session_owner_flows": {}}
        )
        return serialize(state)

    def get(self, flow_id: str) -> Optional[bytes]:
        """ONE flow's full checkpoint blob (either write path), or None.
        The flow hospital's replay-retry reads this at readmission time.
        A CRC-corrupt record quarantines (= None) instead of raising."""
        rows = self.db.query(
            "SELECT blob FROM checkpoints WHERE flow_id = ?", (flow_id,)
        )
        if rows:
            try:
                return recovery.unframe(rows[0][0])
            except recovery.CorruptRecordError as exc:
                self._quarantine(flow_id, "checkpoints", rows[0][0], str(exc))
                return None
        rows = self.db.query(
            "SELECT blob FROM cp_header WHERE flow_id = ?", (flow_id,)
        )
        if rows:
            try:
                return self._assemble(flow_id, rows[0][0])
            except recovery.CorruptRecordError as exc:
                self._quarantine(flow_id, "cp_header", rows[0][0], str(exc))
                return None
        return None

    def all_checkpoints(self) -> List[Tuple[str, bytes]]:
        out: List[Tuple[str, bytes]] = []
        legacy = set()
        for flow_id, blob in self.db.query(
            "SELECT flow_id, blob FROM checkpoints"
        ):
            legacy.add(flow_id)
            try:
                out.append((flow_id, recovery.unframe(blob)))
            except recovery.CorruptRecordError as exc:
                self._quarantine(flow_id, "checkpoints", blob, str(exc))
        for flow_id, blob in self.db.query(
            "SELECT flow_id, blob FROM cp_header"
        ):
            if flow_id in legacy:
                continue
            try:
                out.append((flow_id, self._assemble(flow_id, blob)))
            except recovery.CorruptRecordError as exc:
                self._quarantine(flow_id, "cp_header", blob, str(exc))
        return out

    def count(self) -> int:
        return (
            self.db.query("SELECT COUNT(*) FROM checkpoints")[0][0]
            + self.db.query(
                "SELECT COUNT(*) FROM cp_header WHERE flow_id NOT IN"
                " (SELECT flow_id FROM checkpoints)"
            )[0][0]
        )


class TransactionStorage:
    """Validated SignedTransactions by id, with a commit-observer feed
    (reference DBTransactionStorage + Rx updates).

    Reads go through an instance LRU: `get` used to deserialize a fresh
    SignedTransaction per call, and every fresh instance re-derives its
    id (a full Merkle build) on first use — backchain resolution and
    dependency checks hit the same hot transactions repeatedly, making
    this one of the larger per-pair costs in the system profile.
    SignedTransaction is immutable, so sharing instances is safe."""

    CACHE_MAX = 1024

    def __init__(self, db: NodeDatabase):
        self.db = db
        db.execute(
            "CREATE TABLE IF NOT EXISTS transactions "
            "(tx_id BLOB PRIMARY KEY, blob BLOB NOT NULL)"
        )
        self._observers: List[Callable] = []
        import threading
        from collections import OrderedDict

        self._cache: "OrderedDict[bytes, object]" = OrderedDict()
        # flows run on RPC pool workers + the p2p pump + the blocking
        # executor concurrently; an unsynchronized hit-then-move_to_end
        # racing an eviction would raise KeyError out of storage.get
        self._cache_lock = lockorder.make_lock(
            "TransactionStorage._cache_lock"
        )

    def add(self, stx) -> bool:
        """Record; returns False if already present. Fires observers on new."""
        recorded = self.add_batch([stx])
        return bool(recorded)

    def add_batch(self, txs) -> List:
        """Insert many in ONE sqlite transaction; observers fire only
        AFTER the batch commits (an observer announcing a row that a
        later failure rolls back would hand subscribers a transaction
        the database never kept)."""
        recorded = []
        with self.db.transaction():
            for stx in txs:
                existing = self.db.query(
                    "SELECT 1 FROM transactions WHERE tx_id = ?",
                    (stx.id.bytes,),
                )
                if existing:
                    continue
                self.db.execute(
                    "INSERT INTO transactions(tx_id, blob) VALUES(?, ?)",
                    (stx.id.bytes, serialize(stx)),
                )
                recorded.append(stx)
        for stx in recorded:
            self._cache_put(stx.id.bytes, stx)
            for obs in list(self._observers):
                obs(stx)
        return recorded

    def _cache_put(self, key: bytes, stx) -> None:
        with self._cache_lock:
            self._cache[key] = stx
            self._cache.move_to_end(key)
            while len(self._cache) > self.CACHE_MAX:
                self._cache.popitem(last=False)

    def get(self, tx_id: SecureHash):
        with self._cache_lock:
            hit = self._cache.get(tx_id.bytes)
            if hit is not None:
                self._cache.move_to_end(tx_id.bytes)
                return hit
        rows = self.db.query(
            "SELECT blob FROM transactions WHERE tx_id = ?", (tx_id.bytes,)
        )
        if not rows:
            return None
        stx = deserialize(rows[0][0])
        self._cache_put(tx_id.bytes, stx)
        return stx

    def track(self, observer: Callable) -> None:
        self._observers.append(observer)

    def all(self) -> List:
        """Every validated transaction (feed snapshots, explorer)."""
        return [
            deserialize(row[0])
            for row in self.db.query("SELECT blob FROM transactions")
        ]

    def latest(self, n: int) -> List:
        """The newest `n` transactions (insertion order), newest first —
        a bounded query so dashboards never materialize the whole store."""
        return [
            deserialize(row[0])
            for row in self.db.query(
                "SELECT blob FROM transactions ORDER BY rowid DESC LIMIT ?",
                (int(n),),
            )
        ]

    def count(self) -> int:
        return self.db.query("SELECT COUNT(*) FROM transactions")[0][0]


class AttachmentStorage:
    """Content-addressed attachment store with hash verification on read
    (reference NodeAttachmentService: hash check catches disk corruption)."""

    def __init__(self, db: NodeDatabase):
        self.db = db
        db.execute(
            "CREATE TABLE IF NOT EXISTS attachments "
            "(att_id BLOB PRIMARY KEY, data BLOB NOT NULL)"
        )

    def import_attachment(self, data: bytes) -> SecureHash:
        att_id = SecureHash.sha256(data)
        self.db.execute(
            "INSERT OR IGNORE INTO attachments(att_id, data) VALUES(?, ?)",
            (att_id.bytes, data),
        )
        return att_id

    def open_attachment(self, att_id: SecureHash):
        from ..core.contracts.structures import Attachment

        rows = self.db.query(
            "SELECT data FROM attachments WHERE att_id = ?", (att_id.bytes,)
        )
        if not rows:
            return None
        data = rows[0][0]
        if SecureHash.sha256(data) != att_id:
            raise IOError(f"attachment {att_id} corrupted on disk")
        return Attachment(att_id, data)

    def has_attachment(self, att_id: SecureHash) -> bool:
        return bool(
            self.db.query(
                "SELECT 1 FROM attachments WHERE att_id = ?", (att_id.bytes,)
            )
        )

    def attachment_size(self, att_id: SecureHash):
        rows = self.db.query(
            "SELECT length(data) FROM attachments WHERE att_id = ?",
            (att_id.bytes,),
        )
        return rows[0][0] if rows else None

    def read_chunk(self, att_id: SecureHash, offset: int, length: int):
        """Byte range without materialising the whole blob on the server —
        sqlite substr() slices in-engine (reference: large attachments
        stream via Artemis minLargeMessageSize, NodeMessagingClient.kt:172;
        here the chunk RPC protocol is the streaming seam)."""
        rows = self.db.query(
            "SELECT substr(data, ?, ?) FROM attachments WHERE att_id = ?",
            (offset + 1, length, att_id.bytes),  # substr is 1-based
        )
        return rows[0][0] if rows else None


class KVStore:
    """Generic named blob map (the JDBCHashMap replacement)."""

    def __init__(self, db: NodeDatabase, name: str):
        assert name.isidentifier()
        self.db = db
        self.table = f"kv_{name}"
        db.execute(
            f"CREATE TABLE IF NOT EXISTS {self.table} "
            "(k BLOB PRIMARY KEY, v BLOB NOT NULL)"
        )

    def put(self, key: bytes, value: bytes) -> None:
        self.db.execute(
            f"INSERT INTO {self.table}(k, v) VALUES(?, ?) "
            "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
            (key, value),
        )

    def put_if_absent(self, key: bytes, value: bytes) -> bool:
        """Atomic insert-if-absent; returns True if inserted."""
        with self.db.lock:
            cur = self.db.execute(
                f"INSERT OR IGNORE INTO {self.table}(k, v) VALUES(?, ?)",
                (key, value),
            )
            return cur.rowcount == 1

    def get(self, key: bytes) -> Optional[bytes]:
        rows = self.db.query(
            f"SELECT v FROM {self.table} WHERE k = ?", (key,)
        )
        return rows[0][0] if rows else None

    #: sqlite's default SQLITE_MAX_VARIABLE_NUMBER floor; chunking keeps
    #: get_many safe for arbitrarily large merged batches
    _IN_CHUNK = 500

    def get_many(self, keys) -> Dict[bytes, bytes]:
        """Present subset of `keys` in one SELECT per chunk — the batch
        read under a merged uniqueness commit (one pass over the merged
        StateRef set instead of one query per ref)."""
        keys = [bytes(k) for k in keys]
        found: Dict[bytes, bytes] = {}
        for i in range(0, len(keys), self._IN_CHUNK):
            chunk = keys[i:i + self._IN_CHUNK]
            marks = ",".join("?" * len(chunk))
            for k, v in self.db.query(
                f"SELECT k, v FROM {self.table} WHERE k IN ({marks})",
                tuple(chunk),
            ):
                found[bytes(k)] = bytes(v)
        return found

    def put_many(self, pairs) -> None:
        """Batch upsert via one executemany (one commit cycle)."""
        self.db.executemany(
            f"INSERT INTO {self.table}(k, v) VALUES(?, ?) "
            "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
            [(k, v) for k, v in pairs],
        )

    def delete(self, key: bytes) -> None:
        self.db.execute(f"DELETE FROM {self.table} WHERE k = ?", (key,))

    def items(self) -> Iterator[Tuple[bytes, bytes]]:
        return iter(self.db.query(f"SELECT k, v FROM {self.table}"))

    def __len__(self) -> int:
        return self.db.query(f"SELECT COUNT(*) FROM {self.table}")[0][0]

"""Multi-lane flow executor (docs/perf-system.md round 20).

The round-11 profile named the bank-side convoy: ONE p2p pump thread at
~96% CPU share stepping every flow continuation inline while ~25 threads
park. PR 11 made the pump's wire work (frame/parse/route) GIL-releasing
native calls, so the pump could overlap Python flow execution — except
the flow execution still ran ON the pump. This executor is the missing
half: session-message continuations dispatch onto N lane threads with
per-flow affinity, so the native drain of batch N+1 overlaps the Python
flow steps of batch N.

Affinity, not locking, is the ordering story: every session message
carries the `x-session-route` hint ("h:<sid>" / "t:<sid>", stamped by
`statemachine._send_session_message`), the hint's `<flow id>` prefix
picks the lane, and a lane is a FIFO — so one flow's (and one
session's) messages process in arrival order on one thread. Cross-flow
messages interleave freely across lanes; the per-FSM step lock
(`FlowStateMachine._step_lock`) stays the authority on state, exactly
as it already is for the blocking-executor and RPC threads.

Each lane owns its own lock + condition + queue: a submit wakes only
the target lane's worker (and only that lane's depth-blocked
submitters), never the whole pool — cross-lane contention would
serialize exactly the path this executor parallelizes.

Backpressure: each lane queue is bounded (LANE_DEPTH); `submit` BLOCKS
when the target lane is full, which parks the pump, which backs up the
broker queue, which engages the existing `CORDA_TPU_P2P_QUEUE_MAX`
caps — no new unbounded queue.

`CORDA_TPU_FLOW_LANES` sizes the pool (default: CPU count, except 0 on
a single-CPU host — nothing to overlap with; 0 restores today's
on-pump dispatch byte-identically). The deterministic in-memory test
transport stays inline unless a MockNetwork opts in explicitly
(`MockNetwork(flow_lanes=N)`), mirroring `dispatches_blocking_off_pump`.
"""
from __future__ import annotations

import os
import threading
import time
import zlib
from collections import deque
from typing import Callable, List

from ..utils import eventlog, lockorder

#: bound on each lane's pending-continuation queue: overflow blocks the
#: submitter (the pump), composing with the broker-side queue caps
LANE_DEPTH = 512


def default_lanes() -> int:
    """CORDA_TPU_FLOW_LANES, defaulting to the CPU count — every core
    can run a flow step while the pump drains natively — EXCEPT on a
    single-CPU host, where the default is 0: there is no second core to
    overlap with, so a lone lane is pure handoff overhead (measured
    ~5% on the 1-core build container's system stage). Set the knob
    explicitly to force lanes anywhere. 0 = on-pump dispatch."""
    raw = os.environ.get("CORDA_TPU_FLOW_LANES")
    if raw is None or raw == "":
        cpus = os.cpu_count() or 1
        return cpus if cpus >= 2 else 0
    return max(0, int(raw))


def lane_key(hint: str) -> str:
    """Per-flow affinity key of an `x-session-route` hint: the flow-id
    prefix of the session id ("<flow id>:<n>"), so every session of one
    flow — and every message of one session — lands on one lane."""
    sid = hint[2:] if hint[:2] in ("h:", "t:") else hint
    return sid.rsplit(":", 1)[0]


class _Lane:
    """One FIFO worker lane: own lock, own condition, own queue — a
    submit wakes only THIS lane."""

    def __init__(self, idx: int, name: str):
        self.lock = lockorder.make_lock(f"FlowLane[{idx}].lock")
        self.cv = lockorder.make_condition(self.lock, f"FlowLane[{idx}].cv")
        self.q: deque = deque()
        # guarded-by: lock
        self.busy = False
        self.stopped = False
        self.dispatched = 0
        self.completed = 0
        self.errors = 0


class FlowLaneExecutor:
    """N FIFO worker lanes with stable key -> lane assignment."""

    def __init__(self, n_lanes: int, name: str = "node",
                 depth: int = LANE_DEPTH):
        self.n_lanes = max(1, int(n_lanes))
        self.name = name
        self.depth = depth
        self._lanes: List[_Lane] = [
            _Lane(i, name) for i in range(self.n_lanes)
        ]
        self._threads = [
            threading.Thread(
                target=self._run, args=(lane,),
                name=f"flow-lane-{i}-{name}", daemon=True,
            )
            for i, lane in enumerate(self._lanes)
        ]
        for t in self._threads:
            t.start()

    # -- submission ----------------------------------------------------------

    def lane_of(self, key: str) -> int:
        """Stable, process-deterministic lane assignment (crc32, not
        hash(): str hashing is per-process salted)."""
        return zlib.crc32(key.encode("utf-8", "replace")) % self.n_lanes

    def submit(self, key: str, fn: Callable[[], None]) -> int:
        """Enqueue `fn` on the lane owning `key`; blocks while that lane
        is at depth (backpressure to the pump). Returns the lane index.
        Raises RuntimeError after stop() — callers fall back inline."""
        idx = self.lane_of(key)
        lane = self._lanes[idx]
        with lane.lock:
            while len(lane.q) >= self.depth and not lane.stopped:
                # lint: allow(blocking_under_lock) — cv wraps this lock
                lane.cv.wait(timeout=0.5)
            if lane.stopped:
                raise RuntimeError("flow lane executor is stopped")
            lane.q.append(fn)
            lane.dispatched += 1
            lane.cv.notify_all()
        return idx

    # -- worker --------------------------------------------------------------

    def _run(self, lane: _Lane) -> None:
        while True:
            with lane.lock:
                while not lane.q and not lane.stopped:
                    # lint: allow(blocking_under_lock) — cv wraps this lock
                    lane.cv.wait(timeout=0.5)
                if not lane.q:
                    return  # stopped and (drained or abandoned) empty
                fn = lane.q.popleft()
                lane.busy = True
                lane.cv.notify_all()  # wake a depth-blocked submitter
            try:
                fn()
            except BaseException as exc:
                # a continuation error must never kill the lane; the
                # flow's own _fail path already handled flow errors, so
                # anything landing here is a dispatch-layer bug worth
                # loud evidence
                with lane.lock:
                    lane.errors += 1
                eventlog.emit(
                    "error", "flowlanes",
                    "lane continuation error",
                    error=f"{type(exc).__name__}: {exc}", node=self.name,
                )
            finally:
                with lane.lock:
                    lane.busy = False
                    lane.completed += 1
                    lane.cv.notify_all()

    # -- lifecycle / introspection -------------------------------------------

    def depth_of(self, idx: int) -> int:
        return len(self._lanes[idx].q)

    def pending(self) -> int:
        return sum(len(lane.q) for lane in self._lanes)

    def idle(self) -> bool:
        for lane in self._lanes:
            with lane.lock:
                if lane.busy or lane.q:
                    return False
        return True

    def quiesce(self, timeout: float = 30.0) -> bool:
        """Wait until every lane is empty AND idle (the in-memory
        transport's run_network barrier). Returns False on timeout."""
        deadline = time.monotonic() + timeout
        for lane in self._lanes:  # sequential: total bounded by deadline
            with lane.lock:
                while lane.busy or lane.q:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                    # lint: allow(blocking_under_lock) — cv wraps this lock
                    lane.cv.wait(timeout=min(remaining, 0.2))
        return True

    def stop(self, drain: bool = True, timeout: float = 10.0) -> bool:
        """Stop the lanes. drain=True runs everything already queued
        first (node stop: in-flight continuations complete and their
        broker messages get acked); drain=False abandons the queues
        (their messages stay unacked -> broker redelivery)."""
        drained = True
        if drain:
            drained = self.quiesce(timeout)
        for lane in self._lanes:
            with lane.lock:
                lane.stopped = True
                if not drain:
                    lane.q.clear()
                lane.cv.notify_all()
        for t in self._threads:
            t.join(timeout=2)
        return drained

    def stats(self) -> dict:
        out = {"lanes": self.n_lanes, "dispatched": 0, "completed": 0,
               "errors": 0, "pending": 0}
        for lane in self._lanes:
            with lane.lock:
                out["dispatched"] += lane.dispatched
                out["completed"] += lane.completed
                out["errors"] += lane.errors
                out["pending"] += len(lane.q)
        return out

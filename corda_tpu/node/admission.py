"""Overload protection: admission control at the flow-start seam plus the
node-wide overload state machine.

The committee-consensus measurements (PAPERS: EdDSA/BLS in committee-based
consensus) show sustained throughput COLLAPSING — not plateauing — once
ingest outruns the signature/consensus pipeline: queues grow without
bound, latency blows through every SLO, and goodput falls below what the
node could serve had it simply refused the excess. PR 3 gave the node the
gauges (queue depth, batcher occupancy, blocking backlog); this module
makes it ACT on them:

  * `AdmissionController` gates NEW top-level client flows at
    `StateMachineManager.start_flow`: a token bucket (steady-state rate +
    burst) plus a live-flow concurrency cap. Rejections raise
    `NodeOverloadedError` carrying a computed `retry_after_ms` hint that
    propagates through the RPC layer so `CordaRPCClient` callers can back
    off instead of hammering.
  * PRIORITY traffic is classified and never shed before new client
    work: responder flows (session replies for already-admitted flows —
    notary commits arrive this way), hospital checkpoint-replay retries
    (they re-enter via `_restore`, below the admission seam), and flows
    whose class sets `_system_flow = True`.
  * `OverloadStateMachine` tracks normal -> shedding -> recovering with
    hysteresis (enter on a high-threshold breach of any registered
    signal, leave for `recovering` once every signal is back under its
    low threshold, return to `normal` after a quiet dwell). While
    shedding, admission rejects all new client work; `/readyz` serves
    503 until the machine is back to `normal` (the dwell prevents
    load-balancer flapping).

Knobs: CORDA_TPU_ADMISSION_RATE (flow starts/s; 0/unset = no rate gate),
CORDA_TPU_ADMISSION_BURST (bucket size, default 2x rate),
CORDA_TPU_ADMISSION_MAX_FLOWS (live-flow cap; 0/unset = no cap),
CORDA_TPU_ADMISSION_RETRY_MS (hint floor when shedding, default 250),
CORDA_TPU_OVERLOAD_HOLD_S (recovering -> normal dwell, default 2).
NodeConfiguration's admission_rate / admission_burst / admission_max_flows
override the environment per node.
"""
from __future__ import annotations

import math
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..core.serialization.codec import register_adapter
from ..utils import eventlog, lockorder


class NodeOverloadedError(Exception):
    """The node refused new work to protect work already in flight.

    `retry_after_ms` is the node's own estimate of when capacity frees
    up (token-bucket refill time, or the shed-state hint) — clients
    should back off at least that long before retrying."""

    def __init__(self, message: str, retry_after_ms: int = 0):
        super().__init__(message)
        self.retry_after_ms = max(0, int(retry_after_ms))


register_adapter(
    NodeOverloadedError, "NodeOverloadedError",
    lambda e: {"msg": str(e), "retry_after_ms": e.retry_after_ms},
    lambda d: NodeOverloadedError(
        d["msg"], retry_after_ms=d.get("retry_after_ms", 0)
    ),
)


class TokenBucket:
    """Thread-safe token bucket: `rate` tokens/s refill up to `burst`.

    `try_acquire` never blocks — on failure it returns the refill wait,
    which becomes the client-facing retry_after hint."""

    def __init__(self, rate: float, burst: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst if burst is not None else 2 * rate))
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()
        self._lock = lockorder.make_lock("TokenBucket._lock")

    def try_acquire(self, n: float = 1.0) -> Tuple[bool, float]:
        """(acquired, seconds_until_available_if_not)."""
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate
            )
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True, 0.0
            if self.rate <= 0:
                return False, 60.0  # bucket can never refill: park the caller
            return False, (n - self._tokens) / self.rate

    @property
    def tokens(self) -> float:
        with self._lock:
            now = self._clock()
            return min(
                self.burst, self._tokens + (now - self._last) * self.rate
            )


# -- overload state machine ---------------------------------------------------

NORMAL, SHEDDING, RECOVERING = "normal", "shedding", "recovering"
_STATE_CODE = {NORMAL: 0, RECOVERING: 1, SHEDDING: 2}


class OverloadStateMachine:
    """normal -> shedding -> recovering -> normal, with hysteresis.

    Signals are cheap zero-arg reads (the PR 3 backpressure gauges: P2P
    queue depth, verifier batcher occupancy, blocking backlog, live
    flows). The machine enters SHEDDING the moment ANY signal reaches
    its high threshold, moves to RECOVERING once EVERY signal is back
    at-or-under its low threshold, and returns to NORMAL after
    `hold_s` of continuous quiet (a breach during the dwell restarts
    it; a high breach re-enters SHEDDING).

    Evaluation is pull-based: `evaluate()` runs on every admission
    attempt and every health probe, so there is no sampler thread to
    manage and deterministic tests drive it with an injected clock."""

    def __init__(self, hold_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 metrics=None, node_name: str = ""):
        if hold_s is None:
            hold_s = float(os.environ.get("CORDA_TPU_OVERLOAD_HOLD_S", 2.0))
        self.hold_s = hold_s
        self._clock = clock
        self._node = node_name
        self._lock = lockorder.make_lock("OverloadStateMachine._lock")
        #: (name, read fn, high, low)
        self._signals: List[Tuple[str, Callable[[], float], float, float]] = []
        self._state = NORMAL
        self._since = clock()
        self._quiet_since: Optional[float] = None
        self._last_breach: Optional[str] = None
        self.transitions = 0
        self._metrics = metrics
        if metrics is not None:
            metrics.gauge(
                "Overload.State", lambda: _STATE_CODE.get(self._state, 0)
            )
            self._entered = metrics.counter("Overload.SheddingEntered")
        else:
            self._entered = None

    def add_signal(self, name: str, read: Callable[[], float],
                   high: float, low: Optional[float] = None) -> None:
        """Register a load signal. `low` defaults to high/4 — the
        hysteresis gap that keeps a queue hovering at the threshold from
        flapping the state (and /readyz) on every probe."""
        if low is None:
            low = high / 4.0
        with self._lock:
            self._signals.append((name, read, float(high), float(low)))

    @property
    def state(self) -> str:
        return self._state

    @property
    def shedding(self) -> bool:
        return self._state == SHEDDING

    def evaluate(self, now: Optional[float] = None) -> str:
        now = self._clock() if now is None else now
        breach_high: Optional[str] = None
        breach_low = False
        with self._lock:
            signals = list(self._signals)
        for name, read, high, low in signals:
            try:
                v = float(read())
            except Exception:
                continue  # a dead signal must not wedge admission
            if v >= high and breach_high is None:
                breach_high = f"{name}={v:g} >= {high:g}"
            if v > low:
                breach_low = True
        with self._lock:
            prev = self._state
            if breach_high is not None:
                self._last_breach = breach_high
                self._quiet_since = None
                if prev != SHEDDING:
                    self._transition_locked(SHEDDING, now)
            elif prev == SHEDDING:
                if not breach_low:
                    self._quiet_since = now
                    self._transition_locked(RECOVERING, now)
            elif prev == RECOVERING:
                if breach_low:
                    self._quiet_since = None  # dwell restarts on noise
                elif self._quiet_since is None:
                    self._quiet_since = now
                elif now - self._quiet_since >= self.hold_s:
                    self._transition_locked(NORMAL, now)
            return self._state

    def _transition_locked(self, state: str, now: float) -> None:
        prev, self._state, self._since = self._state, state, now
        self.transitions += 1
        if state == SHEDDING and self._entered is not None:
            self._entered.inc()
        eventlog.emit(
            "warning" if state == SHEDDING else "info",
            "overload", f"overload state {prev} -> {state}",
            node=self._node, cause=self._last_breach,
        )

    def snapshot(self, evaluate: bool = True) -> Dict:
        if evaluate:
            self.evaluate()
        # signal reads run OUTSIDE the lock (they take other locks —
        # queue_depth takes the network's; holding ours across them
        # would block concurrent admit()/evaluate() for the probe)
        with self._lock:
            signals = list(self._signals)
        readings = {}
        for name, read, high, low in signals:
            try:
                readings[name] = {
                    "value": float(read()), "high": high, "low": low,
                }
            except Exception as exc:
                readings[name] = {"error": repr(exc)}
        with self._lock:
            return {
                "state": self._state,
                "since_s": round(self._clock() - self._since, 3),
                "hold_s": self.hold_s,
                "transitions": self.transitions,
                "last_breach": self._last_breach,
                "signals": readings,
            }


# -- admission control --------------------------------------------------------

def _env_float(name: str) -> Optional[float]:
    raw = os.environ.get(name)
    if raw in (None, ""):
        return None
    v = float(raw)
    return v if v > 0 else None


class AdmissionController:
    """Gate for NEW top-level client flows (the RPC start_flow seam).

    Decision order, cheapest-reject first and priority always through:
      1. priority traffic (responder flows, `_system_flow` classes) is
         admitted unconditionally — it completes work already admitted
         somewhere, so shedding it would only grow the backlog;
      2. while the overload machine sheds, every new client flow is
         rejected (degradation mode);
      3. the live-flow concurrency cap;
      4. the token-bucket rate limit.

    Every admit/reject lands in the `Admission.*` counter families and
    (rejections) the flight recorder."""

    def __init__(self, rate: Optional[float] = None,
                 burst: Optional[float] = None,
                 max_flows: Optional[int] = None,
                 live_flows: Optional[Callable[[], int]] = None,
                 overload: Optional[OverloadStateMachine] = None,
                 metrics=None,
                 clock: Callable[[], float] = time.monotonic,
                 node_name: str = ""):
        if rate is None:
            rate = _env_float("CORDA_TPU_ADMISSION_RATE")
        if burst is None:
            burst = _env_float("CORDA_TPU_ADMISSION_BURST")
        if max_flows is None:
            mf = _env_float("CORDA_TPU_ADMISSION_MAX_FLOWS")
            max_flows = int(mf) if mf is not None else None
        self.bucket = (
            TokenBucket(rate, burst, clock=clock)
            if rate is not None and rate > 0 else None
        )
        self.max_flows = (
            int(max_flows) if max_flows is not None and max_flows > 0
            else None
        )
        self.live_flows = live_flows or (lambda: 0)
        self.overload = overload
        self._clock = clock
        self._node = node_name
        self.shed_retry_ms = int(
            float(os.environ.get("CORDA_TPU_ADMISSION_RETRY_MS", 250))
        )
        from ..utils.metrics import MetricRegistry

        m = metrics or MetricRegistry()
        # eager creation: the Admission.* families must render on
        # /metrics from the first scrape, not from the first rejection
        self.admitted = m.counter("Admission.Admitted")
        self.priority = m.counter("Admission.Priority")
        self.rejected = m.counter("Admission.Rejected")
        self.rejected_rate = m.counter("Admission.RejectedByRate")
        self.rejected_cap = m.counter("Admission.RejectedByCap")
        self.rejected_shedding = m.counter("Admission.RejectedShedding")

    @staticmethod
    def is_priority(flow=None, is_responder: bool = False) -> bool:
        """System/priority classification: responder flows (session
        replies for work already admitted on SOME node — the notary's
        commit-serving flows arrive this way) and classes marked
        `_system_flow = True`. Hospital retries never reach the
        admission seam at all (`_restore` re-enters below it)."""
        if is_responder:
            return True
        return flow is not None and getattr(
            type(flow), "_system_flow", False
        )

    def admit(self, flow=None, is_responder: bool = False) -> None:
        """Admit or raise NodeOverloadedError. Priority traffic NEVER
        raises (and is not charged against the bucket/cap) — the
        priority short-circuit runs FIRST so the dominant traffic class
        (every responder session message) skips the O(signals) overload
        sweep entirely."""
        if self.is_priority(flow, is_responder):
            self.priority.inc()
            return
        if self.overload is not None:
            self.overload.evaluate()
        if self.overload is not None and self.overload.shedding:
            self._reject(
                self.rejected_shedding, "node is shedding load",
                self.shed_retry_ms, flow,
            )
        if self.max_flows is not None and self.live_flows() >= self.max_flows:
            self._reject(
                self.rejected_cap,
                f"live-flow cap reached ({self.max_flows})",
                self.shed_retry_ms, flow,
            )
        if self.bucket is not None:
            ok, wait_s = self.bucket.try_acquire()
            if not ok:
                self._reject(
                    self.rejected_rate, "flow-start rate limit",
                    max(1, math.ceil(wait_s * 1000)), flow,
                )
        self.admitted.inc()

    def _reject(self, reason_counter, cause: str, retry_after_ms: int,
                flow) -> None:
        self.rejected.inc()
        reason_counter.inc()
        eventlog.emit(
            "warning", "admission", "flow start rejected",
            node=self._node, cause=cause,
            flow=type(flow).__name__ if flow is not None else None,
            retry_after_ms=retry_after_ms,
        )
        raise NodeOverloadedError(
            f"node overloaded: {cause}; retry after {retry_after_ms} ms",
            retry_after_ms=retry_after_ms,
        )

    def snapshot(self) -> Dict:
        out = {
            "max_flows": self.max_flows,
            "live_flows": self.live_flows(),
            "shed_retry_ms": self.shed_retry_ms,
            "admitted": self.admitted.value,
            "priority": self.priority.value,
            "rejected": self.rejected.value,
            "rejected_by_rate": self.rejected_rate.value,
            "rejected_by_cap": self.rejected_cap.value,
            "rejected_shedding": self.rejected_shedding.value,
        }
        if self.bucket is not None:
            out["rate"] = self.bucket.rate
            out["burst"] = self.bucket.burst
            out["tokens"] = round(self.bucket.tokens, 3)
        return out

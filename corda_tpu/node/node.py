"""Node wiring (reference `AbstractNode.kt:160-221` start sequence).

`AbstractNode` assembles: database → verifier service → ServiceHub → SMM →
notary service (if configured) → messaging handlers → checkpoint restore.
Transport and DB location come from `NodeConfiguration`, so the same class
backs MockNetwork test nodes (in-memory DB + pumped network) and standalone
nodes (file DB + broker transport).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, List, Optional

from ..core.crypto import crypto
from ..core.crypto.keys import KeyPair
from ..core.identity import Party
from ..verifier.batcher import SignatureBatcher
from ..verifier.service import (
    InMemoryTransactionVerifierService,
    OutOfProcessTransactionVerifierService,
)
from .database import CheckpointStorage, NodeDatabase
from .services import NetworkMapCache, ServiceHub
from .statemachine import StateMachineManager


@dataclass
class NodeConfiguration:
    """Reference `FullNodeConfiguration` / `reference.conf` defaults."""
    my_legal_name: str
    db_path: str = ":memory:"
    verifier_type: str = "InMemory"  # InMemory | OutOfProcess
    notary_type: Optional[str] = None  # None | simple | validating
    # entropy for the deterministic dev identity key (None -> random)
    identity_entropy: Optional[int] = None
    advertised_services: List[str] = field(default_factory=list)
    # validate every checkpoint at write time (full re-deserialize per
    # step — O(steps^2) per flow): on for tests/MockNetwork, off by
    # default in the standalone production process (node.conf
    # "dev_checkpoint_check": true re-enables)
    dev_checkpoint_check: bool = True


class AbstractNode:
    """A node: services + state machine + messaging, one legal identity."""

    def __init__(self, config: NodeConfiguration, messaging_factory, broker=None,
                 clock=None):
        """messaging_factory(me: Party) -> MessagingService.  `clock` is a
        zero-arg callable returning unix seconds (default time.time);
        simulations pass a utils.clocks.TestClock (reference TestClock)."""
        self.config = config
        if config.identity_entropy is not None:
            self._identity_key = crypto.entropy_to_keypair(config.identity_entropy)
        else:
            self._identity_key = crypto.generate_keypair()
        self.info = Party(config.my_legal_name, self._identity_key.public)
        self.database = NodeDatabase(config.db_path)
        self.checkpoint_storage = CheckpointStorage(self.database)
        self._broker = broker
        self.network = messaging_factory(self.info)
        verifier = self._make_transaction_verifier_service()
        self.services = ServiceHub(
            self.info, self.database, verifier, self._identity_key, clock=clock
        )
        self.smm = StateMachineManager(
            self.services, self.network, self.checkpoint_storage, self.info,
            dev_checkpoint_check=config.dev_checkpoint_check,
        )
        self.services._smm = self.smm
        if hasattr(self.network, "metrics"):
            # per-topic P2P handler timers land in the node's registry
            self.network.metrics = self.smm.metrics
        from .scheduler import SchedulerService

        self.scheduler = SchedulerService(self.database, self.services, self.smm)
        self.services.scheduler = self.scheduler
        self.notary_service = None
        if config.notary_type is not None:
            self._make_notary_service()
        self.started = False

    # -- assembly ------------------------------------------------------------

    def _make_transaction_verifier_service(self):
        if self.config.verifier_type == "OutOfProcess":
            if self._broker is None:
                raise ValueError("OutOfProcess verifier requires a broker")
            return OutOfProcessTransactionVerifierService(
                self._broker, self.config.my_legal_name
            )
        return InMemoryTransactionVerifierService(batcher=SignatureBatcher())

    def _make_notary_service(self):
        from .notary import SimpleNotaryService, ValidatingNotaryService

        if self.config.notary_type == "validating":
            self.notary_service = ValidatingNotaryService(self.services, self.info)
            if NetworkMapCache.VALIDATING_NOTARY_SERVICE not in self.config.advertised_services:
                self.config.advertised_services.append(
                    NetworkMapCache.VALIDATING_NOTARY_SERVICE
                )
        else:
            self.notary_service = SimpleNotaryService(self.services, self.info)
        self.services.notary_service = self.notary_service
        if NetworkMapCache.NOTARY_SERVICE not in self.config.advertised_services:
            self.config.advertised_services.append(NetworkMapCache.NOTARY_SERVICE)

    def start(self) -> "AbstractNode":
        """Install core flows, register self in the network map, restore
        checkpoints (reference AbstractNode.start + smm.start)."""
        from ..core import flows as _core_flows  # noqa: F401 — registers core flows
        from . import notary as _notary  # noqa: F401 — registers notary responders

        self.services.network_map_cache.add_node(
            self.info, self.config.advertised_services
        )
        self.smm.start()
        if hasattr(self.network, "start"):
            # Open the P2P pump only now that handlers are installed (a
            # message consumed before this point would be dropped).
            self.network.start()
        self.started = True
        return self

    def stop(self) -> None:
        if hasattr(self.network, "stop"):
            self.network.stop()
        svc = self.services.transaction_verifier_service
        if hasattr(svc, "stop"):
            svc.stop()
        self.database.close()

    # -- conveniences --------------------------------------------------------

    def start_flow(self, flow, *args_for_restore, **kw):
        return self.smm.start_flow(flow, *args_for_restore, **kw)

    def register_peer(self, peer_info: Party, advertised: Iterable[str] = ()) -> None:
        self.services.network_map_cache.add_node(peer_info, advertised)
        self.services.identity_service.register_identity(peer_info)

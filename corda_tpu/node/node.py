"""Node wiring (reference `AbstractNode.kt:160-221` start sequence).

`AbstractNode` assembles: database → verifier service → ServiceHub → SMM →
notary service (if configured) → messaging handlers → checkpoint restore.
Transport and DB location come from `NodeConfiguration`, so the same class
backs MockNetwork test nodes (in-memory DB + pumped network) and standalone
nodes (file DB + broker transport).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, List, Optional

from ..core.crypto import crypto
from ..core.crypto.keys import KeyPair
from ..core.identity import Party
from ..utils import eventlog, lockorder
from ..utils.metrics import MetricRegistry, MonitoringService
from ..verifier.batcher import SignatureBatcher
from ..verifier.service import (
    InMemoryTransactionVerifierService,
    OutOfProcessTransactionVerifierService,
)
from .database import CheckpointStorage, NodeDatabase
from .health import HealthTracker
from .services import NetworkMapCache, ServiceHub
from .statemachine import StateMachineManager


@dataclass
class NodeConfiguration:
    """Reference `FullNodeConfiguration` / `reference.conf` defaults."""
    my_legal_name: str
    db_path: str = ":memory:"
    verifier_type: str = "InMemory"  # InMemory | OutOfProcess
    notary_type: Optional[str] = None  # None | simple | validating
    # entropy for the deterministic dev identity key (None -> random)
    identity_entropy: Optional[int] = None
    advertised_services: List[str] = field(default_factory=list)
    # validate every checkpoint at write time (full re-deserialize per
    # step — O(steps^2) per flow): on for tests/MockNetwork, off by
    # default in the standalone production process (node.conf
    # "dev_checkpoint_check": true re-enables)
    dev_checkpoint_check: bool = True
    # Raft notary cluster membership (notary_type "raft-validating" /
    # "raft-simple"): {"name": cluster legal name, "index": my member
    # index, "members": [{"name": legal name, "entropy": int}, ...]}.
    # Deterministic member entropies let every member derive the full
    # member key set and the cluster's composite identity locally
    # (reference ServiceIdentityGenerator distributes the composite key
    # to the member dirs at deploy time).
    raft_cluster: Optional[dict] = None
    # PBFT notary cluster membership (notary_type "bft"): same block
    # shape as raft_cluster; needs >= 4 members (n >= 3f+1, f >= 1).
    bft_cluster: Optional[dict] = None
    # Operations endpoint (GET /metrics Prometheus exposition,
    # GET /traces/<id>, GET /traces/slow): None = off, 0 = ephemeral
    # port (read it back from node.ops_server.port), N = fixed port.
    ops_port: Optional[int] = None
    # Overload protection / admission control (docs/robustness.md):
    # token-bucket rate limit on NEW client flow starts (flows/s; None
    # falls back to CORDA_TPU_ADMISSION_RATE, unset = no rate gate),
    # bucket burst (default 2x rate), and the live-flow concurrency cap
    # (None -> CORDA_TPU_ADMISSION_MAX_FLOWS, unset = no cap). With
    # neither rate nor cap configured the admission seam is inert.
    admission_rate: Optional[float] = None
    admission_burst: Optional[float] = None
    admission_max_flows: Optional[int] = None
    # Horizontal scale (docs/sharding.md): `shards` partitions the
    # notary's uniqueness provider into N state-ref-keyed shards (one
    # consensus group each, two-phase cross-shard commits); None/0/1 =
    # the unsharded provider, byte-identical to every prior round.
    # `node_workers` runs the flow/verify hot path in M OS worker
    # processes behind this node's broker (standalone nodes only —
    # MockNetwork ignores it; wired by node/__main__.py + shardhost.py).
    shards: Optional[int] = None
    node_workers: Optional[int] = None
    # Multi-domain federation (docs/robustness.md §6): `domain` pins this
    # node to one named trust segment — it advertises the
    # `corda.domain.<name>` tag and its network-map fetches/subscriptions
    # are scoped to that domain plus domainless nodes and advertised
    # cross-domain gateways. `gateway` additionally advertises
    # `corda.gateway`, making the node visible from EVERY domain's
    # scoped map. Both default off: an unconfigured network advertises
    # no domain bytes and behaves byte-identically to a single-domain
    # deployment (kill switch).
    domain: Optional[str] = None
    gateway: bool = False


class AbstractNode:
    """A node: services + state machine + messaging, one legal identity."""

    def __init__(self, config: NodeConfiguration, messaging_factory, broker=None,
                 clock=None):
        """messaging_factory(me: Party) -> MessagingService.  `clock` is a
        zero-arg callable returning unix seconds (default time.time);
        simulations pass a utils.clocks.TestClock (reference TestClock)."""
        self.config = config
        # Multi-domain federation: fold the domain/gateway config into
        # the advertised service tags ONCE, before anything registers —
        # the tags then ride every existing registration path (network
        # map, MockNetwork fan-out, cluster identities) unchanged. An
        # unconfigured node appends nothing (kill switch).
        if config.domain is not None:
            tag = NetworkMapCache.DOMAIN_SERVICE_PREFIX + config.domain
            if tag not in config.advertised_services:
                config.advertised_services.append(tag)
        if (config.gateway
                and NetworkMapCache.GATEWAY_SERVICE
                not in config.advertised_services):
            config.advertised_services.append(NetworkMapCache.GATEWAY_SERVICE)
        # flight recorder: bridge every corda_tpu.* stdlib log record into
        # the process event log (idempotent), so component warnings that
        # predate the recorder still land in /logs
        eventlog.install_stdlib_bridge()
        # lifecycle + component health (served at /healthz and /readyz)
        self.health = HealthTracker()
        if config.identity_entropy is not None:
            self._identity_key = crypto.entropy_to_keypair(config.identity_entropy)
        else:
            self._identity_key = crypto.generate_keypair()
        self.info = Party(config.my_legal_name, self._identity_key.public)
        self.database = NodeDatabase(config.db_path)
        self.checkpoint_storage = CheckpointStorage(self.database)
        self._broker = broker
        self.network = messaging_factory(self.info)
        # ONE registry for the whole node (SMM flow metrics, P2P handler
        # timers, RPC timers, verifier Verification.* families) so the
        # ops endpoint's /metrics is a single coherent snapshot
        self.metrics = MetricRegistry()
        verifier = self._make_transaction_verifier_service()
        self.services = ServiceHub(
            self.info, self.database, verifier, self._identity_key, clock=clock
        )
        self.services.monitoring = MonitoringService(self.metrics)
        # RPC reachability: node_health() resolves the tracker through
        # the service hub (the RPC layer never sees the node object)
        self.services.health = self.health
        self.smm = StateMachineManager(
            self.services, self.network, self.checkpoint_storage, self.info,
            dev_checkpoint_check=config.dev_checkpoint_check,
        )
        self.services._smm = self.smm
        # Group-committed checkpoints (docs/perf-system.md round 20):
        # on async transports concurrent flows (lane threads + blocking
        # pool + RPC workers) write step-checkpoints concurrently, so
        # their sqlite commits coalesce into one commit cycle per drain
        # window — each writer still blocks until ITS write committed
        # (suspend durability unchanged). The deterministic in-memory
        # transport has no concurrency to coalesce and stays per-op.
        import os as _os

        if (
            getattr(self.network, "ASYNC_FLOW_DISPATCH", False)
            and _os.environ.get("CORDA_TPU_CP_GROUP_COMMIT", "1") != "0"
        ):
            self.checkpoint_storage.enable_group_commit(
                linger_ms=float(
                    _os.environ.get("CORDA_TPU_CP_LINGER_MS", 0.0)
                )
            )
        if hasattr(self.network, "metrics"):
            # per-topic P2P handler timers land in the node's registry
            self.network.metrics = self.smm.metrics
        from .scheduler import SchedulerService

        self.scheduler = SchedulerService(self.database, self.services, self.smm)
        self.services.scheduler = self.scheduler
        self.notary_service = None
        if config.notary_type is not None:
            self._make_notary_service()
        self.started = False
        self._setup_overload_protection()
        self._register_health_checks()
        self._register_backpressure_metrics()

    # -- assembly ------------------------------------------------------------

    def _setup_overload_protection(self) -> None:
        """The act-on-backpressure layer (docs/robustness.md): an
        overload state machine fed by the PR-3 gauges, plus — when
        admission is configured — an AdmissionController on the SMM's
        flow-start seam. The state machine always exists (it backs the
        `overload` health component and the Overload.State gauge); with
        default thresholds it only trips under real saturation."""
        import os as _os

        from .admission import AdmissionController, OverloadStateMachine

        self.overload = OverloadStateMachine(
            metrics=self.metrics, node_name=self.info.name,
        )
        net = self.network
        if hasattr(net, "queue_depth"):
            self.overload.add_signal(
                "p2p_queue_depth", net.queue_depth,
                high=float(_os.environ.get(
                    "CORDA_TPU_OVERLOAD_QDEPTH_HIGH", 5000
                )),
            )
        self.overload.add_signal(
            "blocking_backlog",
            lambda: (
                self.smm._blocking_executor._work_queue.qsize()
                if self.smm._blocking_executor is not None else 0
            ),
            high=float(_os.environ.get(
                "CORDA_TPU_OVERLOAD_BACKLOG_HIGH", 256
            )),
        )
        batcher = getattr(
            self.services.transaction_verifier_service, "_batcher", None
        )
        if batcher is not None:
            self.overload.add_signal(
                "batcher_queued_batches", lambda: batcher.queued_batches,
                high=float(_os.environ.get(
                    "CORDA_TPU_OVERLOAD_BATCHER_HIGH", 64
                )),
            )
        cfg = self.config
        env = _os.environ
        rate = (
            cfg.admission_rate if cfg.admission_rate is not None
            else (float(env["CORDA_TPU_ADMISSION_RATE"])
                  if env.get("CORDA_TPU_ADMISSION_RATE") else None)
        )
        max_flows = (
            cfg.admission_max_flows if cfg.admission_max_flows is not None
            else (int(float(env["CORDA_TPU_ADMISSION_MAX_FLOWS"]))
                  if env.get("CORDA_TPU_ADMISSION_MAX_FLOWS") else None)
        )
        self.admission = None
        if (rate and rate > 0) or (max_flows and max_flows > 0):
            self.admission = AdmissionController(
                rate=rate, burst=cfg.admission_burst, max_flows=max_flows,
                live_flows=lambda: self.smm.in_flight_count,
                overload=self.overload, metrics=self.metrics,
                node_name=self.info.name,
            )
            self.smm.admission = self.admission
            if max_flows and max_flows > 0:
                # live flows at the cap IS saturation: sustained bursts
                # flip the machine to shedding, and recovery (flows
                # draining under the low-water mark + the quiet dwell)
                # flips /readyz back to 200
                self.overload.add_signal(
                    "live_flows", lambda: self.smm.in_flight_count,
                    high=float(max_flows),
                )
        # shed telemetry: broker sheds land in Shed.* counters + the
        # flight recorder; the in-memory test transport exposes its
        # drop count as a gauge on the same family
        shed_dead = self.metrics.counter("Shed.DeadLettered")
        shed_rej = self.metrics.counter("Shed.RejectedSends")
        broker = getattr(net, "broker", None)
        if broker is not None and hasattr(broker, "on_shed"):
            def on_shed(queue: str, policy: str, _msg) -> None:
                (shed_dead if policy == "drop_oldest" else shed_rej).inc()
                eventlog.emit(
                    "warning", "messaging", "queue shed",
                    queue=queue, policy=policy, node=self.info.name,
                )

            broker.on_shed = on_shed
        inmem = getattr(net, "network", None)
        if inmem is not None and hasattr(inmem, "shed_counts"):
            self.metrics.gauge(
                "Shed.NetworkDropped",
                lambda: inmem.shed_counts.get(self.info.name, 0),
            )
        if self.notary_service is not None:
            provider = self.notary_service.uniqueness_provider
            if hasattr(provider, "sheds"):
                self.metrics.gauge(
                    "Shed.NotaryQueue", lambda: provider.sheds
                )

    def _register_health_checks(self) -> None:
        """Component checks behind /healthz and /readyz. Check bodies are
        cheap reads only — they run on ops-server request threads.

        Degradation checks (queue depth, blocking backlog) are
        DEBOUNCED: a breach must hold for CORDA_TPU_HEALTH_SUSTAIN_S
        (default 5 s) of continuous probing before readiness degrades —
        one spike at probe time must not make the load balancer yank a
        healthy node."""
        import os as _os

        from .health import SustainedBreach

        sustain_s = float(_os.environ.get("CORDA_TPU_HEALTH_SUSTAIN_S", 5.0))
        qdepth_degrade = float(
            _os.environ.get("CORDA_TPU_HEALTH_QDEPTH_DEGRADE", 5000)
        )
        msg_breach = SustainedBreach(sustain_s)
        sm_breach = SustainedBreach(sustain_s)

        def check_messaging():
            net = self.network
            detail = {}
            if hasattr(net, "queue_depth"):
                detail["queue_depth"] = net.queue_depth()
            broker = getattr(net, "broker", None)
            if broker is not None:
                # broker reachability: this node's inbound queue must exist
                detail["ok"] = broker.queue_exists(net.queue_name)
            elif hasattr(net, "running"):
                detail["ok"] = bool(net.running)
            return detail

        def check_verifier():
            svc = self.services.transaction_verifier_service
            if hasattr(svc, "healthcheck"):
                return svc.healthcheck()
            return {"backend": type(svc).__name__}

        def check_statemachine():
            detail = {"flows_in_flight": self.smm.in_flight_count}
            executor = self.smm._blocking_executor
            if executor is not None:
                detail["blocking_backlog"] = executor._work_queue.qsize()
                detail["blocking_workers"] = executor._max_workers
            return detail

        def check_backpressure():
            # READINESS-only (liveness=False): sustained inbound-queue
            # saturation or blocking-backlog saturation means this node
            # should stop receiving new routing — but it is overload,
            # not sickness: failing /healthz would invite an
            # orchestrator restart that destroys exactly the in-flight
            # work the backpressure is protecting
            detail = {}
            degraded = []
            net = self.network
            if hasattr(net, "queue_depth"):
                depth = net.queue_depth()
                detail["queue_depth"] = depth
                if msg_breach.observe(depth > qdepth_degrade):
                    degraded.append(
                        f"queue depth > {qdepth_degrade:g} for "
                        f"{msg_breach.breached_for_s:.1f}s"
                    )
            executor = self.smm._blocking_executor
            if executor is not None:
                # saturation = a backlog several times the worker count
                # (the threads mostly block on cluster commits; a deep
                # queue here is the upstream sign of a commit stall) —
                # sustained, so one probe-time burst cannot flip /readyz
                backlog = executor._work_queue.qsize()
                workers = executor._max_workers
                detail["blocking_backlog"] = backlog
                if sm_breach.observe(backlog >= workers * 8):
                    degraded.append(
                        "blocking backlog saturated for "
                        f"{sm_breach.breached_for_s:.1f}s"
                    )
            detail["ok"] = not degraded
            if degraded:
                detail["degraded"] = "; ".join(degraded)
            return detail

        def check_overload():
            # overload is an ADMISSION verdict, not a liveness one:
            # shedding flips /readyz 503 (stop routing new work here)
            # while /healthz stays 200 with this component's detail —
            # recovery (back to "normal" after the quiet dwell) flips
            # /readyz 200 again
            snap = self.overload.snapshot()
            return {"ok": snap["state"] == "normal", **snap}

        def check_hospital():
            # informational (never fails the probe): recovery activity
            # and ward pressure belong in the same operator view as the
            # component checks
            snap = self.smm.hospital.snapshot()
            return {
                "ok": True,
                "recovering": len(snap["recovering"]),
                "ward": len(snap["ward"]),
                "retries": snap["retries"],
            }

        self.health.register("messaging", check_messaging)
        self.health.register("verifier", check_verifier)
        self.health.register("statemachine", check_statemachine)
        self.health.register("hospital", check_hospital, readiness=False)
        self.health.register("overload", check_overload, liveness=False)
        self.health.register("backpressure", check_backpressure,
                             liveness=False)

        if self.notary_service is not None:
            def check_notary():
                detail = {"type": self.config.notary_type}
                raft = getattr(self, "raft_node", None)
                if raft is not None:
                    detail["role"] = raft.role
                    detail["leader"] = raft.leader_id
                    # a member that knows no leader cannot serve commits
                    detail["ok"] = (
                        raft.role == "leader" or raft.leader_id is not None
                    )
                replica = getattr(self, "bft_replica", None)
                if replica is not None:
                    detail["view"] = replica.view
                    detail["primary"] = replica.primary
                return detail

            self.health.register("notary", check_notary)

    def _register_backpressure_metrics(self) -> None:
        """Queue-depth / occupancy / device gauges on the node registry —
        the "which queue is backing up" half of the flight recorder."""
        net = self.network
        if hasattr(net, "queue_depth"):
            self.metrics.gauge("P2P.QueueDepth", net.queue_depth)
        svc = self.services.transaction_verifier_service
        batcher = getattr(svc, "_batcher", None)
        if batcher is not None:
            batcher.bind_metrics(self.metrics)
        self.metrics.gauge("Flows.BlockingBacklog", lambda: (
            self.smm._blocking_executor._work_queue.qsize()
            if self.smm._blocking_executor is not None else 0
        ))
        # JAX device telemetry: resolved lazily and WITHOUT importing jax
        # (a gauge read must never trigger backend initialization)
        import sys as _sys

        from ..utils import profiling as _profiling

        def jax_backend():
            jax = _sys.modules.get("jax")
            if jax is None:
                return "uninitialized"
            try:
                return jax.default_backend()
            except Exception:
                return "uninitialized"

        self.metrics.gauge("Jax.Backend", jax_backend)
        self.metrics.gauge(
            "Jax.CompileCount", lambda: _profiling.dispatch_totals()[1]
        )
        # per-shape-bucket ed25519 compile counts, always-on: a
        # recompile storm in production names the churning bucket here
        # instead of only in a bench run's stage_timings (the label
        # suffix renders as Prometheus labels on the same family)
        for bucket in _profiling.ED25519_BUCKET_LABELS:
            self.metrics.gauge(
                f"Jax.CompileCount{{bucket={bucket}}}",
                lambda b=bucket: _profiling.compile_count(
                    "ed25519.batch_shape", b
                ),
            )
        self.metrics.gauge(
            "Jax.DispatchCount", lambda: _profiling.dispatch_totals()[0]
        )
        self.metrics.gauge(
            "Jax.DispatchWallSeconds",
            lambda: round(_profiling.dispatch_totals()[2], 6),
        )

        # kernel op-budget attestation (ops/opbudget.py): −1 until this
        # process traced the kernels (bench --gate, tier-1 gate, or
        # GET /opbudget?compute=1) — read via sys.modules so a scrape
        # can never trigger the jax import, let alone the trace
        def opbudget_gauge(kernel: str, metric: str):
            def read():
                mod = _sys.modules.get("corda_tpu.ops.opbudget")
                return -1.0 if mod is None else mod.gauge_value(
                    kernel, metric
                )

            return read

        for kernel in _profiling.OPBUDGET_KERNELS:
            self.metrics.gauge(
                f"Kernel.OpBudget.U32MulElemsPerSig{{kernel={kernel}}}",
                opbudget_gauge(kernel, "u32_mul_elems_per_sig"),
            )
            self.metrics.gauge(
                f"Kernel.OpBudget.FieldMulsPerSig{{kernel={kernel}}}",
                opbudget_gauge(kernel, "field_mul_equiv_per_sig"),
            )

        # device-plane kernel flight ledger (docs/observability.md
        # "Device plane"): ring depth, cumulative padded vs REAL rows,
        # overall padding occupancy, and per-kernel roofline attainment
        # (-1 until that kernel dispatched — attainment is MEASURED).
        # All reads are jax-free plain-python (utils/profiling), and by
        # riding the registry they flow into the /metrics/history ring.
        self.metrics.gauge(
            "Kernel.Ledger.Records",
            lambda: _profiling.ledger_gauges()["records"],
        )
        self.metrics.gauge(
            "Kernel.Ledger.Rows",
            lambda: _profiling.ledger_gauges()["rows"],
        )
        self.metrics.gauge(
            "Kernel.Ledger.RealRows",
            lambda: _profiling.ledger_gauges()["real_rows"],
        )
        self.metrics.gauge(
            "Kernel.Ledger.OccupancyPct",
            lambda: _profiling.ledger_gauges()["occupancy_pct"],
        )
        for kernel in _profiling.LEDGER_KERNELS:
            self.metrics.gauge(
                f"Kernel.Attainment{{kernel={kernel}}}",
                lambda k=kernel: _profiling.attainment_value(k),
            )

        # bank-side flow hot path (docs/perf-system.md round 20): lane
        # executor occupancy, vault selection-cache effectiveness, and
        # checkpoint group-commit coalescing — the three families a
        # flow-throughput regression triages by
        lanes = getattr(net, "_lanes", None) or getattr(
            getattr(net, "network", None), "lane_executor", None
        )
        if lanes is not None:
            self.metrics.gauge("Flows.Lanes", lambda: lanes.n_lanes)
            self.metrics.gauge(
                "Flows.LaneDispatched",
                lambda: lanes.stats()["dispatched"],
            )
            self.metrics.gauge(
                "Flows.LanePending", lambda: lanes.pending()
            )
            self.metrics.gauge(
                "Flows.LaneErrors", lambda: lanes.stats()["errors"]
            )
        vault = self.services.vault_service
        self.metrics.gauge(
            "Vault.CacheSize", lambda: len(vault._decoded)
        )
        self.metrics.gauge(
            "Vault.CacheHits", lambda: vault.stats["cache_hits"]
        )
        self.metrics.gauge(
            "Vault.CacheDecodes", lambda: vault.stats["decodes"]
        )
        self.metrics.gauge(
            "Vault.CacheGenerationFlushes",
            lambda: vault.stats["generation_flushes"],
        )

        def _cp_stat(key: str):
            def read():
                snap = self.checkpoint_storage.group_commit_stats
                return -1.0 if snap is None else snap[key]

            return read

        self.metrics.gauge(
            "Checkpoint.GroupCommitBatches", _cp_stat("batches")
        )
        self.metrics.gauge("Checkpoint.GroupCommitOps", _cp_stat("ops"))
        self.metrics.gauge(
            "Checkpoint.GroupCommitMaxBatch", _cp_stat("max_batch")
        )

        # sampling profiler (utils/sampler.py): capture activity for the
        # /profile endpoint and RPC node_profile
        from ..utils import sampler as _sampler

        self.metrics.gauge("Profiler.Captures", _sampler.captures_total)
        self.metrics.gauge("Profiler.Samples", _sampler.samples_total)
        self.metrics.gauge("Profiler.Active", _sampler.active_captures)

        # native-extension availability (corda_tpu.native): 1 loaded,
        # 0 fell back to pure Python (the eventlog names why), -1 load
        # never attempted in this process — the gauge read must not
        # trigger a compile, so it only reflects recorded status
        def native_gauge(ext: str):
            def read():
                from .. import native as _native_pkg

                entry = _native_pkg.availability().get(ext)
                if entry is None:
                    return -1.0
                return 1.0 if entry["available"] else 0.0

            return read

        from .. import native as _native_pkg

        for _ext in _native_pkg.EXTENSIONS:
            self.metrics.gauge(
                f"Native.Available{{ext={_ext}}}", native_gauge(_ext)
            )

    def _make_transaction_verifier_service(self):
        if self.config.verifier_type == "OutOfProcess":
            if self._broker is None:
                raise ValueError("OutOfProcess verifier requires a broker")
            return OutOfProcessTransactionVerifierService(
                self._broker, self.config.my_legal_name,
                metrics=self.metrics,
            )
        return InMemoryTransactionVerifierService(batcher=SignatureBatcher())

    def _make_notary_service(self):
        from .notary import (
            SimpleNotaryService,
            ValidatingNotaryService,
            default_uniqueness_provider,
        )

        if (self.config.notary_type or "").startswith("raft"):
            self._make_raft_notary_service()
            return
        if self.config.notary_type == "bft":
            self._make_bft_notary_service()
            return
        # partitioned commit log when configured (node.conf "shards" /
        # create_node(shards=) beats CORDA_TPU_SHARDS; None defers to it)
        provider = default_uniqueness_provider(
            self.database, shards=self.config.shards
        )
        if self.config.notary_type == "validating":
            self.notary_service = ValidatingNotaryService(
                self.services, self.info, uniqueness_provider=provider
            )
            if NetworkMapCache.VALIDATING_NOTARY_SERVICE not in self.config.advertised_services:
                self.config.advertised_services.append(
                    NetworkMapCache.VALIDATING_NOTARY_SERVICE
                )
        else:
            self.notary_service = SimpleNotaryService(
                self.services, self.info, uniqueness_provider=provider
            )
        self.services.notary_service = self.notary_service
        if NetworkMapCache.NOTARY_SERVICE not in self.config.advertised_services:
            self.config.advertised_services.append(NetworkMapCache.NOTARY_SERVICE)

    def _make_bft_notary_service(self):
        """One member of a PBFT notary cluster as a REAL OS process
        (reference BFTNonValidatingNotaryService over BFT-SMaRt,
        `BFTSMaRt.kt:79-276`, whose replicas/clients talk over their own
        sockets; here PBFT traffic rides the node's P2P messaging —
        BFT_TOPIC over the broker/bridges, including self-delivery
        through the member's own inbound queue so every replica entry
        point runs on the messaging pump thread, which is what makes the
        single-threaded replica state machine safe).

        Each member runs one replica AND one client; a commit broadcasts
        the putall to all n replicas and accepts once f+1 DISTINCT
        replicas return identical verdicts carrying valid signatures
        over the tx id — those f+1 signatures fulfil the cluster's
        f+1-threshold composite identity (validated by NotaryClientFlow
        like any notary signature set)."""
        import threading as _threading

        from ..core.crypto import crypto as _crypto
        from ..core.identity import Party
        from ..core.serialization.codec import deserialize, serialize
        from .bft import BFT_TOPIC, BFTClient, BFTReplica
        from .cluster_identity import generate_service_identity
        from .notary import BFTUniquenessProvider, SimpleNotaryService

        cfg = self.config.bft_cluster
        if not cfg:
            raise ValueError("notary_type bft requires a bft_cluster block")
        members = cfg["members"]
        n = len(members)
        my_index = int(cfg["index"])
        f = (n - 1) // 3
        parties = [
            Party(m["name"], _crypto.entropy_to_keypair(m["entropy"]).public)
            for m in members
        ]
        self.cluster_party = generate_service_identity(
            cfg["name"], [p.owning_key for p in parties], threshold=f + 1
        )
        name_of = {i: p for i, p in enumerate(parties)}
        index_of = {p.name: i for i, p in enumerate(parties)}
        leaf_keys = {k.encoded for k in self.cluster_party.owning_key.keys}

        def bft_send(dst_index: int, msg: dict) -> None:
            try:
                self.network.send(name_of[dst_index], BFT_TOPIC,
                                  serialize(msg))
            except Exception:
                pass  # peer route not up yet: PBFT tolerates loss

        def transport(dst: int, payload: bytes) -> None:
            bft_send(dst, {"k": "m", "s": my_index, "p": payload})

        def reply_fn(client_id: str, request_id: str, result) -> None:
            dst = index_of.get(client_id)
            if dst is not None:
                bft_send(dst, {"k": "r", "s": my_index,
                               "rid": request_id, "res": result})

        def sign_tx(tx_id_bytes: bytes):
            return self.services.key_management_service.sign(
                tx_id_bytes, self.info.owning_key
            )

        # Replica prepare-vote signing identities: cordform generates a
        # RANDOM per-member seed at deploy time, written only to that
        # member's own config, with every member's PUBLIC key shared via
        # the cluster block ("signing_pub" per member). Entropy-derived
        # seeds are the dev fallback for hand-written configs — like the
        # shared dev identity entropies themselves, they are derivable by
        # anyone who can read the cluster block, so they authenticate
        # members against outsiders but not against each other.
        import hashlib as _hashlib

        from ..core.crypto import ed25519_math as _edm

        def _dev_seed(entropy) -> bytes:
            return _hashlib.sha512(
                b"corda-tpu-bft-replica:%d" % int(entropy)
            ).digest()[:32]

        my_seed_hex = cfg.get("signing_seed")
        my_seed = (
            bytes.fromhex(my_seed_hex)
            if my_seed_hex
            else _dev_seed(members[my_index]["entropy"])
        )
        my_pub_hex = members[my_index].get("signing_pub")
        if my_pub_hex and _edm.public_from_seed(my_seed) != bytes.fromhex(
            my_pub_hex
        ):
            # e.g. a stale node.conf after a redeploy regenerated seeds:
            # this replica's votes would be silently rejected by peers,
            # degrading fault tolerance with no error anywhere — fail fast
            raise ValueError(
                "bft_cluster signing_seed does not match this member's "
                "signing_pub in the members list (stale config after a "
                "redeploy?)"
            )
        replica_pubs = {
            i: (
                bytes.fromhex(m["signing_pub"])
                if m.get("signing_pub")
                else _edm.public_from_seed(_dev_seed(m["entropy"]))
            )
            for i, m in enumerate(members)
        }
        # Aggregating vote mode (docs/bls-aggregation.md): opt in with
        # bft_cluster {"vote_scheme": "bls"}. Per-member "bls_pub" +
        # "bls_pop" (hex) ride the shared members list and this member's
        # own "bls_secret" its private config. Dev keys are derived ONLY
        # when the whole cluster block carries no BLS key material at
        # all (a pure dev deployment, same trust caveat as the dev
        # ed25519 seeds) — a PARTIALLY keyed block (one member's pub
        # missing mid-rollout) must reach BFTReplica incomplete so its
        # documented ed25519 fallback fires, never be silently filled
        # with publicly-derivable dev keys that would weaken the
        # Byzantine threshold.
        bls_kwargs = {}
        if cfg.get("vote_scheme") == "bls":
            from ..core.crypto import bls_math as _bls_math

            any_explicit = bool(cfg.get("bls_secret")) or any(
                m.get("bls_pub") or m.get("bls_pop") for m in members
            )
            if not any_explicit:
                from .bft import dev_bls_committee

                dev_sks, dev_pubs, dev_pops = dev_bls_committee(n)
                bls_kwargs = {
                    "bls_signing_key": dev_sks[my_index],
                    "replica_bls_pubs": dev_pubs,
                    "replica_bls_pops": dev_pops,
                }
            else:
                my_sk = (
                    int(cfg["bls_secret"], 16)
                    if cfg.get("bls_secret") else None
                )
                pubs = {
                    i: bytes.fromhex(m["bls_pub"])
                    for i, m in enumerate(members) if m.get("bls_pub")
                }
                my_pub = pubs.get(my_index)
                if (
                    my_sk is not None and my_pub is not None
                    and _bls_math.sk_to_pk(my_sk) != my_pub
                ):
                    # same fail-fast as the ed25519 signing_seed check
                    # above: signing votes every peer drops (via the
                    # aggregate-failure fallback) silently degrades
                    # fault tolerance with no error anywhere
                    raise ValueError(
                        "bft_cluster bls_secret does not match this "
                        "member's bls_pub in the members list (stale "
                        "config after a redeploy?)"
                    )
                bls_kwargs = {
                    "bls_signing_key": my_sk,
                    "replica_bls_pubs": pubs,
                    "replica_bls_pops": {
                        i: bytes.fromhex(m["bls_pop"])
                        for i, m in enumerate(members) if m.get("bls_pop")
                    },
                }
        apply_fn, snapshot_fn, restore_fn, meta_store = (
            BFTUniquenessProvider.make_replica_state(
                self.database, sign_tx_fn=sign_tx
            )
        )
        replica = BFTReplica(
            my_index, n, transport, apply_fn, reply_fn,
            signing_seed=my_seed,
            replica_pubs=replica_pubs,
            snapshot_fn=snapshot_fn,
            restore_fn=restore_fn,
            meta_store=meta_store,
            **bls_kwargs,
        )
        if cfg.get("view_timeout") is not None:
            # per-deployment view-change timer (tests use a short one so
            # a primary kill fails over inside the client's wait window)
            vt = float(cfg["view_timeout"])
            if vt <= 0:
                # a non-positive timer would fire a view change on every
                # tick whenever any request is pending — perpetual churn
                raise ValueError(
                    f"bft_cluster view_timeout must be > 0, got {vt}"
                )
            replica.VIEW_TIMEOUT = vt
        self.bft_replica = replica
        # the replica state machine is single-threaded by design (unlike
        # RaftNode, which locks internally): the pump handler and the
        # view-change ticker serialize through this lock
        self._bft_lock = lockorder.make_rlock("AbstractNode._bft_lock")

        def validate_reply(command, result) -> bool:
            # conflict-free verdicts count toward the f+1 quorum only
            # with a valid cluster-leaf signature over the tx id
            if not isinstance(result, dict) or result.get("conflicts"):
                return True
            tx_hex = (command or {}).get("tx_id")
            if tx_hex is None:
                return True
            sig = result.get("tx_sig")
            if sig is None:
                return False
            try:
                return (
                    sig.by.encoded in leaf_keys
                    and sig.is_valid(bytes.fromhex(tx_hex))
                )
            except Exception:
                return False

        client = BFTClient(
            self.info.name, n,
            lambda rid, req: bft_send(rid, {"k": "q", "req": req}),
            reply_validator=validate_reply,
        )
        self._bft_client = client

        def on_bft_message(sender, payload) -> None:
            # The replica/reply index binds to the AUTHENTICATED channel
            # sender, never the self-declared msg["s"]: one peer must not
            # be able to vote as every replica (quorum dedup in
            # BFTClient/BFTReplica counts one vote per identity).
            sender_idx = index_of.get(getattr(sender, "name", None))
            msg = deserialize(payload)
            kind = msg.get("k")
            if kind == "m":
                if sender_idx is None or msg.get("s") != sender_idx:
                    return
                with self._bft_lock:
                    replica.on_message(sender_idx, msg["p"])
            elif kind == "q":
                if sender_idx is None:
                    return  # only cluster members may inject commands
                with self._bft_lock:
                    replica.on_request(msg["req"])
            elif kind == "r":
                if sender_idx is None or msg.get("s") != sender_idx:
                    return
                client.on_reply(sender_idx, msg["rid"], msg["res"])

        self.network.add_handler(BFT_TOPIC, on_bft_message)
        if hasattr(self.network, "also_serve"):
            self.network.also_serve(self.cluster_party.name)

        # reference parity: the BFT notary is non-validating
        self.notary_service = SimpleNotaryService(
            self.services, self.info,
            uniqueness_provider=BFTUniquenessProvider(client),
        )
        self.services.notary_service = self.notary_service
        self._cluster_services = [NetworkMapCache.NOTARY_SERVICE]
        self.services.network_map_cache.add_node(
            self.cluster_party, list(self._cluster_services)
        )
        self.services.identity_service.register_identity(self.cluster_party)

    def _make_raft_notary_service(self):
        """One member of a Raft notary cluster (reference
        RaftValidatingNotaryService over Copycat,
        `RaftUniquenessProvider.kt:71-156`): Raft traffic rides the
        node's own P2P messaging (RAFT_TOPIC over bridges), the
        uniqueness log replicates through this node's database, and the
        cluster presents a threshold-1 composite identity any member's
        signature fulfils."""
        from ..core.crypto import crypto as _crypto
        from ..core.identity import Party
        from .cluster_identity import generate_service_identity
        from .notary import (
            RaftUniquenessProvider,
            SimpleNotaryService,
            ValidatingNotaryService,
        )
        from .raft import RAFT_TOPIC, RaftNode

        cfg = self.config.raft_cluster
        if not cfg:
            raise ValueError(
                "notary_type raft-* requires a raft_cluster config block"
            )
        members = cfg["members"]
        my_index = int(cfg["index"])
        ids = [f"r{i}" for i in range(len(members))]
        parties = [
            Party(m["name"], _crypto.entropy_to_keypair(m["entropy"]).public)
            for m in members
        ]
        self.cluster_party = generate_service_identity(
            cfg["name"], [p.owning_key for p in parties], threshold=1
        )
        party_by_id = dict(zip(ids, parties))
        id_by_name = {p.name: rid for rid, p in party_by_id.items()}

        def transport(dst: str, payload: bytes) -> None:
            try:
                self.network.send(party_by_id[dst], RAFT_TOPIC, payload)
            except Exception:
                pass  # peer route not up yet: Raft tolerates loss

        raft = RaftNode(
            ids[my_index], [r for r in ids if r != ids[my_index]],
            transport,
            lambda cmd: self._raft_provider.apply(cmd),
            db=self.database, seed=my_index,
        )
        self.raft_node = raft
        self._raft_provider = RaftUniquenessProvider(
            raft, self.database, forwarding_retry=True
        )

        def on_raft_message(sender, payload):
            rid = id_by_name.get(getattr(sender, "name", None))
            if rid is not None:
                raft.on_message(rid, payload)

        self.network.add_handler(RAFT_TOPIC, on_raft_message)
        # messages addressed to the CLUSTER identity land here too
        if hasattr(self.network, "also_serve"):
            self.network.also_serve(self.cluster_party.name)

        validating = self.config.notary_type == "raft-validating"
        cls = ValidatingNotaryService if validating else SimpleNotaryService
        self.notary_service = cls(
            self.services, self.info, uniqueness_provider=self._raft_provider
        )
        self.services.notary_service = self.notary_service
        # Notary services are advertised by the CLUSTER identity only —
        # a member's own entry must not show up as a second notary in
        # notary_identities().
        self._cluster_services = [NetworkMapCache.NOTARY_SERVICE]
        if validating:
            self._cluster_services.insert(
                0, NetworkMapCache.VALIDATING_NOTARY_SERVICE
            )
        if self.config.domain is not None:
            # the CLUSTER identity carries the member's domain so the
            # scoped map and notaries_in_domain() route to it
            self._cluster_services.append(
                NetworkMapCache.DOMAIN_SERVICE_PREFIX + self.config.domain
            )
        self.services.network_map_cache.add_node(
            self.cluster_party, list(self._cluster_services)
        )
        self.services.identity_service.register_identity(self.cluster_party)

    def cluster_registration_signer(self):
        """(party, advertised_services, signer) for NetworkMapClient's
        extra_identities: the member signs cluster entries with its leaf
        key wrapped as a threshold-satisfying composite signature."""
        from ..core.crypto import crypto as _crypto
        from ..core.crypto.composite import CompositeSignaturesWithKeys

        def signer(data: bytes) -> bytes:
            raw = _crypto.do_sign(self._identity_key.private, data)
            return CompositeSignaturesWithKeys(
                ((self.info.owning_key, raw),)
            ).serialize()

        return (
            self.cluster_party,
            list(self._cluster_services),
            signer,
        )

    def start(self) -> "AbstractNode":
        """Install core flows, register self in the network map, restore
        checkpoints (reference AbstractNode.start + smm.start)."""
        from ..core import flows as _core_flows  # noqa: F401 — registers core flows
        from . import notary as _notary  # noqa: F401 — registers notary responders

        self.services.network_map_cache.add_node(
            self.info, self.config.advertised_services
        )
        self.smm.start()
        # Surface crash-interrupted notary changes (journal entries left
        # by a coordinator death mid-2PC). The checkpointed flow itself
        # resumes through the SMM; this is the operator-visible signal
        # that NotaryChangeRecoveryFlow has work if the flow is gone.
        try:
            from .notary_change import pending_notary_changes

            pending = pending_notary_changes(self.services)
            if pending:
                eventlog.emit(
                    "warn", "notary", "incomplete notary changes found",
                    node=self.info.name, count=len(pending),
                    tx_ids=[tx[:16] for tx, _ in pending],
                )
        # a corrupt journal must not block node start; recovery re-reads
        # it on demand
        except Exception:  # lint: allow(swallow)
            pass
        if hasattr(self.network, "start"):
            # Open the P2P pump only now that handlers are installed (a
            # message consumed before this point would be dropped).
            self.network.start()
        if getattr(self, "raft_node", None) is not None:
            self._start_raft_ticker()
        if getattr(self, "bft_replica", None) is not None:
            self._start_bft_ticker()
        if self.config.ops_port is not None:
            from ..utils.timeseries import MetricsHistory, history_enabled
            from .opsserver import OpsServer

            # metric time-series ride along with the ops endpoint (a
            # node nobody can scrape has nobody to keep history for);
            # CORDA_TPU_METRICS_HISTORY=0 keeps the node poller-free
            if history_enabled():
                self.metrics_history = MetricsHistory(
                    self.smm.metrics, name=self.info.name
                ).start()
                # the RPC layer never sees the node object; hang the
                # history off the smm like hospital/metrics so
                # node_metrics_history() can reach it
                self.smm.metrics_history = self.metrics_history
            # tracer deliberately unpinned: the endpoint resolves the
            # process tracer per request, like the span producers do
            self.ops_server = OpsServer(
                self.smm.metrics, health=self.health,
                hospital=self.smm.hospital,
                admission=self.admission, overload=self.overload,
                history=getattr(self, "metrics_history", None),
                port=self.config.ops_port,
            )
        self.started = True
        self.health.mark_serving()
        eventlog.emit(
            "info", "node", "node started", node=self.info.name,
            notary=self.config.notary_type or "none",
        )
        return self

    #: Raft abstract time units per wall-clock second: the RaftNode's
    #: ELECTION_TIMEOUT of (10, 20) units becomes 2.5-5 s and heartbeats
    #: go every 750 ms. Deliberately conservative: heartbeats ride the
    #: P2P bridges, whose latency spikes well past 100 ms when member
    #: processes share cores with flow execution — an aggressive scale
    #: (tried at 20 units/s) caused continuous leader churn under load.
    RAFT_TIME_SCALE = 4.0

    def _start_raft_ticker(self) -> None:
        import threading as _threading
        import time as _time

        self._raft_stop = _threading.Event()

        def run():
            while not self._raft_stop.wait(0.05):
                try:
                    self.raft_node.tick(_time.monotonic() * self.RAFT_TIME_SCALE)
                except Exception:
                    pass  # a tick must never kill the ticker

        self._raft_ticker = _threading.Thread(
            target=run, name=f"raft-tick-{self.info.name}", daemon=True
        )
        self._raft_ticker.start()

    def _start_bft_ticker(self) -> None:
        """View-change timer: a dead primary must not stall the cluster.
        Ticks serialize with the pump handler through _bft_lock (the
        replica state machine is single-threaded by design)."""
        import threading as _threading
        import time as _time

        self._bft_stop = _threading.Event()

        def run():
            while not self._bft_stop.wait(0.25):
                try:
                    with self._bft_lock:
                        self.bft_replica.tick(_time.monotonic())
                except Exception:
                    pass  # a tick must never kill the ticker

        self._bft_ticker = _threading.Thread(
            target=run, name=f"bft-tick-{self.info.name}", daemon=True
        )
        self._bft_ticker.start()

    def drain(self) -> None:
        """Flip /healthz and /readyz to 503 (the load balancer's cue to
        stop routing here) WITHOUT tearing anything down — callable ahead
        of stop() for graceful rollouts; stop() calls it implicitly."""
        self.health.mark_draining()
        eventlog.emit("info", "node", "node draining", node=self.info.name)

    def stop(self) -> None:
        # drain FIRST: while components shut down below, any /healthz
        # probe that still lands answers 503 instead of a half-true 200
        if self.health.state not in ("draining", "stopped"):
            self.drain()
        if getattr(self, "ops_server", None) is not None:
            self.ops_server.stop()
            self.ops_server = None
        if getattr(self, "metrics_history", None) is not None:
            self.metrics_history.stop()
            self.metrics_history = None
        if getattr(self, "_raft_stop", None) is not None:
            self._raft_stop.set()
            self._raft_ticker.join(timeout=2)
        if getattr(self, "_bft_stop", None) is not None:
            self._bft_stop.set()
            self._bft_ticker.join(timeout=2)
        if hasattr(self.network, "stop"):
            self.network.stop()
        if self.smm._blocking_executor is not None:
            self.smm._blocking_executor.shutdown(
                wait=False, cancel_futures=True
            )
        # cancel scheduled hospital retries: a readmission firing into a
        # torn-down node would replay flows against closed services
        self.smm.hospital.close()
        svc = self.services.transaction_verifier_service
        if hasattr(svc, "stop"):
            svc.stop()
        self.database.close()
        self.health.mark_stopped()
        eventlog.emit("info", "node", "node stopped", node=self.info.name)

    # -- conveniences --------------------------------------------------------

    def start_flow(self, flow, *args_for_restore, **kw):
        return self.smm.start_flow(flow, *args_for_restore, **kw)

    def register_peer(self, peer_info: Party, advertised: Iterable[str] = ()) -> None:
        self.services.network_map_cache.add_node(peer_info, advertised)
        self.services.identity_service.register_identity(peer_info)

"""BFT state-machine replication for the notary commit log.

Reference: `node/.../services/transactions/BFTSMaRt.kt` wraps the
BFT-SMaRt library (ServiceProxy.invokeOrdered + DefaultRecoverable
replicas, `BFTSMaRt.kt:79-276`).  The TPU build implements the PBFT core
itself: 3f+1 replicas, pre-prepare/prepare/commit phases, 2f+1 quorums,
view change on primary timeout.  The replicated operation is the same
`putall` uniqueness command the Raft provider applies, and the client
accepts a result once f+1 replicas return identical signed verdicts
(reference: response extractor aggregating >= requiredReplies signatures).

Same determinism contract as raft.py: `tick(now)` drives timeouts,
`on_message` handles peer traffic; tests step the cluster explicitly.

Scope: normal-case consensus + view change with prepared-certificate
carry-over; checkpoint/garbage-collection of the PBFT log is not
implemented (the log is bounded by ledger growth, like the Raft provider).
"""
from __future__ import annotations

import hashlib
import threading
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..core.serialization.codec import deserialize, serialize

BFT_TOPIC = "platform.bft"


def _digest(request: dict) -> bytes:
    return hashlib.sha256(serialize(request)).digest()


class BFTReplica:
    """One PBFT replica.

    transport: send(peer_id, payload); apply_fn(command) -> result applied
    exactly once per committed sequence number, in order, on every replica.
    reply_fn(client_id, request_id, result) delivers the signed verdict
    back to the requesting client.
    """

    VIEW_TIMEOUT = 30.0

    def __init__(
        self,
        replica_id: int,
        n_replicas: int,
        transport: Callable[[int, bytes], None],
        apply_fn: Callable[[dict], object],
        reply_fn: Callable[[str, str, object], None],
    ):
        assert n_replicas >= 4, "BFT needs n >= 3f+1 with f >= 1"
        self.id = replica_id
        self.n = n_replicas
        self.f = (n_replicas - 1) // 3
        self.transport = transport
        self.apply_fn = apply_fn
        self.reply_fn = reply_fn
        self.view = 0
        self.next_seq = 0  # primary's sequence counter
        self.last_executed = -1
        # seq -> state
        self.requests: Dict[bytes, dict] = {}  # digest -> request
        self.pre_prepares: Dict[int, bytes] = {}  # seq -> digest
        # votes keyed (view, seq, digest): PBFT quorums are per-view
        self.prepares: Dict[Tuple[int, int, bytes], Set[int]] = {}
        self.commits: Dict[Tuple[int, int, bytes], Set[int]] = {}
        # carried-over prepared claims during view change: (seq, digest) -> voters
        self._vc_prepared_claims: Dict[Tuple[int, bytes], Set[int]] = {}
        self.committed: Dict[int, bytes] = {}  # seq -> digest (quorum reached)
        self.executed: Set[int] = set()
        # view change
        self.view_change_votes: Dict[int, Set[int]] = {}  # new view -> voters
        self._pending_since: Optional[float] = None
        self._now = 0.0

    # -- identity helpers ----------------------------------------------------

    @property
    def primary(self) -> int:
        return self.view % self.n

    @property
    def is_primary(self) -> bool:
        return self.id == self.primary

    def _broadcast(self, msg: dict) -> None:
        payload = serialize(msg)
        for peer in range(self.n):
            if peer != self.id:
                try:
                    self.transport(peer, payload)
                except Exception:
                    pass

    # -- client request entry ------------------------------------------------

    def on_request(self, request: dict) -> None:
        """A client request arrived (any replica can receive it; non-primary
        forwards to the primary and starts its complaint timer)."""
        d = _digest(request)
        self.requests[d] = request
        if self.is_primary:
            if d in self.pre_prepares.values():
                return  # duplicate
            seq = self.next_seq
            self.next_seq += 1
            self.pre_prepares[seq] = d
            self._broadcast({
                "kind": "pre_prepare", "view": self.view, "seq": seq,
                "digest": d, "request": request,
            })
            self._record_prepare(seq, d, self.id)
        else:
            try:
                self.transport(self.primary, serialize({
                    "kind": "forward", "request": request,
                }))
            except Exception:
                pass
            if self._pending_since is None:
                self._pending_since = self._now

    # -- message handling ----------------------------------------------------

    def on_message(self, sender: int, payload: bytes) -> None:
        msg = deserialize(payload)
        kind = msg["kind"]
        if kind == "forward":
            self.on_request(msg["request"])
        elif kind == "pre_prepare":
            self._on_pre_prepare(sender, msg)
        elif kind == "prepare":
            if msg["view"] == self.view and self._seq_in_window(msg["seq"]):
                self._record_prepare(msg["seq"], msg["digest"], sender)
        elif kind == "commit":
            if msg["view"] == self.view and self._seq_in_window(msg["seq"]):
                self._record_commit(msg["seq"], msg["digest"], sender)
        elif kind == "view_change":
            self._on_view_change(sender, msg)
        elif kind == "new_view":
            self._on_new_view(sender, msg)

    # Bound on how far ahead of execution the log may run: caps state growth
    # against a faulty peer spraying arbitrary (seq, digest) votes.
    MAX_INFLIGHT = 10_000

    def _seq_in_window(self, seq: int) -> bool:
        return self.last_executed < seq <= self.last_executed + self.MAX_INFLIGHT or seq <= self.last_executed

    def _on_pre_prepare(self, sender: int, msg: dict) -> None:
        if msg["view"] != self.view or sender != self.primary:
            return
        seq, d = msg["seq"], msg["digest"]
        if not self._seq_in_window(seq):
            return
        if seq in self.pre_prepares and self.pre_prepares[seq] != d:
            return  # equivocation: ignore (view change will handle)
        self.pre_prepares[seq] = d
        self.requests[d] = msg["request"]
        self._pending_since = None  # primary is alive
        self._broadcast({
            "kind": "prepare", "view": self.view, "seq": seq, "digest": d,
        })
        self._record_prepare(seq, d, sender)
        self._record_prepare(seq, d, self.id)

    def _record_prepare(self, seq: int, d: bytes, voter: int) -> None:
        votes = self.prepares.setdefault((self.view, seq, d), set())
        if voter in votes:
            return
        votes.add(voter)
        # prepared: pre-prepare + 2f prepares (incl. our own vote counting)
        if len(votes) >= 2 * self.f + 1 and self.pre_prepares.get(seq) == d:
            ckey = (self.view, seq, d)
            if self.id not in self.commits.get(ckey, set()):
                self._broadcast({
                    "kind": "commit", "view": self.view, "seq": seq,
                    "digest": d,
                })
                self._record_commit(seq, d, self.id)

    def _record_commit(self, seq: int, d: bytes, voter: int) -> None:
        votes = self.commits.setdefault((self.view, seq, d), set())
        if voter in votes:
            return
        votes.add(voter)
        if len(votes) >= 2 * self.f + 1:
            self.committed[seq] = d
            self._execute_ready()

    def _execute_ready(self) -> None:
        while self.last_executed + 1 in self.committed:
            seq = self.last_executed + 1
            d = self.committed[seq]
            request = self.requests.get(d)
            if request is None:
                return  # wait for the request body
            self.last_executed = seq
            if seq not in self.executed:
                self.executed.add(seq)
                result = self.apply_fn(request["command"])
                self.reply_fn(
                    request["client_id"], request["request_id"], result
                )

    # -- view change ---------------------------------------------------------

    def tick(self, now: float) -> None:
        self._now = now
        if (
            self._pending_since is not None
            and now - self._pending_since >= self.VIEW_TIMEOUT
        ):
            self._pending_since = None
            self._start_view_change(self.view + 1)

    def _start_view_change(self, new_view: int) -> None:
        votes = self.view_change_votes.setdefault(new_view, set())
        votes.add(self.id)
        self._broadcast({
            "kind": "view_change", "new_view": new_view,
            # prepared claims: (seq, digest, request) we locally prepared.
            # Receivers only honor a claim corroborated by f+1 distinct
            # replicas (at least one honest), so a single Byzantine replica
            # cannot inject commands. (Production hardening: signed
            # prepared certificates per PBFT.)
            "prepared": [
                [seq, d, self.requests.get(d)]
                for (view, seq, d), v in self.prepares.items()
                if len(v) >= 2 * self.f + 1 and self.pre_prepares.get(seq) == d
            ],
        })
        # our own claims count toward the f+1 corroboration
        for (view, seq, d), v in self.prepares.items():
            if len(v) >= 2 * self.f + 1 and self.pre_prepares.get(seq) == d:
                self._vc_prepared_claims.setdefault((seq, d), set()).add(self.id)
        self._maybe_enter_view(new_view)

    def _on_view_change(self, sender: int, msg: dict) -> None:
        new_view = msg["new_view"]
        if new_view <= self.view:
            return
        votes = self.view_change_votes.setdefault(new_view, set())
        votes.add(sender)
        for seq, d, request in msg["prepared"]:
            if request is None or _digest(request) != d:
                continue  # malformed claim
            claims = self._vc_prepared_claims.setdefault((seq, d), set())
            claims.add(sender)
            # carry over only once f+1 replicas (>= one honest) corroborate
            if len(claims) >= self.f + 1:
                self.requests[d] = request
                self.pre_prepares.setdefault(seq, d)
        # join the view change once f+1 replicas demand it
        if self.id not in votes and len(votes) >= self.f + 1:
            self._start_view_change(new_view)
        self._maybe_enter_view(new_view)

    def _maybe_enter_view(self, new_view: int) -> None:
        votes = self.view_change_votes.get(new_view, set())
        if len(votes) >= 2 * self.f + 1 and new_view > self.view:
            self.view = new_view
            self._pending_since = None
            if self.is_primary:
                self.next_seq = max(self.pre_prepares, default=self.last_executed) + 1
                # re-propose carried-over uncommitted work, then fresh queue
                self._broadcast({"kind": "new_view", "view": self.view})
                for seq, d in sorted(self.pre_prepares.items()):
                    if seq > self.last_executed and d in self.requests:
                        self._broadcast({
                            "kind": "pre_prepare", "view": self.view,
                            "seq": seq, "digest": d,
                            "request": self.requests[d],
                        })
                        self._record_prepare(seq, d, self.id)
                # pending client requests that never got a seq
                for d, request in list(self.requests.items()):
                    if d not in self.pre_prepares.values():
                        self.on_request(request)

    def _on_new_view(self, sender: int, msg: dict) -> None:
        if msg["view"] > self.view and sender == msg["view"] % self.n:
            self.view = msg["view"]
            self._pending_since = None


class BFTClient:
    """Client proxy: broadcast the command to every replica, accept the
    result once f+1 identical replies arrive (reference BFTSMaRt.Client
    response extractor)."""

    def __init__(self, client_id: str, n_replicas: int,
                 send_to_replica: Callable[[int, dict], None]):
        self.client_id = client_id
        self.n = n_replicas
        self.f = (n_replicas - 1) // 3
        self._send = send_to_replica
        self._pending: Dict[str, Future] = {}
        self._replies: Dict[str, List[object]] = {}
        self._counter = 0
        self._lock = threading.Lock()

    def submit(self, command: dict) -> Future:
        with self._lock:
            self._counter += 1
            request_id = f"{self.client_id}:{self._counter}"
            fut: Future = Future()
            self._pending[request_id] = fut
            self._replies[request_id] = []
        fut.request_id = request_id  # lets callers forget() on timeout
        request = {
            "client_id": self.client_id, "request_id": request_id,
            "command": command,
        }
        for r in range(self.n):
            try:
                self._send(r, request)
            except Exception:
                pass
        return fut

    def forget(self, request_id: str) -> None:
        """Drop a timed-out request so late replies cannot leak memory."""
        with self._lock:
            self._pending.pop(request_id, None)
            self._replies.pop(request_id, None)

    def on_reply(self, request_id: str, result: object) -> None:
        with self._lock:
            fut = self._pending.get(request_id)
            if fut is None or fut.done():
                return
            replies = self._replies[request_id]
            replies.append(result)
            blob = serialize(result)
            matching = sum(1 for r in replies if serialize(r) == blob)
            if matching >= self.f + 1:
                self._pending.pop(request_id)
                self._replies.pop(request_id)
                fut.set_result(result)

"""BFT state-machine replication for the notary commit log.

Reference: `node/.../services/transactions/BFTSMaRt.kt` wraps the
BFT-SMaRt library (ServiceProxy.invokeOrdered + DefaultRecoverable
replicas, `BFTSMaRt.kt:79-276`).  The TPU build implements the PBFT core
itself: 3f+1 replicas, pre-prepare/prepare/commit phases, 2f+1 quorums,
view change on primary timeout.  The replicated operation is the same
`putall` uniqueness command the Raft provider applies, and the client
accepts a result once f+1 replicas return identical signed verdicts
(reference: response extractor aggregating >= requiredReplies signatures).

Same determinism contract as raft.py: `tick(now)` drives timeouts,
`on_message` handles peer traffic; tests step the cluster explicitly.

Scope: normal-case consensus, view change with prepared-certificate
carry-over, and PBFT stable checkpoints (every CHECKPOINT_INTERVAL
executions a replica broadcasts a signed digest of its applied state; a
2f+1 certificate makes the checkpoint stable and truncates every log
structure at or below it — the paper's §4.3 garbage collection, playing
the role of BFT-SMaRt's DefaultRecoverable snapshot/log-truncation cycle,
reference `BFTSMaRt.kt:150-276`). Replica memory is bounded by
CHECKPOINT_INTERVAL + in-flight work instead of ledger growth.
"""
from __future__ import annotations

import hashlib
import logging
import threading
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..core.serialization.codec import deserialize, serialize
from ..utils import lockorder

logger = logging.getLogger(__name__)

BFT_TOPIC = "platform.bft"


def _digest(request: dict) -> bytes:
    return hashlib.sha256(serialize(request)).digest()


def dev_signing_seed(replica_id: int) -> bytes:
    """Deterministic DEV-ONLY replica signing seed.

    Lets tests and single-operator clusters skip key distribution; any
    party can derive these, so production clusters MUST pass their own
    `signing_seed` + `replica_pubs` to BFTReplica.
    """
    return hashlib.sha256(b"corda-tpu-bft-dev-key:%d" % replica_id).digest()


def dev_bls_committee(n_replicas: int):
    """Deterministic DEV-ONLY BLS vote-key material for an aggregating
    committee: ({id: secret scalar}, {id: 48B pubkey}, {id: PoP bytes}).
    Same caveat as dev_signing_seed — production clusters distribute
    their own keys and proofs of possession at the key ceremony."""
    from ..core.crypto import bls_math

    sks = {
        i: bls_math.keygen(
            hashlib.sha256(b"corda-tpu-bft-dev-bls:%d" % i).digest()
        )
        for i in range(n_replicas)
    }
    pubs = {i: bls_math.sk_to_pk(sk) for i, sk in sks.items()}
    pops = {i: bls_math.pop_prove(sk) for i, sk in sks.items()}
    return sks, pubs, pops


def _prepare_statement(view: int, seq: int, digest: bytes) -> bytes:
    """Canonical byte statement a prepare signature covers."""
    return b"bft-prepare\x00" + serialize({"v": view, "s": seq, "d": digest})


def _checkpoint_statement(seq: int, digest: bytes) -> bytes:
    """Canonical byte statement a checkpoint signature covers (view-free:
    PBFT checkpoints certify executed state, not view membership)."""
    return b"bft-checkpoint\x00" + serialize({"s": seq, "d": digest})


class BFTReplica:
    """One PBFT replica.

    transport: send(peer_id, payload); apply_fn(command) -> result applied
    exactly once per committed sequence number, in order, on every replica.
    reply_fn(client_id, request_id, result) delivers the signed verdict
    back to the requesting client.
    """

    VIEW_TIMEOUT = 30.0

    def __init__(
        self,
        replica_id: int,
        n_replicas: int,
        transport: Callable[[int, bytes], None],
        apply_fn: Callable[[dict], object],
        reply_fn: Callable[[str, str, object], None],
        signing_seed: Optional[bytes] = None,
        replica_pubs: Optional[Dict[int, bytes]] = None,
        snapshot_fn: Optional[Callable[[], bytes]] = None,
        restore_fn: Optional[Callable[[bytes], None]] = None,
        meta_store=None,
        bls_signing_key: Optional[int] = None,
        replica_bls_pubs: Optional[Dict[int, bytes]] = None,
        replica_bls_pops: Optional[Dict[int, bytes]] = None,
    ):
        """snapshot_fn/restore_fn: dump/load the applied state machine
        (the uniqueness map) for catch-up state transfer; meta_store: a
        KVStore persisting (last_executed, view) so a RESTARTED replica
        resumes from its own durable state instead of seq 0 (reference
        BFTSMaRt.Replica's DefaultRecoverable snapshot get/install,
        `BFTSMaRt.kt:150-276`).

        bls_signing_key / replica_bls_pubs / replica_bls_pops: the
        AGGREGATING vote mode (PAPERS arXiv 2302.00418). When this
        replica holds a BLS secret AND every committee member has a BLS
        pubkey with a VALID proof of possession, prepare votes are
        BLS-signed, per-vote verification is deferred, and commit
        certification becomes ONE aggregate check per block (plus one
        aggregated certificate per view-change claim) instead of 2f+1
        per-vote verifies. Any member lacking a key — or shipping a bad
        PoP — drops the whole committee back to per-vote Ed25519: a
        split committee signing under two schemes could never assemble
        either certificate."""
        assert n_replicas >= 4, "BFT needs n >= 3f+1 with f >= 1"
        from ..core.crypto import ed25519_math

        self.id = replica_id
        self.n = n_replicas
        self.f = (n_replicas - 1) // 3
        self.transport = transport
        self.apply_fn = apply_fn
        self.reply_fn = reply_fn
        # Replica signing identity: prepare votes are ed25519-signed so a
        # view-change message can carry a self-certifying prepared
        # certificate (2f+1 verifiable prepare signatures) per PBFT.
        self._signing_seed = signing_seed or dev_signing_seed(replica_id)
        self.replica_pubs = replica_pubs or {
            i: ed25519_math.public_from_seed(dev_signing_seed(i))
            for i in range(n_replicas)
        }
        # -- aggregating vote mode ------------------------------------------
        self.vote_scheme = "ed25519"
        self._bls_sk = None
        self.replica_bls_pubs = dict(replica_bls_pubs or {})
        if bls_signing_key is not None:
            missing = [
                i for i in range(n_replicas)
                if i not in self.replica_bls_pubs
            ]
            bad_pops = []
            if not missing:
                from ..core.crypto import crypto as _crypto

                for i, pub in self.replica_bls_pubs.items():
                    pop = (replica_bls_pops or {}).get(i)
                    # rogue-key gate: a vote key joins the aggregate
                    # committee only with a valid proof of possession
                    if pop is None or not _crypto.bls_register_key(pub, pop):
                        bad_pops.append(i)
            if missing or bad_pops:
                logger.warning(
                    "%s: BLS vote keys incomplete (missing=%s bad_pop=%s); "
                    "falling back to per-vote ed25519", self.id,
                    missing, bad_pops,
                )
            else:
                self.vote_scheme = "bls"
                self._bls_sk = bls_signing_key
        # telemetry for the aggregate-vs-naive claim: how many aggregate
        # checks and how many individual vote verifies this replica ran
        self.agg_checks = 0
        self.vote_verifies = 0
        # (view, seq, digest) quorums whose vote set already passed an
        # aggregate check (no re-check when trailing votes arrive)
        self._certified: Set[Tuple[int, int, bytes]] = set()
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        self._meta = meta_store
        self.view = 0
        self.next_seq = 0  # primary's sequence counter
        self.last_executed = -1
        if meta_store is not None:
            blob = meta_store.get(b"bft_meta")
            if blob is not None:
                meta = deserialize(blob)
                # the umap already reflects every execution <= this seq
                # (apply happens before the meta save; re-apply of the
                # boundary entry is idempotent)
                self.last_executed = int(meta["last_executed"])
                self.view = int(meta["view"])
                self.stable_seq = int(meta.get("stable_seq", -1))
                # digest + cert ride along so a restarted replica holds a
                # USABLE stable checkpoint (digest comparison in
                # _record_checkpoint, future checkpoint proofs), not a
                # bare seq with empty evidence
                self.stable_digest = bytes(meta.get("stable_digest", b""))
                self.stable_cert = {
                    int(k): v
                    for k, v in dict(meta.get("stable_cert", {})).items()
                }
                self._diverged = bool(meta.get("diverged", False))
                # a restarted PRIMARY must not reassign sequence numbers
                # its peers already hold pre-prepares for (the
                # equivocation guard would stall every request for a
                # whole VIEW_TIMEOUT) — resume from the persisted counter
                self.next_seq = max(
                    int(meta.get("next_seq", 0)), self.last_executed + 1
                )
        # stable checkpoint: the highest seq with a 2f+1 checkpoint
        # certificate; every log structure is truncated at/below it
        if not hasattr(self, "stable_seq"):
            self.stable_seq = -1
        if not hasattr(self, "stable_digest"):
            self.stable_digest = b""
        if not hasattr(self, "stable_cert"):
            self.stable_cert: Dict[int, bytes] = {}  # voter -> checkpoint sig
        # our OWN checkpoint digests by seq (GC'd below stable): compared
        # against certified digests so a silently corrupted replica
        # detects its divergence instead of executing on wrong state
        self._own_ckpt_digests: Dict[int, bytes] = {}
        if not hasattr(self, "_diverged"):
            self._diverged = False
        # (seq, state digest) -> {voter: signature}
        self.checkpoint_votes: Dict[Tuple[int, bytes], Dict[int, bytes]] = {}
        # seq -> state
        self.requests: Dict[bytes, dict] = {}  # digest -> request
        self.pre_prepares: Dict[int, bytes] = {}  # seq -> digest
        # votes keyed (view, seq, digest): PBFT quorums are per-view
        self.prepares: Dict[Tuple[int, int, bytes], Set[int]] = {}
        self.commits: Dict[Tuple[int, int, bytes], Set[int]] = {}
        # signed prepare votes backing the prepared certificates:
        # (view, seq, digest) -> {voter: signature}
        self.prepare_sigs: Dict[Tuple[int, int, bytes], Dict[int, bytes]] = {}
        self.committed: Dict[int, bytes] = {}  # seq -> digest (quorum reached)
        self.executed: Set[int] = set()
        # view change
        self.view_change_votes: Dict[int, Set[int]] = {}  # new view -> voters
        self._pending_since: Optional[float] = None
        self._now = 0.0
        # catch-up state transfer (see _maybe_request_state)
        self._gap_since: Optional[float] = None
        self._state_resps: Dict[int, tuple] = {}  # sender -> (n, digest, dump)

    # -- identity helpers ----------------------------------------------------

    @property
    def primary(self) -> int:
        return self.view % self.n

    @property
    def is_primary(self) -> bool:
        return self.id == self.primary

    def _broadcast(self, msg: dict) -> None:
        payload = serialize(msg)
        for peer in range(self.n):
            if peer != self.id:
                try:
                    self.transport(peer, payload)
                except Exception:
                    pass

    # -- prepare-vote signatures ---------------------------------------------

    def _sign_prepare(self, view: int, seq: int, d: bytes) -> bytes:
        stmt = _prepare_statement(view, seq, d)
        if self.vote_scheme == "bls":
            from ..core.crypto import bls_math

            return bls_math.sign(self._bls_sk, stmt)
        from ..core.crypto import ed25519_math

        return ed25519_math.sign(self._signing_seed, stmt)

    def _verify_replica_sig(
        self, voter: int, statement: bytes, sig: object
    ) -> bool:
        from ..core.crypto import ed25519_math

        pub = self.replica_pubs.get(voter)
        if pub is None or not isinstance(sig, (bytes, bytearray)):
            return False
        try:
            return ed25519_math.verify(pub, statement, bytes(sig))
        except Exception:
            return False

    def _verify_vote(self, voter: int, statement: bytes, sig: object) -> bool:
        """Individually verify ONE prepare vote (the per-vote path: the
        only path in ed25519 mode, the FALLBACK path in bls mode)."""
        self.vote_verifies += 1
        if self.vote_scheme == "bls":
            from ..core.crypto import bls_math

            pub = self.replica_bls_pubs.get(voter)
            if pub is None or not isinstance(sig, (bytes, bytearray)):
                return False
            try:
                return bls_math.verify(pub, bytes(sig), statement)
            except Exception:
                return False
        return self._verify_replica_sig(voter, statement, sig)

    def _verify_prepare_sig(
        self, voter: int, view: int, seq: int, d: bytes, sig: object
    ) -> bool:
        return self._verify_vote(
            voter, _prepare_statement(view, seq, d), sig
        )

    def _accept_prepare_sig(
        self, voter: int, view: int, seq: int, d: bytes, sig: object
    ) -> bool:
        """Receipt-time vote admission. Ed25519 mode verifies eagerly
        (the classic per-vote cost); bls mode only shape-checks and
        DEFERS cryptographic verification to the one aggregate check at
        quorum (_certify_quorum) — the aggregation win."""
        if self.vote_scheme == "bls":
            return isinstance(sig, (bytes, bytearray)) and len(sig) == 96
        return self._verify_prepare_sig(voter, view, seq, d, sig)

    def _certify_quorum(self, view: int, seq: int, d: bytes) -> bool:
        """ONE aggregate check certifying a 2f+1 prepare quorum (bls
        mode). On failure — some Byzantine vote in the set — fall back
        to individual verification, drop the bad votes, and certify only
        if an honest 2f+1 remains."""
        ckey = (view, seq, d)
        if ckey in self._certified:
            return True
        from ..core.crypto import crypto as _crypto

        sigs = self.prepare_sigs.get(ckey, {})
        stmt = _prepare_statement(view, seq, d)
        voters = sorted(sigs)
        self.agg_checks += 1
        try:
            agg = _crypto.aggregate([sigs[v] for v in voters])
            ok = _crypto.aggregate_verify(
                [self.replica_bls_pubs[v] for v in voters], stmt, agg
            )
        except Exception:
            ok = False
        if ok:
            self._certified.add(ckey)
            return True
        good = {v: s for v, s in sigs.items()
                if self._verify_vote(v, stmt, s)}
        dropped = set(sigs) - set(good)
        logger.warning(
            "%s: aggregate vote check failed at seq %d; dropped invalid "
            "votes from %s", self.id, seq, sorted(dropped),
        )
        self.prepare_sigs[ckey] = good
        self.prepares[ckey] = set(good)
        if len(good) >= 2 * self.f + 1:
            self._certified.add(ckey)
            return True
        return False

    # -- client request entry ------------------------------------------------

    def on_request(self, request: dict) -> None:
        """A client request arrived (any replica can receive it; non-primary
        forwards to the primary and starts its complaint timer)."""
        d = _digest(request)
        self.requests[d] = request
        if self.is_primary:
            if d in self.pre_prepares.values():
                return  # duplicate
            seq = self.next_seq
            self.next_seq += 1
            self._save_meta()  # a restarted primary must not reuse seqs
            self.pre_prepares[seq] = d
            psig = self._sign_prepare(self.view, seq, d)
            self._broadcast({
                "kind": "pre_prepare", "view": self.view, "seq": seq,
                "digest": d, "request": request, "psig": psig,
            })
            self._record_prepare(seq, d, self.id, psig)
        else:
            try:
                self.transport(self.primary, serialize({
                    "kind": "forward", "request": request,
                }))
            except Exception:
                pass
            if self._pending_since is None:
                self._pending_since = self._now

    # -- message handling ----------------------------------------------------

    def on_message(self, sender: int, payload: bytes) -> None:
        msg = deserialize(payload)
        kind = msg["kind"]
        if kind == "forward":
            self.on_request(msg["request"])
        elif kind == "pre_prepare":
            self._on_pre_prepare(sender, msg)
        elif kind == "prepare":
            if (
                msg["view"] == self.view
                and self._seq_in_window(msg["seq"])
                and self._accept_prepare_sig(
                    sender, msg["view"], msg["seq"], msg["digest"],
                    msg.get("psig"),
                )
            ):
                self._record_prepare(
                    msg["seq"], msg["digest"], sender, msg["psig"]
                )
            elif (
                msg["view"] > self.view
                and self._verify_prepare_sig(
                    sender, msg["view"], msg["seq"], msg["digest"],
                    msg.get("psig"),
                )
            ):
                # signature-verified traffic from a HIGHER view: the
                # cluster moved on while we were down — evidence for the
                # state-transfer trigger (we cannot join view V+1's
                # quorums, so the normal gap detector never fires)
                self._ahead_view_evidence = max(
                    getattr(self, "_ahead_view_evidence", -1), msg["view"]
                )
        elif kind == "commit":
            if msg["view"] == self.view and self._seq_in_window(msg["seq"]):
                self._record_commit(msg["seq"], msg["digest"], sender)
        elif kind == "view_change":
            self._on_view_change(sender, msg)
        elif kind == "new_view":
            self._on_new_view(sender, msg)
        elif kind == "checkpoint":
            self._on_checkpoint(sender, msg)
        elif kind == "state_req":
            self._on_state_req(sender, msg)
        elif kind == "state_resp":
            self._on_state_resp(sender, msg)

    # Bound on how far ahead of execution the log may run: caps state growth
    # against a faulty peer spraying arbitrary (seq, digest) votes.
    MAX_INFLIGHT = 10_000

    def _seq_in_window(self, seq: int) -> bool:
        # PBFT water marks: below the stable checkpoint the log is GONE —
        # accepting votes there would regrow the structures GC just freed
        return self.stable_seq < seq <= self.last_executed + self.MAX_INFLIGHT

    def _on_pre_prepare(self, sender: int, msg: dict) -> None:
        if msg["view"] != self.view or sender != self.primary:
            return
        seq, d = msg["seq"], msg["digest"]
        if not self._seq_in_window(seq):
            return
        if seq in self.pre_prepares and self.pre_prepares[seq] != d:
            return  # equivocation: ignore (view change will handle)
        if _digest(msg["request"]) != d:
            # the digest IS the commit key: accepting a body that does
            # not hash to it would let a Byzantine primary send the SAME
            # digest with DIFFERENT bodies to different replicas — one
            # quorum, divergent executions
            return
        if not self._accept_prepare_sig(
            sender, msg["view"], seq, d, msg.get("psig")
        ):
            return  # unsigned/forged pre-prepare
        self.pre_prepares[seq] = d
        self.requests[d] = msg["request"]
        self._pending_since = None  # primary is alive
        own = self._sign_prepare(self.view, seq, d)
        self._broadcast({
            "kind": "prepare", "view": self.view, "seq": seq, "digest": d,
            "psig": own,
        })
        self._record_prepare(seq, d, sender, msg["psig"])
        self._record_prepare(seq, d, self.id, own)

    def _record_prepare(self, seq: int, d: bytes, voter: int, sig: bytes) -> None:
        votes = self.prepares.setdefault((self.view, seq, d), set())
        if voter in votes:
            return
        votes.add(voter)
        self.prepare_sigs.setdefault((self.view, seq, d), {})[voter] = sig
        # prepared: pre-prepare + 2f prepares (incl. our own vote counting)
        if len(votes) >= 2 * self.f + 1 and self.pre_prepares.get(seq) == d:
            if self.vote_scheme == "bls" and not self._certify_quorum(
                self.view, seq, d
            ):
                return  # bad votes dropped; quorum must refill
            ckey = (self.view, seq, d)
            if self.id not in self.commits.get(ckey, set()):
                self._broadcast({
                    "kind": "commit", "view": self.view, "seq": seq,
                    "digest": d,
                })
                self._record_commit(seq, d, self.id)

    def _record_commit(self, seq: int, d: bytes, voter: int) -> None:
        votes = self.commits.setdefault((self.view, seq, d), set())
        if voter in votes:
            return
        votes.add(voter)
        if len(votes) >= 2 * self.f + 1:
            self.committed[seq] = d
            self._execute_ready()

    def _execute_ready(self) -> None:
        if self._diverged:
            return  # corrupt local state: no execution until re-synced
        while self.last_executed + 1 in self.committed:
            seq = self.last_executed + 1
            d = self.committed[seq]
            request = self.requests.get(d)
            if request is None:
                return  # wait for the request body
            self.last_executed = seq
            if seq not in self.executed:
                self.executed.add(seq)
                # apply + meta save commit as ONE sqlite cycle (the meta
                # store exposes its db's transaction context); a crash
                # between them is also safe — re-apply is idempotent
                txn = getattr(
                    getattr(self._meta, "db", None), "transaction", None
                )
                if txn is not None:
                    with txn():
                        result = self.apply_fn(request["command"])
                        self._save_meta()
                else:
                    result = self.apply_fn(request["command"])
                    self._save_meta()
                self.reply_fn(
                    request["client_id"], request["request_id"], result
                )
                if (
                    self.snapshot_fn is not None
                    and seq > 0
                    and seq % self.CHECKPOINT_INTERVAL == 0
                ):
                    self._emit_checkpoint(seq)

    # -- stable checkpoints + log GC (PBFT §4.3) ------------------------------

    #: executions between checkpoint broadcasts; replica log memory is
    #: O(CHECKPOINT_INTERVAL + in-flight), not O(ledger)
    CHECKPOINT_INTERVAL = 128

    def _emit_checkpoint(self, seq: int) -> None:
        """Broadcast a signed digest of the applied state at `seq`.
        snapshot_fn must be deterministic across replicas (all replicas
        applied the same commands in the same order, so a canonical
        serialization of the state map digests identically)."""
        from ..core.crypto import ed25519_math

        d = hashlib.sha256(self.snapshot_fn()).digest()
        sig = ed25519_math.sign(
            self._signing_seed, _checkpoint_statement(seq, d)
        )
        self._broadcast({
            "kind": "checkpoint", "seq": seq, "digest": d, "csig": sig,
        })
        self._own_ckpt_digests[seq] = d
        self._record_checkpoint(seq, d, self.id, sig)

    def _verify_checkpoint_sig(
        self, voter: int, seq: int, d: bytes, sig: object
    ) -> bool:
        return self._verify_replica_sig(
            voter, _checkpoint_statement(seq, d), sig
        )

    def _on_checkpoint(self, sender: int, msg: dict) -> None:
        seq, d = msg.get("seq"), msg.get("digest")
        if not isinstance(seq, int) or seq <= self.stable_seq:
            return
        # a Byzantine non-bytes digest would otherwise raise inside
        # serialize() (before the sig check) or as a dict key, and the
        # exception would escape on_message into the cluster message pump
        if not isinstance(d, bytes) or len(d) != 32:
            return
        if seq > self.last_executed + self.MAX_INFLIGHT:
            return  # vote spray from a faulty peer: cap state growth
        if not self._verify_checkpoint_sig(sender, seq, d, msg.get("csig")):
            return
        self._record_checkpoint(seq, d, sender, msg["csig"])

    def _record_checkpoint(self, seq: int, d: bytes, voter: int, sig: bytes) -> None:
        # one live vote per (voter, seq): a faulty replica validly signing
        # unlimited DISTINCT digests for one seq must not grow the vote
        # table per message (its newest vote simply replaces the old one)
        for (s, dd), vv in list(self.checkpoint_votes.items()):
            if s == seq and dd != d and voter in vv:
                del vv[voter]
                if not vv:
                    del self.checkpoint_votes[(s, dd)]
        votes = self.checkpoint_votes.setdefault((seq, d), {})
        votes[voter] = sig
        # 2f+1 signatures over the same (seq, state digest): at least f+1
        # honest replicas hold this state — it can never be rolled back,
        # so everything at or below it is garbage
        if len(votes) >= 2 * self.f + 1 and seq > self.stable_seq:
            self.stable_seq = seq
            self.stable_digest = d
            self.stable_cert = dict(votes)
            self._gc_log(seq)
            self._save_meta()
            logger.debug(
                "%s: stable checkpoint at seq %d, log truncated", self.id, seq
            )
            # divergence detection: 2f+1 replicas certified a digest for a
            # seq we already executed — if OUR snapshot at that seq says
            # otherwise, our local state is silently corrupt (disk rot,
            # bad restore). Executing further compounds the damage; halt
            # execution and re-sync via state transfer instead.
            own = self._own_ckpt_digests.get(seq)
            if own is not None and own != d and self.last_executed >= seq:
                # halt REGARDLESS of restore_fn: a halted replica is a
                # crashed one (the cluster tolerates f of those); a
                # corrupt replica signing wrong verdicts is worse
                logger.error(
                    "%s: LOCAL STATE DIVERGED at seq %d (own digest %s != "
                    "certified %s) — halting execution%s",
                    self.id, seq, own.hex()[:16], d.hex()[:16],
                    ", requesting state" if self.restore_fn else
                    " (no restore_fn: manual recovery required)",
                )
                self._diverged = True
                self._save_meta()  # the halt must survive a crash
                if self.restore_fn is not None:
                    self._state_resps.clear()
                    self._broadcast({"kind": "state_req", "have": -1})
                return
            if self.stable_seq > self.last_executed:
                # the cluster certified state BEYOND our execution, and the
                # GC above just discarded the committed/missing-body
                # evidence the gap detector relied on — fetch state NOW,
                # not whenever future traffic happens to re-arm the timer
                self._state_resps.clear()
                self._broadcast(
                    {"kind": "state_req", "have": self.last_executed}
                )

    def _gc_log(self, n: int) -> None:
        """Discard every log structure at or below seq `n` (the paper's
        garbage collection). Request bodies survive only while some live
        pre-prepare references them or they are still unsequenced."""
        dropped_digests = {
            d for s, d in self.pre_prepares.items() if s <= n
        }
        for seq in [s for s in self.pre_prepares if s <= n]:
            del self.pre_prepares[seq]
        live = set(self.pre_prepares.values())
        for d in dropped_digests - live:
            self.requests.pop(d, None)
        for store in (self.prepares, self.commits, self.prepare_sigs):
            for key in [k for k in store if k[1] <= n]:
                del store[key]
        self._certified = {k for k in self._certified if k[1] > n}
        for seq in [s for s in self.committed if s <= n]:
            del self.committed[seq]
        self.executed = {s for s in self.executed if s > n}
        for key in [k for k in self.checkpoint_votes if k[0] <= n]:
            del self.checkpoint_votes[key]
        # keep the boundary digest (s == n): the divergence check in
        # _record_checkpoint compares it right after this GC runs
        for seq in [s for s in self._own_ckpt_digests if s < n]:
            del self._own_ckpt_digests[seq]

    # -- durable meta + catch-up state transfer -------------------------------

    def _save_meta(self) -> None:
        if self._meta is not None:
            self._meta.put(b"bft_meta", serialize({
                "last_executed": self.last_executed, "view": self.view,
                "next_seq": self.next_seq, "stable_seq": self.stable_seq,
                "stable_digest": self.stable_digest,
                "stable_cert": self.stable_cert,
                # the divergence halt must survive a crash: a restart on
                # corrupt state with the flag lost would execute and sign
                # client verdicts until the NEXT checkpoint re-detected it
                "diverged": self._diverged,
            }))

    #: a gap between last_executed and higher committed seqs that persists
    #: this long means the missing entries committed while we were down
    #: (consensus traffic is not re-broadcast) — fetch state from peers
    STATE_GAP_TIMEOUT = 2.0

    def _maybe_request_state(self) -> None:
        nxt = self.last_executed + 1
        missing_seq = (
            any(s > nxt for s in self.committed) and nxt not in self.committed
        )
        # a committed next instance whose REQUEST BODY we never saw (the
        # pre-prepare is not re-broadcast) blocks execution just as hard
        missing_body = (
            nxt in self.committed and self.committed[nxt] not in self.requests
        )
        # signature-verified prepare traffic from a view AHEAD of ours:
        # a restart that slept through a view change can otherwise never
        # accumulate gap evidence (every current-view message is dropped
        # by the view guards)
        behind_view = (
            getattr(self, "_ahead_view_evidence", -1) > self.view
        )
        # an adopted stable checkpoint ahead of our execution is standing
        # lag evidence (the commit evidence below it was GC'd): keep the
        # timer armed in case the immediate state_req was lost
        behind_ckpt = self.stable_seq > self.last_executed
        lagging = (
            missing_seq or missing_body or behind_view or behind_ckpt
            or self._diverged
        )
        if not lagging:
            self._gap_since = None
            return
        if self._gap_since is None:
            self._gap_since = self._now
            return
        if self._now - self._gap_since < self.STATE_GAP_TIMEOUT:
            return
        self._gap_since = self._now  # rate-limit re-requests
        self._state_resps.clear()
        self._broadcast({
            "kind": "state_req",
            # diverged: our execution point is untrusted — ask for
            # everything so every peer responds
            "have": -1 if self._diverged else self.last_executed,
        })

    def _on_state_req(self, sender: int, msg: dict) -> None:
        if self.snapshot_fn is None:
            return
        have = msg.get("have", -1)
        if not isinstance(have, int):
            return  # malformed (Byzantine) — must not raise out of pump
        if have >= self.last_executed:
            return  # requester is not behind us
        # a faulty peer looping state_req must not make us serialize the
        # whole uniqueness map per message (O(ledger) amplification) —
        # at most one snapshot per sender per gap-timeout
        last = getattr(self, "_state_served", {}).get(sender, -1e18)
        if self._now - last < self.STATE_GAP_TIMEOUT:
            return
        self._state_served = getattr(self, "_state_served", {})
        self._state_served[sender] = self._now
        dump = self.snapshot_fn()
        self.transport(sender, serialize({
            "kind": "state_resp",
            "last_executed": self.last_executed,
            "view": self.view,
            "digest": hashlib.sha256(dump).digest(),
            "dump": dump,
        }))

    def _on_state_resp(self, sender: int, msg: dict) -> None:
        """Install a peer snapshot once f+1 DISTINCT replicas agree on
        (last_executed, digest) — at least one of them is honest, so the
        agreed state is the real committed prefix (the Byzantine-safe
        equivalent of BFT-SMaRt's state-transfer quorum)."""
        if self.restore_fn is None:
            return
        # type-validate before use: a Byzantine state_resp (and the
        # diverged-recovery flow actively solicits one from EVERY peer)
        # must not raise out of on_message into the message pump
        n = msg.get("last_executed")
        dump = msg.get("dump")
        digest = msg.get("digest")
        view = msg.get("view")
        if (
            not isinstance(n, int) or not isinstance(view, int)
            or not isinstance(dump, bytes)
            or not isinstance(digest, bytes) or len(digest) != 32
        ):
            return
        # a DIVERGED replica accepts certified state even at or below its
        # own (untrusted) execution point — its last_executed was reached
        # on corrupt state and proves nothing
        if n <= self.last_executed and not self._diverged:
            return
        if hashlib.sha256(dump).digest() != digest:
            return  # dump does not match its claimed digest
        self._state_resps[sender] = (n, digest, dump, view)
        # group by (n, digest, view): the VIEW must be part of the f+1
        # agreement — taking it from an arbitrary responder would let one
        # Byzantine member wedge the recovering replica on a bogus view
        groups: Dict[tuple, list] = {}
        for rid, (rn, rd, rdump, rview) in self._state_resps.items():
            groups.setdefault((rn, rd, rview), []).append((rid, rdump))
        for (rn, _rd, rview), members in groups.items():
            acceptable = rn > self.last_executed or (
                self._diverged and rn >= self.stable_seq
            )
            if acceptable and len(members) >= self.f + 1:
                _rid, rdump = members[0]
                self.restore_fn(rdump)
                if self._diverged:
                    # seqs "executed" on the corrupt state must re-execute
                    # on the restored one; anything whose body was GC'd
                    # re-fetches via the normal gap path
                    self.executed = {s for s in self.executed if s <= rn}
                    self._diverged = False
                self.last_executed = rn
                self.next_seq = max(self.next_seq, rn + 1)
                self.view = max(self.view, rview)
                # the installed snapshot is f+1-agreed: treat it as our
                # stable checkpoint and truncate the log below it. Only
                # overwrite the digest/cert when the snapshot is AT or
                # ABOVE the current stable seq — pairing a higher
                # certified seq with a lower snapshot's digest would
                # persist a checkpoint whose digest belongs to a
                # different seq (review finding r5)
                if rn >= self.stable_seq:
                    # keep a genuine 2f+1 cert when the snapshot merely
                    # re-confirms the existing stable point — wiping it
                    # would persist a checkpoint with no evidence
                    if rn > self.stable_seq or _rd != self.stable_digest:
                        self.stable_digest = _rd
                        self.stable_cert = {}
                    self.stable_seq = rn
                self._gc_log(self.stable_seq)
                self._save_meta()
                self._state_resps.clear()
                self._gap_since = None
                self._ahead_view_evidence = -1
                logger.info(
                    "%s installed state snapshot up to seq %d (view %d)",
                    self.id, rn, self.view,
                )
                self._execute_ready()  # buffered later seqs may now chain
                return

    # -- view change ---------------------------------------------------------

    def tick(self, now: float) -> None:
        self._now = now
        self._maybe_request_state()
        if (
            self._pending_since is not None
            and now - self._pending_since >= self.VIEW_TIMEOUT
        ):
            self._pending_since = None
            self._start_view_change(self.view + 1)

    def _prepared_certificates(self) -> List[list]:
        """Self-certifying prepared entries: [seq, digest, request,
        prepared_view, cert] with >= 2f+1 verifiable prepare signatures
        each — a single view-change message proves preparedness (PBFT's
        P set), so a committed request can never be dropped just because
        few members of the new-view quorum saw it prepare.

        cert is [[voter, sig], ...] in ed25519 mode; in bls mode it is
        ["bls", [voters...], agg_sig] — ONE aggregated signature whose
        verification costs the receiver one aggregate check instead of
        2f+1 per-vote verifies."""
        out = []
        for (view, seq, d), voters in self.prepares.items():
            if len(voters) < 2 * self.f + 1 or self.pre_prepares.get(seq) != d:
                continue
            sigs = self.prepare_sigs.get((view, seq, d), {})
            if self.vote_scheme == "bls":
                cert = self._assemble_bls_cert(view, seq, d, sigs)
                if cert is not None and d in self.requests:
                    out.append([seq, d, self.requests[d], view, cert])
                continue
            cert = [[v, sigs[v]] for v in sorted(sigs)][: 2 * self.f + 1]
            if len(cert) >= 2 * self.f + 1 and d in self.requests:
                out.append([seq, d, self.requests[d], view, cert])
        return out

    def _assemble_bls_cert(self, view: int, seq: int, d: bytes, sigs):
        """["bls", voters, agg_sig] over 2f+1 stored votes, aggregate-
        checked locally before emission (trailing votes are stored
        unverified once a quorum certified, so emission re-validates);
        on failure, filters individually and retries once."""
        from ..core.crypto import crypto as _crypto

        stmt = _prepare_statement(view, seq, d)
        pool = dict(sigs)
        for _ in range(2):
            voters = sorted(pool)[: 2 * self.f + 1]
            if len(voters) < 2 * self.f + 1:
                return None
            try:
                agg = _crypto.aggregate([pool[v] for v in voters])
                self.agg_checks += 1
                if _crypto.aggregate_verify(
                    [self.replica_bls_pubs[v] for v in voters], stmt, agg
                ):
                    return ["bls", voters, agg]
            except Exception:
                pass
            pool = {v: s for v, s in pool.items()
                    if self._verify_vote(v, stmt, s)}
        return None

    def _cert_voters(self, prep_view: int, seq: int, d: bytes, cert) -> Set[int]:
        """The distinct replicas whose signatures in a prepared-
        certificate claim verify. Aggregated ["bls", voters, agg_sig]
        certs cost ONE aggregate check; the legacy per-vote list costs
        one verify per entry. Raises TypeError/ValueError on malformed
        shapes (the caller treats that as a bad claim)."""
        if (
            isinstance(cert, (list, tuple)) and len(cert) == 3
            and cert[0] == "bls"
        ):
            if self.vote_scheme != "bls":
                return set()  # an ed25519 committee never signed this
            from ..core.crypto import crypto as _crypto

            voters = {int(v) for v in cert[1]}
            if len(voters) != len(list(cert[1])) or not all(
                v in self.replica_bls_pubs for v in voters
            ):
                return set()
            stmt = _prepare_statement(prep_view, seq, d)
            self.agg_checks += 1
            try:
                ok = _crypto.aggregate_verify(
                    [self.replica_bls_pubs[v] for v in sorted(voters)],
                    stmt, cert[2],
                )
            except Exception:
                ok = False
            return voters if ok else set()
        return {
            voter
            for voter, sig in cert
            if self._verify_prepare_sig(voter, prep_view, seq, d, sig)
        }

    def _start_view_change(self, new_view: int) -> None:
        votes = self.view_change_votes.setdefault(new_view, set())
        votes.add(self.id)
        self._broadcast({
            "kind": "view_change", "new_view": new_view,
            "prepared": self._prepared_certificates(),
        })
        self._maybe_enter_view(new_view)

    def _on_view_change(self, sender: int, msg: dict) -> None:
        new_view = msg["new_view"]
        if new_view <= self.view:
            return
        votes = self.view_change_votes.setdefault(new_view, set())
        votes.add(sender)
        for claim in msg["prepared"]:
            # the whole claim is attacker-controlled: any shape error in the
            # tuple OR the certificate entries must not crash the replica
            try:
                seq, d, request, prep_view, cert = claim
                if request is None or _digest(request) != d:
                    continue
                # verify the prepared certificate: 2f+1 distinct replicas'
                # signatures over the prepare statement (prep_view, seq, d)
                valid_voters = self._cert_voters(prep_view, seq, d, cert)
            except (TypeError, ValueError):
                continue  # malformed claim
            if len(valid_voters) >= 2 * self.f + 1:
                self.requests[d] = request
                self.pre_prepares.setdefault(seq, d)
        # join the view change once f+1 replicas demand it
        if self.id not in votes and len(votes) >= self.f + 1:
            self._start_view_change(new_view)
        self._maybe_enter_view(new_view)

    def _maybe_enter_view(self, new_view: int) -> None:
        votes = self.view_change_votes.get(new_view, set())
        if len(votes) >= 2 * self.f + 1 and new_view > self.view:
            self.view = new_view
            self._pending_since = None
            from ..utils import eventlog

            eventlog.emit(
                "info", "bft", "entered view",
                replica=self.id, view=new_view,
                primary=new_view % self.n,
            )
            if self.is_primary:
                self.next_seq = max(self.pre_prepares, default=self.last_executed) + 1
                # re-propose carried-over uncommitted work, then fresh queue
                self._broadcast({"kind": "new_view", "view": self.view})
                for seq, d in sorted(self.pre_prepares.items()):
                    if seq > self.last_executed and d in self.requests:
                        psig = self._sign_prepare(self.view, seq, d)
                        self._broadcast({
                            "kind": "pre_prepare", "view": self.view,
                            "seq": seq, "digest": d,
                            "request": self.requests[d], "psig": psig,
                        })
                        self._record_prepare(seq, d, self.id, psig)
                # pending client requests that never got a seq
                for d, request in list(self.requests.items()):
                    if d not in self.pre_prepares.values():
                        self.on_request(request)

    def _on_new_view(self, sender: int, msg: dict) -> None:
        if msg["view"] > self.view and sender == msg["view"] % self.n:
            self.view = msg["view"]
            self._pending_since = None


class BFTClient:
    """Client proxy: broadcast the command to every replica, accept the
    result once f+1 DISTINCT replicas return identical replies (reference
    BFTSMaRt.Client response extractor aggregating >= requiredReplies).
    Deduplication by replica identity is what makes the quorum Byzantine-
    safe: one faulty replica repeating a fabricated verdict f+1 times must
    not be able to forge a result."""

    def __init__(self, client_id: str, n_replicas: int,
                 send_to_replica: Callable[[int, dict], None],
                 reply_validator: Optional[Callable] = None):
        """reply_validator(command, result) -> bool: when given, a reply
        only counts toward the f+1 quorum if it validates — e.g. the BFT
        notary requires a cryptographically-valid replica signature on
        conflict-free verdicts, so a Byzantine replica echoing the agreed
        verdict WITHOUT its signature cannot complete the quorum and
        starve the client of the f+1 signatures it needs (the honest
        >= 2f+1 majority still reaches f+1 valid replies)."""
        self.client_id = client_id
        self.n = n_replicas
        self.f = (n_replicas - 1) // 3
        self._send = send_to_replica
        self._reply_validator = reply_validator
        self._pending: Dict[str, Future] = {}
        self._commands: Dict[str, dict] = {}
        # request_id -> {replica_id: result}: one vote per replica
        self._replies: Dict[str, Dict[int, object]] = {}
        self._counter = 0
        self._lock = lockorder.make_lock("BFTClient._lock")

    def submit(self, command: dict) -> Future:
        with self._lock:
            self._counter += 1
            request_id = f"{self.client_id}:{self._counter}"
            fut: Future = Future()
            self._pending[request_id] = fut
            self._replies[request_id] = {}
            self._commands[request_id] = command
        fut.request_id = request_id  # lets callers forget() on timeout
        request = {
            "client_id": self.client_id, "request_id": request_id,
            "command": command,
        }
        for r in range(self.n):
            try:
                self._send(r, request)
            except Exception:
                pass
        return fut

    def forget(self, request_id: str) -> None:
        """Drop a timed-out request so late replies cannot leak memory."""
        with self._lock:
            self._pending.pop(request_id, None)
            self._replies.pop(request_id, None)
            self._commands.pop(request_id, None)

    @staticmethod
    def _verdict_of(result: object) -> object:
        """The agreement-relevant part of a reply: per-replica signatures
        (tx_sig) necessarily DIFFER across honest replicas, so they are
        excluded from the f+1 identical-verdict comparison and aggregated
        separately (reference BFTSMaRt response extractor: compares
        verdicts, collects >= requiredReplies signatures)."""
        if isinstance(result, dict) and "tx_sig" in result:
            return {k: v for k, v in result.items() if k != "tx_sig"}
        return result

    def on_reply(self, replica_id: int, request_id: str, result: object) -> None:
        with self._lock:
            fut = self._pending.get(request_id)
            if fut is None or fut.done():
                return
            replies = self._replies[request_id]
            if not (isinstance(replica_id, int) and 0 <= replica_id < self.n):
                return  # fabricated ids must not mint extra quorum votes
            if replica_id in replies:
                return  # one vote per replica: repeats can't inflate quorum
            if self._reply_validator is not None and not self._reply_validator(
                self._commands.get(request_id), result
            ):
                return  # invalid reply (e.g. missing/forged signature):
                        # never counts toward quorum; honest replies will
            replies[replica_id] = result
            blob = serialize(self._verdict_of(result))
            agreeing = [
                rid for rid, r in replies.items()
                if serialize(self._verdict_of(r)) == blob
            ]
            if len(agreeing) >= self.f + 1:
                self._pending.pop(request_id)
                self._replies.pop(request_id)
                self._commands.pop(request_id, None)
                verdict = self._verdict_of(result)
                sigs = [
                    replies[rid]["tx_sig"] for rid in agreeing
                    if isinstance(replies[rid], dict)
                    and replies[rid].get("tx_sig") is not None
                ]
                if isinstance(verdict, dict) and sigs:
                    verdict = dict(verdict)
                    verdict["tx_sigs"] = sigs
                fut.set_result(verdict)

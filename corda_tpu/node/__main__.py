"""Standalone node process: `python -m corda_tpu.node CONFIG_DIR`.

Reference parity: the production `Node` + `NodeStartup` CLI entry
(`node/src/main/kotlin/net/corda/node/internal/NodeStartup.kt`,
`Node.kt:131-160` — embedded Artemis broker + messaging client + RPC).
The node process hosts:

  * the durable broker (journal under the base directory) behind a TCP
    `BrokerServer` — the in-process-Artemis analogue; verifier workers and
    RPC clients connect to this port from other processes;
  * the node runtime itself (services, state machine, scheduler, optional
    notary) wired per `AbstractNode`;
  * the RPC server, served over broker queues so remote clients reach it
    through the same socket.

On startup the chosen broker port is written to `<base>/broker.port` (the
driver DSL reads it; mirrors the reference driver's port allocation
handshake) and a `ready` line is printed.
"""
from __future__ import annotations

import argparse
import os
import signal
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="corda_tpu.node")
    ap.add_argument("config_dir", help="directory with node.conf")
    ap.add_argument("--jax-platform", dest="jax_platform")
    ap.add_argument(
        "--initial-registration", action="store_true",
        help="register with the doorman named by node.conf's doorman_url, "
             "install the returned certificate chain, then exit "
             "(reference NodeStartup --initial-registration)",
    )
    # multi-process sharding (docs/sharding.md): a supervisor process
    # spawns `python -m corda_tpu.node <dir> --shard-worker K` children
    # that attach to ITS broker over TCP and serve the flow/verify hot
    # path with their own GIL each
    ap.add_argument("--shard-worker", type=int, default=None,
                    help="run as worker K of a sharded node (internal; "
                         "spawned by the supervisor)")
    ap.add_argument("--workers", type=int, default=None,
                    help="total worker count (with --shard-worker)")
    ap.add_argument("--broker-port", type=int, default=None,
                    help="the supervisor's broker port (with --shard-worker)")
    ap.add_argument(
        "--ready-file", default=None,
        help="write a JSON readiness record (broker port, pid, ops "
             "port, legal name) to this path — atomically, only once "
             "RPC and the state machine are serving. A remote driver "
             "(loadtest/remote.py over ssh) reads ONE file for the "
             "whole port/pid handshake instead of polling stdout blind",
    )
    args = ap.parse_args(argv)

    # Production nodes raise the cyclic-GC thresholds: flow/session/codec
    # churn trips CPython's default gen0 threshold (700) thousands of
    # times per second under load, and each full collection stalls every
    # pump thread. The JVM reference tunes its collector for the same
    # reason. CORDA_TPU_GC_THRESHOLD=0 disables the tuning.
    import gc

    _gc_thresh = int(os.environ.get("CORDA_TPU_GC_THRESHOLD", "50000"))
    if _gc_thresh > 0:
        gc.set_threshold(_gc_thresh, 50, 50)

    import logging

    console_level = getattr(
        logging, os.environ.get("CORDA_TPU_LOG", "WARNING").upper(),
        logging.WARNING,
    )
    logging.basicConfig(
        level=console_level,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    # Console verbosity lives on the HANDLERS, not the logger tree: the
    # flight-recorder bridge below lowers the corda_tpu logger to INFO
    # (capture_info) so its ring gets the INFO stream, and these handler
    # levels are what keep the console at CORDA_TPU_LOG regardless.
    for _h in logging.getLogger().handlers:
        _h.setLevel(console_level)

    from ..utils import atomicfile, eventlog, faultpoints

    eventlog.install_stdlib_bridge(capture_info=True)

    # CORDA_TPU_CRASH_AT=point[:nth]: arm a real self-SIGKILL at a
    # registered durability barrier — the OS-process slice of the
    # crash-consistency matrix (tests/test_real_tier1.py rides this)
    faultpoints.install_env_crash_hook()

    if args.shard_worker is not None:
        if args.broker_port is None:
            print("error: --shard-worker requires --broker-port", flush=True)
            return 2
        if args.jax_platform:
            os.environ.setdefault(
                "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
            )
            import jax

            jax.config.update("jax_platforms", args.jax_platform)
        from .shardhost import run_worker

        return run_worker(
            args.config_dir, args.shard_worker, args.workers or 1,
            args.broker_port,
        )

    def announce(msg: str, level: str = "info") -> None:
        """Startup lines are BOTH a launcher protocol (the driver greps
        stdout for them) and operational events: print for the former,
        emit into the flight recorder for the latter — nothing bypasses
        the recorder."""
        print(msg, flush=True)
        eventlog.emit(level, "node", msg)

    from .config import load_config

    overrides = {}
    if args.jax_platform:
        overrides["jax_platform"] = args.jax_platform
    cfg = load_config(args.config_dir, overrides)

    if args.initial_registration:
        import json

        conf_path = os.path.join(args.config_dir, "node.conf")
        raw = {}
        if os.path.exists(conf_path):
            with open(conf_path) as fh:
                raw = json.load(fh)
        doorman_url = raw.get("doorman_url")
        if not doorman_url:
            announce(
                "error: --initial-registration requires doorman_url in "
                "node.conf", level="error",
            )
            return 2
        from .registration import NetworkRegistrationHelper

        helper = NetworkRegistrationHelper(
            doorman_url, cfg.node.my_legal_name, cfg.certificates_dir,
            # pin the network trust root when node.conf provides it
            # (SHA-256 hex of the root cert's DER); TOFU + warning otherwise
            expected_root=raw.get("doorman_root_fingerprint"),
        )
        chain = helper.register()
        announce(
            f"registered {cfg.node.my_legal_name}: chain of {len(chain)} "
            f"certificates installed in {cfg.certificates_dir}"
        )
        return 0

    if cfg.jax_platform:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
        )
        import jax

        jax.config.update("jax_platforms", cfg.jax_platform)

    import importlib

    for mod in cfg.cordapps:  # CorDapp scan (AbstractNode.kt:291-315)
        importlib.import_module(mod)

    from ..messaging import Broker
    from ..messaging.net import BrokerServer, RemoteBroker
    from ..rpc.ops import CordaRPCOps
    from ..rpc.server import RPCServer, RPCUser
    from .network import BrokerMessagingService
    from .networkmap import BridgeManager, NetworkMapClient, NetworkMapService
    from .node import AbstractNode

    # Transport security: dev-mode certificate chain + mutual TLS on the
    # broker socket (AbstractNode.configureWithDevSSLCertificate +
    # ArtemisTcpTransport). Peers must chain to the same trust root; point
    # every node's "certificates_dir" at a shared directory (the driver
    # does) or distribute the root cert.
    server_wrap = client_wrap = None
    if cfg.tls:
        from ..core.crypto import pki

        cert_dir = cfg.certificates_dir
        entries = pki.dev_certificates(cert_dir, cfg.node.my_legal_name)
        server_wrap = pki.server_wrap(pki.server_ssl_context(cert_dir, entries))
        client_wrap = pki.client_wrap(pki.client_ssl_context(cert_dir, entries))

    broker = Broker(journal_dir=cfg.journal_dir)
    server = BrokerServer(
        broker, host=cfg.broker_host, port=cfg.broker_port,
        server_wrap=server_wrap,
    )
    server.start()

    bridges = BridgeManager(
        broker,
        remote_broker_factory=lambda h, p: RemoteBroker(h, p, client_wrap=client_wrap),
    )
    # Sharded host (docs/sharding.md): node_workers > 0 turns this process
    # into the SUPERVISOR — the flow/verify hot path runs in spawned
    # worker processes behind this broker, and this node consumes only the
    # ".sup" leg of its inbound queue (the router owns the bare one).
    # Cluster members stay single-process: the Raft/BFT replica state
    # machines are not multi-process safe.
    n_workers = int(cfg.node.node_workers or 0)
    sharded_host = (
        n_workers > 0
        and cfg.node.raft_cluster is None
        and cfg.node.bft_cluster is None
    )
    # Pin the node identity ACROSS RESTARTS (reference: the node keystore
    # persists the legal-identity key). Without this a relaunched process
    # generated a fresh random keypair — peers' in-flight transactions
    # named the OLD Party, so every notarisation after a notary restart
    # failed "signature is not the notary's" (the remote soak's restart
    # disruption caught it). Sharded hosts additionally need the pin so
    # every worker derives the SAME keypair. Cluster members keep their
    # deterministic per-member entropies from the deploy descriptor.
    if (cfg.node.raft_cluster is None and cfg.node.bft_cluster is None):
        ent_path = os.path.join(cfg.base_directory, "identity.entropy")
        if cfg.node.identity_entropy is None:
            if os.path.exists(ent_path):
                with open(ent_path) as fh:
                    cfg.node.identity_entropy = int(fh.read().strip())
            else:
                cfg.node.identity_entropy = int.from_bytes(
                    os.urandom(24), "big"
                )
        # fsync'd: losing the entropy pin to a power cut would respawn
        # the node under a fresh identity (utils/atomicfile.py)
        atomicfile.write_atomic(ent_path, str(cfg.node.identity_entropy))
    queue_suffix = ".sup" if sharded_host else ""
    node = AbstractNode(
        cfg.node,
        messaging_factory=lambda me: BrokerMessagingService(
            broker, me, bridges, queue_suffix=queue_suffix
        ),
        broker=broker,
    )
    supervisor = None
    if sharded_host:
        from .shardhost import ShardSupervisor, worker_tag_of

        # the supervisor restores only UNTAGGED checkpoints from the
        # shared db — a worker's live flows belong to its respawn
        node.smm.checkpoint_filter = lambda fid: worker_tag_of(fid) is None
        supervisor = ShardSupervisor(
            broker, node, args.config_dir, n_workers, server.port,
            bridges=bridges, jax_platform=cfg.jax_platform,
            base_directory=cfg.base_directory,
        )
    users = [
        RPCUser(u["username"], u["password"], set(u.get("permissions", ["ALL"])))
        for u in cfg.rpc_users
    ] or None
    rpc_secret = None
    if sharded_host:
        from .shardhost import rpc_session_secret

        # worker RPC servers compete on the same request queue: session
        # tokens must verify on every sibling (rpc/server.py)
        rpc_secret = rpc_session_secret(cfg.node.identity_entropy)
    rpc = RPCServer(broker, CordaRPCOps(node.services, node.smm), users=users,
                    session_secret=rpc_secret,
                    shard_role="supervisor" if sharded_host else None)

    netmap_service = None
    if cfg.network_map_service:
        netmap_service = NetworkMapService(
            broker,
            persist_path=os.path.join(cfg.base_directory, "networkmap.db"),
        ).start()

    netmap_client = None
    if cfg.network_map or cfg.network_map_service:
        # Register with the directory (possibly ourselves), fetch peers,
        # subscribe to pushes (AbstractNode.kt:584-621).
        if cfg.network_map and not cfg.network_map_service:
            host, port_s = cfg.network_map.rsplit(":", 1)
            map_broker = RemoteBroker(host, int(port_s), client_wrap=client_wrap)
        else:
            map_broker = broker

        def on_entry(reg):
            # Route first: once the peer is resolvable via the identity
            # service, a flow may immediately send to it.
            bridges.set_route(reg.party.name, reg.broker_address)
            node.register_peer(reg.party, reg.advertised_services)
            if supervisor is not None:
                # workers resolve peers through their control queues (the
                # supervisor's egress pump owns the bridge routing)
                supervisor.broadcast_peer(reg.party, reg.advertised_services)

        extra_identities = []
        if getattr(node, "cluster_party", None) is not None:
            # notary cluster member: also register the cluster's
            # composite identity at this member's address
            extra_identities.append(node.cluster_registration_signer())
        netmap_client = NetworkMapClient(
            map_broker, node.info,
            # advertised_address routes peers through an interposed hop
            # (port forward / the soak's partition proxy); the broker
            # itself still binds broker_host:port
            cfg.advertised_address or f"{cfg.broker_host}:{server.port}",
            cfg.node.advertised_services,
            node._identity_key.private,
            on_entry,
            extra_identities=extra_identities,
            extra_refresh_interval=cfg.cluster_route_refresh,
        )
        netmap_client.register_and_fetch()

    node.start()
    if supervisor is not None:
        supervisor.start()
        if getattr(node, "ops_server", None) is not None:
            # GET /workers: per-worker process state + aggregated healthz
            node.ops_server.workers_view = supervisor.snapshot
        announce(
            f"shard supervisor: {n_workers} workers behind "
            f"{cfg.broker_host}:{server.port}"
        )
    # The port file doubles as the readiness signal (written only once RPC
    # and the state machine are serving), so external tooling can poll it.
    # ATOMIC rename: pollers must never observe a created-but-empty file
    # (a launcher reading the instant the file exists raced exactly that).
    port_path = os.path.join(cfg.base_directory, "broker.port")
    atomicfile.write_atomic(port_path, str(server.port))
    if args.ready_file:
        # the remote-driver handshake: one atomic JSON read yields
        # everything the launcher needs (port for RPC, pid for signals)
        ready = {
            "name": cfg.node.my_legal_name,
            "broker_host": cfg.broker_host,
            "broker_port": server.port,
            "advertised_address": cfg.advertised_address,
            "pid": os.getpid(),
            "ops_port": (
                node.ops_server.port
                if getattr(node, "ops_server", None) is not None else None
            ),
            "workers": n_workers,
        }
        atomicfile.write_json_atomic(args.ready_file, ready)
    announce(
        f"node ready: {cfg.node.my_legal_name} broker={server.host}:{server.port}"
    )

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    # Test/tooling hygiene: when the launcher (pytest, bench, driver DSL)
    # dies without cleanup — SIGKILL, timeout — its nodes must not linger
    # and contend with everything that runs after (a leaked notary from a
    # killed bench run once skewed a whole test session). Opt-in: real
    # deployments keep running when their starter exits.
    exit_on_orphan = os.environ.get("CORDA_TPU_EXIT_ON_ORPHAN") == "1"
    parent = os.getppid()
    try:
        while not stop.wait(0.5):
            if exit_on_orphan and os.getppid() != parent:
                announce("launcher died; shutting down (exit-on-orphan)",
                         level="warning")
                break
    finally:
        if netmap_client is not None:
            netmap_client.stop()
        if netmap_service is not None:
            netmap_service.stop()
        if supervisor is not None:
            supervisor.stop()
        bridges.stop()
        rpc.stop()
        node.stop()
        server.stop()
        broker.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())

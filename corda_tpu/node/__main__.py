"""Standalone node process: `python -m corda_tpu.node CONFIG_DIR`.

Reference parity: the production `Node` + `NodeStartup` CLI entry
(`node/src/main/kotlin/net/corda/node/internal/NodeStartup.kt`,
`Node.kt:131-160` — embedded Artemis broker + messaging client + RPC).
The node process hosts:

  * the durable broker (journal under the base directory) behind a TCP
    `BrokerServer` — the in-process-Artemis analogue; verifier workers and
    RPC clients connect to this port from other processes;
  * the node runtime itself (services, state machine, scheduler, optional
    notary) wired per `AbstractNode`;
  * the RPC server, served over broker queues so remote clients reach it
    through the same socket.

On startup the chosen broker port is written to `<base>/broker.port` (the
driver DSL reads it; mirrors the reference driver's port allocation
handshake) and a `ready` line is printed.
"""
from __future__ import annotations

import argparse
import os
import signal
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="corda_tpu.node")
    ap.add_argument("config_dir", help="directory with node.conf")
    ap.add_argument("--jax-platform", dest="jax_platform")
    args = ap.parse_args(argv)

    from .config import load_config

    overrides = {}
    if args.jax_platform:
        overrides["jax_platform"] = args.jax_platform
    cfg = load_config(args.config_dir, overrides)

    if cfg.jax_platform:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
        )
        import jax

        jax.config.update("jax_platforms", cfg.jax_platform)

    import importlib

    for mod in cfg.cordapps:  # CorDapp scan (AbstractNode.kt:291-315)
        importlib.import_module(mod)

    from ..messaging import Broker
    from ..messaging.net import BrokerServer
    from ..rpc.ops import CordaRPCOps
    from ..rpc.server import RPCServer, RPCUser
    from .network import BrokerMessagingService
    from .node import AbstractNode

    broker = Broker(journal_dir=cfg.journal_dir)
    server = BrokerServer(broker, host=cfg.broker_host, port=cfg.broker_port)
    server.start()

    node = AbstractNode(
        cfg.node,
        messaging_factory=lambda me: BrokerMessagingService(broker, me),
        broker=broker,
    )
    users = [
        RPCUser(u["username"], u["password"], set(u.get("permissions", ["ALL"])))
        for u in cfg.rpc_users
    ] or None
    rpc = RPCServer(broker, CordaRPCOps(node.services, node.smm), users=users)
    node.start()
    # The port file doubles as the readiness signal (written only once RPC
    # and the state machine are serving), so external tooling can poll it.
    with open(os.path.join(cfg.base_directory, "broker.port"), "w") as fh:
        fh.write(str(server.port))
    print(
        f"node ready: {cfg.node.my_legal_name} broker={server.host}:{server.port}",
        flush=True,
    )

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    try:
        while not stop.wait(0.5):
            pass
    finally:
        rpc.stop()
        node.stop()
        server.stop()
        broker.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
